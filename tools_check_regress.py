"""Perf-regression gate CLI: fresh BENCH json vs a committed baseline.

    python tools_check_regress.py BENCH_fused.json --baseline BASELINE.json
    python tools_check_regress.py BENCH_fused.json --baseline BASELINE.json \
        --threshold 0.25 --tag-threshold JTOTAL=0.10 --allow SWINALLOC

Prints the per-tag delta table (worse% > 0 means the fresh run regressed:
a cost tag grew or a rate tag dropped) and exits

    0  no tag past its threshold (or the baseline has no numeric tags),
    1  at least one regression,
    2  usage / IO errors (unreadable files, bad --tag-threshold spec).

``--strict`` also fails tags present in the baseline but missing from the
fresh result — a silently vanished measurement is itself a signal.  The
comparison logic lives in tpu_radix_join.observability.regress; bench.py
runs the same check in-process via ``--check-regress BASELINE.json``.

Direction is per-tag and automatic: serve-mode SLO tags are pinned
lower-is-better (``slo_p99_ms`` and friends are latencies;
``admission_rejection_rate`` / ``deadline_miss_rate`` / ``degraded_rate``
regress when they GROW, even though "rate" normally marks a throughput),
so a ``--serve-bench`` BENCH json gates correctly with no extra flags.

The ``--exchange-bench`` footprint tags are pinned the same way:
``wirebytes`` (total bytes the all_to_all actually shipped under the
active codec), ``peak_exchange_bytes`` (largest live allocation of one
staged collective), and ``bytes_per_tuple`` are lower-is-better — a codec
or staging change that inflates the wire regresses even when the join
stays correct and the wall time holds.  The BENCH headline ``value`` is
the wire *reduction* ratio (raw 8 B per tuple over packed bytes per
tuple), which keeps the headline higher-is-better like every other bench.

The observability counters are pinned lower-is-better too: ``PLANDRIFT``
(planner/audit.py — |actual - predicted| join time as a percent of the
cost model's prediction) regresses when it GROWS, catching stale device
profiles in CI before they surface as mispredicted plans; ``PMBUNDLE``
(forensics bundles written) and ``WDOGTRIP`` (hang-watchdog trips) count
deaths per round, so a bench round that starts emitting bundles fails
the gate even if the surviving joins kept their speed.

The calibration-loop tags are pinned lower-is-better as well:
``NCOMPILE`` / ``COMPILEMS`` (backend compiles seen via jax.monitoring —
observability/compilemon.py) regress when a round starts recompiling
warm shapes; ``fit_residual`` and ``stale_constants``
(tools_profile_fit.py) regress when the fitted profile's spread grows or
more constants drift away from the clock.

A ``--partition-bench`` BENCH json gates the destination-grouping A/B
(ops/pallas/partition.py fused kernel vs the sort-based scatter):

    {"metric": "partition_fused_speedup", "value": 1.71, "size": 16777216,
     "num_blocks": 8, "partition_ms": 4757.0, "partition_kernel_ms": 2873.0,
     "partition_sort_ms": 8121.0, "partition_unit_ms": 0.0856}

The headline ``value`` is the wall speedup (sort arm over fused arm,
higher is better); ``partition_ms`` / ``partition_kernel_ms`` /
``partition_sort_ms`` are walls and ``partition_unit_ms`` is the reduced
ms/Mtuple/pass constant the profile fitter recovers — all pinned
lower-is-better, alongside the ``PARTFALLBACK`` counter (silent degrades
to the XLA sort path; on a TPU backend more of them means the fused
kernel stopped being auto-selected).

A ``--sort-bench`` BENCH json gates the flat-sort A/B
(ops/pallas/radix_sort.py LSD radix sort vs the lax.sort emitter):

    {"metric": "radix_sort_speedup", "value": 1.42, "size": 262144,
     "sort_ms": 22.5, "sort_xla_ms": 32.0, "sort_kernel_ms": 14.1,
     "sort_pass_unit_ms": 0.1134, "sort_passes": 4,
     "sort_bounded_ms": 11.3, "sort_bounded_passes": 2, "sortfallback": 0}

The headline ``value`` is the wall speedup (xla arm over radix arm,
higher is better; expected < 1 when the radix arm runs interpreted on
host CPU).  ``sort_ms`` / ``sort_xla_ms`` / ``sort_kernel_ms`` /
``sort_bounded_ms`` are walls, ``sort_pass_unit_ms`` is the reduced
ms/Mtuple/digit-pass constant the profile fitter recovers, and
``sort_passes`` / ``sort_bounded_passes`` count LSD digit passes (more
passes means the key-bound pass skip stopped firing) — all pinned
lower-is-better, alongside the ``SORTFALLBACK`` counter (the sort
auto-select degrading to lax.sort; it ticks at most once per process by
design, so on a TPU backend any nonzero value means the Pallas sort
engine stopped being selected).

A ``--recovery-bench`` BENCH json gates the elastic-recovery A/B
(robustness/membership.py + recovery.py — kill-1-of-8 partition-level
recovery vs the cold full restart it replaces):

    {"metric": "elastic_recovery_speedup", "value": 2.34, "size": 262144,
     "num_partitions": 16, "recover_ms": 334.9, "cold_restart_ms": 784.3,
     "recovern": 2, "resumed_partitions": 14, "ranklost": 1, "mepoch": 1}

The headline ``value`` is the wall ratio (cold restart over recovery,
higher is better).  ``recover_ms``/``cold_restart_ms`` are walls;
``recovern`` (partitions recomputed — the bench refuses to bless a run
where it reaches the partition count, i.e. a veiled restart),
``ranklost``, and ``mepoch`` (membership epochs burned per round) are
pinned lower-is-better: a fleet that starts losing more ranks or
fencing more epochs per round regresses even when each individual
recovery still lands oracle-exact.

The ``--recovery-bench --straggle f`` arm gates the straggler-hedging
tail A/B (robustness/straggler.py — speculative recompute of a slow
rank's unfinished partitions through the manifest fence):

    {"metric": "straggler_hedge_tail_speedup", "value": 2.62,
     "size": 131072, "num_partitions": 32, "straggle_factor": 4.0,
     "hedged_ms": 526.3, "unhedged_ms": 1379.9, "hedgewin": 4,
     "specwaste": 0, "recovern": 4, "manifest_total": 131072}

The headline ``value`` is the tail ratio (unhedged wall over hedged
wall, higher is better).  ``hedged_ms``/``unhedged_ms`` are walls and
``specwaste`` counts speculative recomputes the original won anyway —
all lower-is-better — while ``hedgewin`` (fence wins per hedge round)
is pinned higher-is-better: fewer wins at the same hedge count means
the detector started hedging partitions that were about to finish.

A ``--fleet-bench`` BENCH json gates the crash-only fleet failover A/B
(service/fleet.py + journal.py — SIGKILL one of four supervised serve
workers mid-query vs the cold supervisor restart it replaces):

    {"metric": "fleet_failover_speedup", "value": 7.72, "workers": 4,
     "queries": 5, "failover_ms": 518.1, "cold_restart_ms": 3998.7,
     "failover": 1, "replayn": 1, "jdepth": 1, "wincarn": 4,
     "worker_restarts": 0, "double_exec": 0}

The headline ``value`` is the wall ratio (cold restart over failover,
higher is better).  ``failover_ms``/``cold_restart_ms`` are walls;
``failover`` (mid-query deaths failed over), ``replayn`` (journal
intents replayed), ``jdepth`` (peak unacknowledged journal depth),
``wincarn`` (worker incarnations spawned), and ``worker_restarts`` are
pinned lower-is-better: a fleet that starts burning more incarnations
or replays per round regresses even when each query still lands
oracle-exact.  ``double_exec`` is pinned to ZERO — it counts
fingerprints with more than one journaled outcome, the exactly-once
invariant, and because its baseline is 0 any growth is an infinite
relative delta: a single double execution fails this gate at every
threshold, no ``--allow`` precedent.

A ``--serve-throughput-bench`` BENCH json gates the serving fast-path
A/B (service/resultcache.py result cache, service/microbatch.py +
ops/merge_delta.py fused micro-batches, service/resident.py delta
merges):

    {"metric": "serve_fastpath_speedup", "value": 5.92,
     "unit": "serial_over_fused_wall_q4",
     "cache_cold_latency_ms": 441.1, "cache_hit_latency_ms": 0.14,
     "cache_speedup": 3088.0, "cache_hit_rate": 0.33,
     "batch_speedup_2": 4.1, "batch_speedup_4": 5.9,
     "batch_speedup_8": 6.6, "batch_fuse_ratio": 4.67,
     "delta_speedup_16": 6.9, "delta_speedup_64": 8.3,
     "delta_speedup_256": 8.2, "delta_speedup": 8.3,
     "rchit": 1, "rcmiss": 2, "batchn": 6, "batchq": 28,
     "deltamerge": 9, "resbytes": 1179648, "statusz_polls": 5,
     "double_exec": 0}

The headline ``value`` is the Q=4 fused-over-serial wall speedup
(higher is better), and every ``*_speedup`` plus ``cache_hit_rate`` and
``batch_fuse_ratio`` gate higher-is-better — a fast path that stops
firing shows up as a collapsed ratio before it shows up as latency.
``rchit`` / ``deltamerge`` are pinned higher-is-better (fewer
whole-query amortization wins at the same traffic means a tier went
dark) while ``rcmiss`` is a cost; ``batchn`` / ``batchq`` /
``resbytes`` / ``statusz_polls`` are declared neutral (traffic- and
budget-shaped descriptors whose gated observables are the ratios).
``double_exec`` rides the --fleet-bench zero pin: the bench's
mid-batch ``fleet.worker_kill`` leg must keep the journal exactly-once
even while a fused group dies on a worker's pipe.

The ``--recovery-bench --grow`` arm gates mid-run admission vs fixed
survivors (rank admission re-expanding the assignment map):

    {"metric": "elastic_grow_speedup", "value": 1.18, "size": 524288,
     "num_partitions": 32, "grown_ms": 20.5, "fixed_ms": 24.2,
     "recovern": 18, "resumed_partitions": 14, "rankjoin": 1,
     "survivors_fixed": 8, "survivors_grown": 9}

``grown_ms``/``fixed_ms`` are the critical-path recompute walls (the
slowest single survivor's share — what decides when a data-parallel
epoch completes) and gate lower-is-better; ``rankjoin`` is declared
neutral (a grow arm admits by design — losses regress, joins don't).

The static-analysis counters gate the same way: ``lint_findings`` and
``stale_baseline`` (``tools_lint.py --json`` — live graftlint findings
and baseline suppressions whose finding was already fixed) are pinned
lower-is-better, so a convention regression (a stray direct sort, an
unpinned counter tag, an implicit hot-path host sync) fails this gate
exactly like a perf regression.  The lint rules themselves, their
baseline discipline, and the ``--transfer-guard`` runtime twin are
documented in tools_lint.py.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_radix_join.observability.regress import (DEFAULT_THRESHOLD,
                                                  check_files,
                                                  parse_tag_thresholds)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_check_regress.py",
        description="Compare a fresh result JSON against a perf baseline.")
    p.add_argument("fresh", help="fresh result (BENCH_*.json or any flat "
                                 "JSON of numeric tags)")
    p.add_argument("--baseline", required=True,
                   help="baseline JSON (e.g. BASELINE.json)")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="default relative worsening allowed per tag "
                        "(default %(default)s)")
    p.add_argument("--tag-threshold", action="append", default=[],
                   metavar="TAG=REL",
                   help="per-tag override, repeatable (e.g. JTOTAL=0.10)")
    p.add_argument("--allow", action="append", default=[], metavar="TAG",
                   help="tag allowed to regress this round, repeatable")
    p.add_argument("--strict", action="store_true",
                   help="also fail baseline tags missing from the fresh "
                        "result")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        tag_thr = parse_tag_thresholds(args.tag_threshold)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        code, report = check_files(
            args.fresh, args.baseline, threshold=args.threshold,
            tag_thresholds=tag_thr, allow=args.allow, strict=args.strict)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(report)
    return code


if __name__ == "__main__":
    sys.exit(main())
