"""tpu_radix_join — a TPU-native distributed radix hash join framework.

A from-scratch JAX/XLA rebuild of the capabilities of
lushl9301/Distributed-Radix-Hash-Join-on-GPUs (ETH hpcjoin lineage, C++/MPI/CUDA):
the full histogram -> window allocation -> network partitioning (all-to-all) ->
local partitioning -> build-probe pipeline runs as a single pjit/shard_map SPMD
program over a TPU mesh.  See SURVEY.md at the repo root for the component-level
mapping to the reference (file:line citations throughout the code).
"""

from tpu_radix_join.utils import compat as _compat

_compat.install()

from tpu_radix_join.core.config import JoinConfig
from tpu_radix_join.data.relation import Relation
from tpu_radix_join.operators.hash_join import HashJoin

__version__ = "0.1.0"

__all__ = ["JoinConfig", "Relation", "HashJoin", "__version__"]
