"""Durable query journal: the fleet supervisor's exactly-once WAL.

A supervisor that restarts dead workers (service/fleet.py) needs one
piece of truth that outlives any process: which queries were *accepted*
and which of them already have an *outcome*.  The journal is that truth
— an append-only intent/outcome JSONL file with exactly the atomic-
append + torn-line-tolerant-reader discipline of the cross-run ledger
(observability/ledger.py): every append is a single ``write`` + flush
(so a SIGKILL tears at most one line), torn lines are skipped on read,
and rows stamped with a newer schema than this build understands are
skipped rather than misread.

Record shapes (schema v1)::

    {"schema_version": 1, "kind": "intent",  "fp": ..., "query_id": ...,
     "t_epoch_s": ..., "worker": slot, "incarnation": ..., "attempt": n,
     "request": {...}}
    {"schema_version": 1, "kind": "outcome", "fp": ..., "query_id": ...,
     "t_epoch_s": ..., "worker": slot, "outcome": {...}}

The **fingerprint** (``fp``) is a stable hash of the canonicalized
request JSON: two submissions of the same request line dedup to one
fingerprint, so replay-after-crash can tell "this query already has a
journaled outcome — re-serve it, never re-execute it" from "this intent
is unacknowledged — replay it on a healthy worker".  That pair of rules
is the whole exactly-once story; :meth:`QueryJournal.audit` checks it
(``double_exec`` counts fingerprints with more than one outcome row —
the invariant pinned to zero by the regress gate).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Dict, List, Optional

JOURNAL_SCHEMA_VERSION = 1
JOURNAL_BASENAME = "query_journal.jsonl"

_KINDS = ("intent", "outcome")


#: Request fields that do not change what the query COMPUTES: a deadline
#: changes when we give up, not the answer; display names are client-side
#: labels.  Excluded from the fingerprint so equal work dedups even when
#: clients vary the non-semantic envelope.
NONSEMANTIC_FIELDS = ("deadline_s", "tenant_name", "display_name")


def _canonical(obj, top: bool = False):
    """Canonical form of one request value: dict keys sorted with the
    non-semantic envelope dropped at the top level, integral floats
    folded to int (``2.0`` and ``2`` name the same workload — JSON
    clients disagree on number types, the query does not), tuples and
    lists unified."""
    if isinstance(obj, dict):
        return {k: _canonical(obj[k]) for k in sorted(obj)
                if not (top and k in NONSEMANTIC_FIELDS)}
    if isinstance(obj, bool):          # bool is an int subclass: keep it
        return obj
    if isinstance(obj, float) and obj.is_integer():
        return int(obj)
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    return obj


def request_fingerprint(request: dict) -> str:
    """Stable identity of one query request: sha256 over the sorted-key
    JSON of the *canonicalized* request fields.  Everything that changes
    what the query computes is in the request dict, so equal fingerprints
    mean "the same query" across supervisor incarnations.

    Canonicalization (key order, integral-float folding, non-semantic
    field exclusion — :func:`_canonical`) means two requests for equal
    work hash equal even when the JSON lines differ textually.

    Journal compatibility: hardening the canonicalization CHANGED the
    fingerprint strings for requests carrying floats-with-integral-values
    or a ``deadline_s``.  A pre-hardening journal replayed under this
    build simply sees its old fingerprints as distinct cold entries —
    unacknowledged intents still replay (the fp is read from the intent
    row, never recomputed against the new scheme mid-replay), and no old
    fp can collide with a new one, so exactly-once is preserved; only
    cross-build outcome dedup of textually-divergent duplicates is lost.
    """
    blob = json.dumps(_canonical(request, top=True), sort_keys=True,
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass
class JournalAudit:
    """The exactly-once ledger sheet: accepted vs answered vs doubled."""

    intents: int                 # distinct accepted fingerprints
    outcomes: int                # distinct answered fingerprints
    unacked: int                 # accepted, no outcome yet
    double_exec: int             # fingerprints with >1 outcome row (MUST be 0)
    replays: int                 # intent rows beyond the first per fingerprint

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class QueryJournal:
    """Append-only intent/outcome WAL at ``<dir>/query_journal.jsonl``
    (or an explicit ``*.jsonl`` path).

    Single-writer by design (the supervisor's dispatch loop); the reader
    side is crash-tolerant so a *previous* incarnation's torn final line
    never poisons recovery.
    """

    def __init__(self, dir_or_path: str):
        self.path = (dir_or_path if dir_or_path.endswith(".jsonl")
                     else os.path.join(dir_or_path, JOURNAL_BASENAME))

    # ------------------------------------------------------------- writing
    def _append(self, row: dict) -> dict:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row, default=str) + "\n")
            f.flush()
        return row

    def append_intent(self, request: dict, fp: Optional[str] = None,
                      worker: Optional[int] = None,
                      incarnation: Optional[str] = None,
                      attempt: int = 1) -> dict:
        """Journal "this query is accepted and about to run on
        ``worker``" — written BEFORE the request reaches any worker, so
        a supervisor death between dispatch and outcome leaves a
        replayable record, never a vanished query."""
        return self._append({
            "schema_version": JOURNAL_SCHEMA_VERSION, "kind": "intent",
            "fp": fp or request_fingerprint(request),
            "query_id": request.get("query_id"),
            "t_epoch_s": round(time.time(), 3),
            "worker": worker, "incarnation": incarnation,
            "attempt": int(attempt), "request": request})

    def append_outcome(self, fp: str, outcome: dict,
                       worker: Optional[int] = None) -> dict:
        """Journal the terminal verdict — written as soon as the worker's
        response is read, BEFORE the client sees it, so a lost response
        is re-servable from the journal without re-execution."""
        return self._append({
            "schema_version": JOURNAL_SCHEMA_VERSION, "kind": "outcome",
            "fp": fp, "query_id": outcome.get("query_id"),
            "t_epoch_s": round(time.time(), 3),
            "worker": worker, "outcome": outcome})

    # ------------------------------------------------------------- reading
    def rows(self, kind: Optional[str] = None) -> List[dict]:
        """Tolerant read: missing file -> [], torn lines skipped, rows
        from a newer schema skipped (never misread) — the ledger reader
        discipline verbatim."""
        out: List[dict] = []
        try:
            f = open(self.path)
        except OSError:
            return out
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue               # torn final line of a dead writer
                if not isinstance(row, dict):
                    continue
                if (int(row.get("schema_version", 1))
                        > JOURNAL_SCHEMA_VERSION):
                    continue
                if row.get("kind") not in _KINDS:
                    continue
                if kind is not None and row.get("kind") != kind:
                    continue
                out.append(row)
        return out

    def intents(self) -> Dict[str, dict]:
        """Latest intent row per fingerprint, in journal order."""
        out: Dict[str, dict] = {}
        for row in self.rows("intent"):
            if row.get("fp"):
                out[row["fp"]] = row
        return out

    def outcomes(self) -> Dict[str, dict]:
        """First outcome row per fingerprint (the one the client is owed
        — later duplicates are the double-execution bug the audit
        counts, never the answer)."""
        out: Dict[str, dict] = {}
        for row in self.rows("outcome"):
            fp = row.get("fp")
            if fp and fp not in out:
                out[fp] = row
        return out

    def outcome_for(self, fp: str) -> Optional[dict]:
        """The journaled outcome dict for ``fp``, or None — the re-serve
        dedup lookup (an outcome here means the query MUST NOT run
        again)."""
        row = self.outcomes().get(fp)
        return row.get("outcome") if row else None

    def unacknowledged(self) -> List[dict]:
        """Intent rows (latest per fingerprint) with no journaled outcome
        — the replay set a restarted supervisor owes its clients, in
        original acceptance order."""
        done = set(self.outcomes())
        pend = [row for fp, row in self.intents().items() if fp not in done]
        pend.sort(key=lambda r: (r.get("t_epoch_s") or 0))
        return pend

    def depth(self) -> int:
        """Unacknowledged intents right now (the JDEPTH gauge)."""
        return len(self.unacknowledged())

    # -------------------------------------------------------------- audit
    def audit(self) -> JournalAudit:
        intent_fps: Dict[str, int] = {}
        outcome_fps: Dict[str, int] = {}
        for row in self.rows():
            fp = row.get("fp")
            if not fp:
                continue
            table = (intent_fps if row["kind"] == "intent" else outcome_fps)
            table[fp] = table.get(fp, 0) + 1
        return JournalAudit(
            intents=len(intent_fps),
            outcomes=len(outcome_fps),
            unacked=len(set(intent_fps) - set(outcome_fps)),
            double_exec=sum(1 for n in outcome_fps.values() if n > 1),
            replays=sum(n - 1 for n in intent_fps.values() if n > 1))
