"""SLO accounting: per-tenant latency percentiles and outcome rates.

The millions-of-users contract is stated in percentiles, not means: a
p99 that doubles while the mean holds is exactly the regression a
resident service must catch.  The recorder keeps every query's latency
(bounded history — the serve loop is file-fed today; a windowed reservoir
is the obvious extension when streams get long) and distills:

  * ``slo_p50_ms`` / ``slo_p95_ms`` / ``slo_p99_ms`` — overall, plus the
    same triplet per tenant (one tenant's deadline-heavy workload must
    not hide inside the global tail);
  * ``admission_rejection_rate`` / ``deadline_miss_rate`` /
    ``degraded_rate`` — outcome rates over everything submitted.

``snapshot()`` feeds the ``--metrics-interval`` heartbeat (one flat dict
per tick) and the final serve report; the same tags flow into the
``--serve-bench`` BENCH JSON where tools_check_regress.py gates them
(direction-aware: latency and rejection tags regress when they GROW —
observability/regress.py lower-is-better vocabulary).

Percentile discipline: nearest-rank on the sorted sample (no
interpolation) — small-N percentiles stay actual observed latencies, so
a 20-query bench's p99 is its worst query, not an extrapolation.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

PERCENTILES = (50, 95, 99)


def nearest_rank(sorted_vals: List[float], pct: float) -> float:
    """Nearest-rank percentile of an already-sorted non-empty list."""
    if not sorted_vals:
        raise ValueError("no samples")
    rank = max(1, -(-len(sorted_vals) * pct // 100))   # ceil
    return sorted_vals[int(rank) - 1]


class SLORecorder:
    """Accumulates per-query outcomes; distills SLO tags on demand."""

    def __init__(self):
        self._lat_ms: Dict[str, List[float]] = collections.defaultdict(list)
        self.completed = 0          # queries that ran to a terminal outcome
        self.ok = 0
        self.failed = 0             # classified failures (ran, didn't pass)
        self.rejected = 0           # never ran: admission refusals
        self.deadline_missed = 0
        self.degraded = 0           # served by the fallback engine

    # ------------------------------------------------------------- recording
    def record(self, tenant: str, latency_ms: float, *, ok: bool,
               failure_class: Optional[str] = None,
               degraded: bool = False) -> None:
        """One executed query (admitted, ran, produced an outcome)."""
        self._lat_ms[tenant].append(float(latency_ms))
        self.completed += 1
        if ok:
            self.ok += 1
        else:
            self.failed += 1
        if failure_class == "deadline_exceeded":
            self.deadline_missed += 1
        if degraded:
            self.degraded += 1

    def record_rejection(self) -> None:
        """One admission refusal (the query never executed, so it has no
        latency sample — rejections shape the rate tags only)."""
        self.rejected += 1

    # ------------------------------------------------------------ distilling
    def percentiles(self, tenant: Optional[str] = None) -> Dict[str, float]:
        """{"p50_ms": ..., "p95_ms": ..., "p99_ms": ...} for one tenant or
        (None) the whole service; empty dict when no samples yet."""
        if tenant is None:
            vals = [v for vs in self._lat_ms.values() for v in vs]
        else:
            vals = list(self._lat_ms.get(tenant, ()))
        if not vals:
            return {}
        vals.sort()
        return {f"p{p}_ms": round(nearest_rank(vals, p), 3)
                for p in PERCENTILES}

    def tenants(self) -> List[str]:
        return sorted(self._lat_ms)

    def snapshot(self) -> dict:
        """Flat SLO tag dict: heartbeat tick, final report, and BENCH JSON
        all speak this vocabulary."""
        submitted = self.completed + self.rejected
        out = {
            "queries_submitted": submitted,
            "queries_ok": self.ok,
            "queries_failed": self.failed,
            "queries_rejected": self.rejected,
            "admission_rejection_rate": round(
                self.rejected / submitted, 4) if submitted else 0.0,
            "deadline_miss_rate": round(
                self.deadline_missed / submitted, 4) if submitted else 0.0,
            "degraded_rate": round(
                self.degraded / submitted, 4) if submitted else 0.0,
        }
        overall = self.percentiles()
        out.update({f"slo_{k}": v for k, v in overall.items()})
        for tenant in self.tenants():
            for k, v in self.percentiles(tenant).items():
                out[f"slo_{tenant}_{k}"] = v
        return out
