"""Circuit breaker over the chip backend.

Bench rounds 3-5 showed what a downed TPU tunnel does to a naive caller:
every dispatch blocks on a native futex until a hard timeout, so a
resident session that kept sending queries at a dead backend would turn
one infrastructure outage into N slow failures.  The breaker converts
that into fast, classified degradation:

  * **closed** — queries run on the primary engine.  Consecutive failures
    of a *tripping* class (``backend_unavailable``, ``retries_exhausted``,
    ``device_unavailable`` by default) count toward ``failure_threshold``;
    any success resets the streak (a mix of failing and passing queries is
    a query problem, not a backend problem).
  * **open** — the primary is presumed dead; queries route to the degraded
    CPU fallback engine (robustness/degrade.py machinery) immediately, no
    primary dispatch, no timeout paid.  After ``cooldown_s`` the breaker
    half-opens.
  * **half-open** — exactly one query is dispatched to the primary as a
    health probe (``BRKPROBE``).  Success closes the breaker; failure
    re-opens it and restarts the cooldown.

Failures of non-tripping classes (capacity overflow, data corruption,
deadline expiry, key contracts) never move the breaker: they indict the
query, not the backend — per-query failure isolation means a poisoned
query cannot push its neighbors onto the slow path.

State transitions are recorded as counters (``BRKTRIP``/``BRKPROBE``) and
timeline instant events (``breaker_open`` / ``breaker_half_open`` /
``breaker_close``), so a merged trace shows exactly when the session
degraded and recovered.  The clock is injectable for fake-time tests.
"""

from __future__ import annotations

import time
from typing import Callable, FrozenSet, Optional

from tpu_radix_join.performance.measurements import BRKPROBE, BRKTRIP
from tpu_radix_join.robustness.retry import (BACKEND_UNAVAILABLE,
                                             DEVICE_UNAVAILABLE,
                                             RETRIES_EXHAUSTED)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: failure classes that indict the backend rather than the query
DEFAULT_TRIPPING: FrozenSet[str] = frozenset({
    BACKEND_UNAVAILABLE, RETRIES_EXHAUSTED, DEVICE_UNAVAILABLE})


class CircuitBreaker:
    """Consecutive-failure breaker with half-open health probes."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0,
                 tripping: FrozenSet[str] = DEFAULT_TRIPPING,
                 clock: Callable[[], float] = time.monotonic,
                 measurements=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.tripping = frozenset(tripping)
        self._clock = clock
        self.measurements = measurements
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.trips = 0          # lifetime closed/half-open -> open count
        self.probes = 0         # lifetime half-open probes dispatched

    # ---------------------------------------------------------------- routing
    def allow_primary(self) -> bool:
        """Route decision for the next query: True = dispatch on the
        primary engine; False = serve degraded.  Promotes OPEN ->
        HALF_OPEN once the cooldown has elapsed — the query that sees the
        promotion IS the health probe (record_success/record_failure
        resolves it)."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if (self._clock() - self.opened_at) < self.cooldown_s:
                return False
            self._transition(HALF_OPEN)
        # HALF_OPEN admits exactly one primary probe; concurrent callers
        # (none today — the session is single-threaded) would serialize on
        # the session loop anyway
        self.probes += 1
        m = self.measurements
        if m is not None:
            m.incr(BRKPROBE)
        return True

    # ------------------------------------------------------------- resolution
    def record_success(self) -> None:
        """A primary-engine query completed ok (or failed for a reason
        that does not indict the backend)."""
        self.consecutive_failures = 0
        if self.state == HALF_OPEN:
            self._transition(CLOSED)

    def record_failure(self, failure_class: Optional[str]) -> bool:
        """Account a primary-engine failure; returns True when this
        failure tripped (or re-tripped) the breaker.  Non-tripping classes
        reset the streak like successes do — see module docstring."""
        if failure_class not in self.tripping:
            self.record_success()
            return False
        if self.state == HALF_OPEN:
            self._trip(failure_class)        # probe failed: straight back
            return True
        self.consecutive_failures += 1
        if (self.state == CLOSED
                and self.consecutive_failures >= self.failure_threshold):
            self._trip(failure_class)
            return True
        return False

    # -------------------------------------------------------------- internals
    def _trip(self, failure_class: str) -> None:
        self.trips += 1
        m = self.measurements
        if m is not None:
            m.incr(BRKTRIP)
        self._transition(OPEN, failure_class=failure_class)

    def _transition(self, state: str, **detail) -> None:
        prev, self.state = self.state, state
        if state == OPEN:
            self.opened_at = self._clock()
            self.consecutive_failures = 0
        m = self.measurements
        if m is not None:
            m.event(f"breaker_{state}", prev=prev,
                    trips=self.trips, **detail)

    def snapshot(self) -> dict:
        return {"state": self.state, "trips": self.trips,
                "probes": self.probes,
                "consecutive_failures": self.consecutive_failures}
