"""Device-resident sorted-union state for incremental delta-merge joins.

The session's ``_place`` cache (service/session.py) keeps *generated
relations* warm per engine; this manager keeps the **sorted inner key
lane** itself device-resident per session relation, under an explicit
HBM byte budget, so a follow-up query that only APPENDS Δ new tuples
sorts the Δ and splices it into the resident union
(ops/merge_delta.py :func:`~tpu_radix_join.ops.merge_delta.merge_sorted`)
instead of re-sorting all N+Δ keys — O(N+Δ) streaming work against
O((N+Δ)·U(N+Δ)) sort stages, the win the planner prices as
``serve_delta`` (planner/cost_model.py).

Budget discipline: ``budget_bytes`` is a hard ceiling on the SUM of
resident lane bytes (``nbytes`` of the stored arrays).  Admission of a
lane that would exceed it evicts least-recently-used lanes first; a lane
larger than the whole budget is simply not admitted (the query still
runs, on the full re-sort path).  ``RESBYTES`` holds the high-water
mark of resident bytes (max-hold gauge, JDEPTH discipline) and the live
total is exported through :meth:`stats` into ``/statusz``.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Hashable, Optional, Tuple

from tpu_radix_join.performance.measurements import RESBYTES


@dataclasses.dataclass
class _Resident:
    lane: object            # device array, sorted ascending (jnp.ndarray)
    nbytes: int
    epoch: Optional[int]    # membership epoch the lane was built under
    merges: int = 0         # delta merges absorbed since admission


class ResidentStateManager:
    """LRU-by-bytes pool of device-resident sorted key lanes.

    ``budget_bytes == 0`` disables residency: every get misses, every
    put drops — the session then always takes the full-sort path.
    Keys are caller-chosen hashables (the session uses the relation-spec
    tuple that also keys ``_place``); an epoch mismatch on get drops the
    lane, because a membership change re-partitions what each host
    generates.
    """

    def __init__(self, budget_bytes: int, measurements=None):
        if budget_bytes < 0:
            raise ValueError("budget_bytes must be >= 0")
        self.budget_bytes = budget_bytes
        self.measurements = measurements
        self._lanes: "OrderedDict[Hashable, _Resident]" = OrderedDict()
        self.resident_bytes = 0
        self.admitted = 0
        self.evicted = 0
        self.rejected = 0       # lanes larger than the whole budget
        self.merges = 0

    # ------------------------------------------------------------- lookup
    def get(self, key: Hashable,
            epoch: Optional[int] = None) -> Optional[object]:
        """The resident sorted lane for ``key``, or None.  A lane built
        under a different epoch is dropped, not served."""
        entry = self._lanes.get(key)
        if entry is None:
            return None
        if entry.epoch != epoch:
            self._drop(key)
            return None
        self._lanes.move_to_end(key)
        return entry.lane

    def put(self, key: Hashable, lane, epoch: Optional[int] = None) -> bool:
        """Admit (or replace) the sorted lane for ``key``; returns False
        when the lane alone exceeds the budget (nothing is evicted for a
        lane that cannot fit anyway)."""
        if self.budget_bytes == 0:
            return False
        nbytes = int(lane.nbytes)
        if nbytes > self.budget_bytes:
            self.rejected += 1
            return False
        if key in self._lanes:
            self._drop(key)
        while self.resident_bytes + nbytes > self.budget_bytes:
            victim = next(iter(self._lanes))
            self._drop(victim)
            self.evicted += 1
        self._lanes[key] = _Resident(lane=lane, nbytes=nbytes, epoch=epoch)
        self.resident_bytes += nbytes
        self.admitted += 1
        m = self.measurements
        if m is not None:
            # max-hold gauge (JDEPTH discipline): RESBYTES keeps the
            # high-water mark of resident bytes across the run
            cur = int(m.counters.get(RESBYTES, 0))
            if self.resident_bytes > cur:
                m.incr(RESBYTES, self.resident_bytes - cur)
        return True

    def note_merge(self, key: Hashable) -> None:
        """Record that ``key``'s lane absorbed one delta merge (the lane
        object itself was already replaced via :meth:`put`)."""
        self.merges += 1
        entry = self._lanes.get(key)
        if entry is not None:
            entry.merges += 1

    # ---------------------------------------------------------- lifecycle
    def _drop(self, key: Hashable) -> None:
        entry = self._lanes.pop(key, None)
        if entry is not None:
            self.resident_bytes -= entry.nbytes

    def invalidate(self, key: Optional[Hashable] = None) -> int:
        """Drop one lane (or all, key=None); returns how many went."""
        if key is not None:
            had = key in self._lanes
            self._drop(key)
            return 1 if had else 0
        n = len(self._lanes)
        self._lanes.clear()
        self.resident_bytes = 0
        return n

    def __len__(self) -> int:
        return len(self._lanes)

    def keys(self) -> Tuple[Hashable, ...]:
        return tuple(self._lanes)

    def stats(self) -> dict:
        """The ``/statusz`` residency payload."""
        return {"lanes": len(self._lanes),
                "resident_bytes": self.resident_bytes,
                "budget_bytes": self.budget_bytes,
                "admitted": self.admitted, "evicted": self.evicted,
                "rejected": self.rejected, "merges": self.merges}
