"""Crash-only fleet supervisor: N serve workers, exactly-once queries.

PRs 11/15 made a single join survive rank death *inside* the mesh; the
serving plane itself was still one mortal ``--serve`` process.  The
:class:`FleetSupervisor` is the missing robustness substrate for ROADMAP
item 3: it owns N worker subprocesses (each running the existing
``main.py --serve -`` JSONL loop over a pipe), routes queries to them by
consistent hash on tenant, health-checks them with the LeaseBoard
heartbeat pattern (two missed beats = lapse, exactly the rank-lapse
rule), restarts dead workers with exponential backoff, and quarantines
crash-loopers through the :class:`~tpu_radix_join.service.breaker.
CircuitBreaker` state machine (K deaths without an intervening served
query trips the slot open; the cooldown is the quarantine window, the
half-open probe is the restart attempt; tenants re-hash onto the
surviving ring the moment the slot leaves it).

Correctness across crashes is the :class:`~tpu_radix_join.service.
journal.QueryJournal`'s exactly-once discipline:

  * **intent before dispatch** — an accepted query is journaled before
    any worker sees it, so no crash can vanish it;
  * **outcome before reply** — a worker's verdict is journaled before
    the client reads it, so a lost response is re-*served* from the
    journal, never re-*executed* (fingerprint dedup);
  * **replay on death** — a worker that dies mid-query leaves an
    unacknowledged intent; the supervisor replays it on a healthy
    worker (``FAILOVER``/``REPLAYN``), and a restarted supervisor
    replays every unacknowledged intent before taking new work.

The soak invariant (chaos ``fleet.worker_kill``, robustness/chaos.py
``soak_fleet``): every accepted query gets exactly one outcome — oracle
exact or classified — and the journal audit's ``double_exec`` stays 0.

Graceful drain: ``drain()`` (SIGTERM in ``main.py --fleet``) stops
admission, finishes in-flight queries under their deadlines, closes the
workers' stdin so each serve loop exits cleanly and withdraws its own
lease, and leaves the journal with zero unacknowledged intents — no
query stranded, no lease left to lapse.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from tpu_radix_join.performance.measurements import (DOUBLEEXEC, FAILOVER,
                                                     JDEPTH, REPLAYN,
                                                     WINCARN, WRESTART)
from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.retry import (BACKEND_UNAVAILABLE,
                                             REQUEST_ERROR)
from tpu_radix_join.service.breaker import OPEN, CircuitBreaker
from tpu_radix_join.service.journal import QueryJournal, request_fingerprint
from tpu_radix_join.service.microbatch import SIGNATURE_FIELDS
from tpu_radix_join.service.resultcache import (ResultCache,
                                                content_fingerprint)

#: ring resolution: virtual nodes per worker slot — enough that losing
#: one of a handful of workers re-hashes only its own tenants
_VNODES = 32

#: replay attempts per query before the supervisor gives up and returns
#: a classified failure (every attempt burned a worker incarnation)
_MAX_ATTEMPTS_SLACK = 2


def ring_points(slots: List[int], vnodes: int = _VNODES):
    """The consistent-hash ring for ``slots``: sorted (position, slot)
    pairs, positions drawn per (slot, vnode) so membership changes move
    only the departed slot's arcs."""
    pts = []
    for s in slots:
        for v in range(vnodes):
            h = hashlib.md5(f"w{s}:{v}".encode()).hexdigest()[:8]
            pts.append((int(h, 16), s))
    pts.sort()
    return pts


def route_tenant(tenant: str, slots: List[int],
                 vnodes: int = _VNODES) -> Optional[int]:
    """Owner slot for ``tenant`` on the ring over ``slots`` (None when the
    ring is empty).  Deterministic in (tenant, membership): the same
    tenant re-hashes to the same survivor whenever the same slot set is
    healthy — what keeps a tenant's warm capacity caches on one worker."""
    if not slots:
        return None
    pts = ring_points(sorted(set(slots)), vnodes)
    h = int(hashlib.md5(f"t:{tenant}".encode()).hexdigest()[:8], 16)
    for pos, slot in pts:
        if pos >= h:
            return slot
    return pts[0][1]            # wrap around


class _Worker:
    """One supervised serve subprocess: pipes, lease dir, incarnation,
    backoff state, and the crash-loop breaker for its slot."""

    def __init__(self, slot: int, work_dir: str, breaker: CircuitBreaker):
        self.slot = slot
        self.work_dir = work_dir          # per-incarnation artifacts live here
        self.breaker = breaker            # slot-scoped: survives incarnations
        self.proc: Optional[subprocess.Popen] = None
        self.incarnations = 0             # spawns, lifetime of the slot
        self.deaths = 0
        self.backoff_s = 0.0
        self.not_before = 0.0             # monotonic gate for the next spawn
        self.spawned_mono = 0.0
        self.queries_served = 0
        self._outq: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._reader: Optional[threading.Thread] = None

    @property
    def incarnation_id(self) -> str:
        return f"w{self.slot}i{self.incarnations}"

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def quarantined(self) -> bool:
        return self.breaker.state == OPEN

    def lease_dir(self) -> str:
        return os.path.join(self.work_dir, "leases")

    def lease_age_s(self) -> Optional[float]:
        """Age of the worker's own heartbeat lease (rank 0 of its private
        board), or None when it has not written one yet (booting) or
        withdrew it (clean exit)."""
        try:
            with open(os.path.join(self.lease_dir(),
                                   "lease_r0.json")) as f:
                lease = json.load(f)
            return max(0.0, time.time() - float(lease["t_epoch_s"]))
        except (OSError, ValueError, KeyError):
            return None

    def drain_events(self) -> List[dict]:
        """Everything the reader thread has queued (non-blocking)."""
        out = []
        while True:
            try:
                ev = self._outq.get_nowait()
            except queue.Empty:
                return out
            if ev is not None:
                out.append(ev)

    def next_event(self, timeout: float) -> Optional[dict]:
        """Next stdout JSON event, or None on timeout/EOF (the caller
        distinguishes via :attr:`alive`)."""
        try:
            return self._outq.get(timeout=timeout)
        except queue.Empty:
            return None


class FleetSupervisor:
    """Crash-only pool of ``--serve -`` workers behind one dispatch API.

    Single dispatcher thread by design (mirrors JoinSession's
    single-threaded serving contract): ``dispatch`` is the only mutator
    of routing state, so the exactly-once bookkeeping needs no locks
    beyond each worker's stdout reader queue.
    """

    def __init__(self, workers: int, worker_args: List[str],
                 work_dir: str, measurements=None,
                 lease_s: float = 5.0, missed_beats: int = 2,
                 boot_grace_s: float = 120.0,
                 restart_backoff_s: float = 0.25,
                 restart_backoff_max_s: float = 10.0,
                 crash_loop_threshold: int = 3,
                 crash_loop_window_s: float = 60.0,
                 dispatch_timeout_s: float = 300.0,
                 python: Optional[str] = None,
                 env: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic,
                 result_cache_max: int = 0,
                 result_cache_ttl_s: Optional[float] = None,
                 batch_window_ms: float = 0.0):
        if workers < 1:
            raise ValueError("fleet needs at least one worker")
        self.num_workers = workers
        self.worker_args = list(worker_args)
        self.work_dir = work_dir
        self.measurements = measurements
        self.lease_s = float(lease_s)
        self.missed_beats = int(missed_beats)
        self.boot_grace_s = float(boot_grace_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self._python = python or sys.executable
        self._env = env
        self._clock = clock
        os.makedirs(work_dir, exist_ok=True)
        self.journal = QueryJournal(work_dir)
        self.workers: Dict[int, _Worker] = {}
        for slot in range(workers):
            wdir = os.path.join(work_dir, f"worker{slot}")
            os.makedirs(wdir, exist_ok=True)
            # the slot's crash-loop breaker: K deaths with no served query
            # in between trip it OPEN (quarantine); the cooldown is the
            # quarantine window W; allow_primary()'s half-open promotion
            # is the restart probe, closed again by the first served query
            self.workers[slot] = _Worker(slot, wdir, CircuitBreaker(
                failure_threshold=crash_loop_threshold,
                cooldown_s=crash_loop_window_s, clock=clock,
                measurements=measurements))
        self.draining = False
        self.started = False
        #: supervisor-side result cache: a content hit is answered at the
        #: supervisor, journaled intent+outcome under the submission's
        #: fingerprint (exactly-once holds unchanged), and never reaches
        #: a worker.  Keyed by content + the worker config (worker_args
        #: determine what every worker computes).
        self.result_cache = ResultCache(result_cache_max,
                                        result_cache_ttl_s,
                                        measurements=measurements)
        #: when > 0 the router keys on the batch signature instead of the
        #: tenant, so co-batchable queries from DIFFERENT tenants land on
        #: the same worker and actually meet in its coalescing window
        self.batch_window_ms = float(batch_window_ms)
        #: tenant -> slot of the last routed query (statusz affinity view)
        self.batch_affinity: Dict[str, int] = {}
        # counters mirrored locally so summary() works without a registry
        self.failovers = 0
        self.replays = 0
        self.restarts = 0
        self.journal_served = 0     # outcomes re-served from the journal
        self.peak_depth = 0
        self.queries = 0

    @property
    def lapse_window_s(self) -> float:
        """Two-missed-beats staleness bound — the LeaseBoard rank-lapse
        rule applied to worker heartbeats."""
        return self.lease_s * self.missed_beats

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Spawn the boot pool.  Replaying a previous incarnation's
        unacknowledged intents is the caller's move (:meth:`replay_
        unacknowledged`) so it can route the replayed outcomes to its
        client."""
        if self.started:
            return
        self.started = True
        for slot in range(self.num_workers):
            self._spawn(self.workers[slot])

    def _worker_cmd(self, w: _Worker) -> List[str]:
        # the worker IS the existing serve loop: stdin JSONL in, outcome
        # JSON lines out.  --elastic on + --metrics-interval give it a
        # heartbeating lease (the sampler tick carries the lease write,
        # main.py's serve wiring), which is the health signal we read.
        beat = max(0.1, self.lease_s / 2.0)
        return [self._python, "-m", "tpu_radix_join.main",
                "--serve", "-", *self.worker_args,
                "--elastic", "on",
                "--lease-dir", w.lease_dir(),
                "--rank-lease-s", str(self.lease_s),
                "--rank-missed-beats", str(self.missed_beats),
                "--metrics-interval", str(beat),
                "--timeline-dir", w.work_dir]

    def _spawn(self, w: _Worker) -> None:
        w.incarnations += 1
        env = dict(self._env if self._env is not None else os.environ)
        # the worker must import this package regardless of the
        # supervisor's cwd — prepend the package root, keep the rest
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        # the incarnation id rides into the worker's flight-recorder
        # context (main.py serve wiring) so its forensics bundles group
        # per incarnation under tools_postmortem.py --merge
        env["TPU_RJ_WORKER_INCARNATION"] = w.incarnation_id
        # stale lease files from the previous incarnation must not read
        # as a live heartbeat
        try:
            os.remove(os.path.join(w.lease_dir(), "lease_r0.json"))
        except OSError:
            pass
        w.proc = subprocess.Popen(
            self._worker_cmd(w), stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, bufsize=1, env=env)
        w.spawned_mono = self._clock()
        w._outq = queue.Queue()
        w._reader = threading.Thread(
            target=self._read_worker, args=(w, w.proc),
            name=f"fleet-{w.incarnation_id}", daemon=True)
        w._reader.start()
        m = self.measurements
        if m is not None:
            m.incr(WINCARN)
            m.event("worker_spawn", slot=w.slot,
                    incarnation=w.incarnation_id, pid=w.proc.pid)

    @staticmethod
    def _read_worker(w: _Worker, proc: subprocess.Popen) -> None:
        """Reader thread: worker stdout JSON lines -> the slot's queue;
        EOF pushes a None sentinel so a blocked dispatcher wakes."""
        outq = w._outq
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    outq.put(json.loads(line))
                except ValueError:
                    continue       # torn/no-JSON chatter is not protocol
        except (OSError, ValueError):
            pass
        outq.put(None)

    # --------------------------------------------------------------- health
    def worker_state(self, w: _Worker) -> str:
        """``serving`` | ``booting`` | ``stale`` | ``quarantined`` |
        ``backoff`` | ``dead`` — the statusz vocabulary and the routing
        predicate (only ``serving``/``booting`` take traffic)."""
        if w.quarantined:
            return "quarantined"
        if not w.alive:
            return ("backoff"
                    if self._clock() < w.not_before else "dead")
        age = w.lease_age_s()
        if age is None:
            boot_for = self._clock() - w.spawned_mono
            return "booting" if boot_for <= self.boot_grace_s else "stale"
        return "serving" if age <= self.lapse_window_s else "stale"

    def routable_slots(self) -> List[int]:
        """Slots eligible for new queries right now: alive, not
        quarantined, heartbeat fresh (or still inside boot grace) — the
        consistent-hash ring's live membership."""
        return [s for s, w in sorted(self.workers.items())
                if self.worker_state(w) in ("serving", "booting")]

    def _restartable(self) -> List[_Worker]:
        now = self._clock()
        out = []
        for w in self.workers.values():
            if w.alive:
                continue
            if now < w.not_before:
                continue
            # a quarantined slot restarts only when its breaker half-opens
            # (allow_primary promotes OPEN -> HALF_OPEN after cooldown);
            # the restarted incarnation is the health probe
            if not w.breaker.allow_primary():
                continue
            out.append(w)
        return out

    def _ensure_capacity(self, deadline: float) -> Optional[int]:
        """A routable slot, restarting dead workers (with backoff) as
        needed; None when every slot stays down past ``deadline``."""
        while True:
            live = self.routable_slots()
            if live:
                return live[0]
            for w in self._restartable():
                self.restarts += 1
                m = self.measurements
                if m is not None:
                    m.incr(WRESTART)
                self._spawn(w)
            if self.routable_slots():
                continue
            if self._clock() >= deadline:
                return None
            time.sleep(0.05)

    # -------------------------------------------------------------- routing
    def _batch_signature(self, request: dict) -> Optional[str]:
        """The request's co-batchability class as a ring key, or None when
        batching is off — mirrors service/microbatch.batch_signature over
        the wire dict (same fields, same defaults as QueryRequest)."""
        if self.batch_window_ms <= 0:
            return None
        defaults = {"tuples_per_node": 1 << 16, "outer_kind": "unique",
                    "modulo": None, "zipf_theta": 0.75, "repeats": 1}
        sig = tuple(request.get(f, defaults[f]) for f in SIGNATURE_FIELDS)
        return f"sig:{sig}"

    def pick_worker(self, tenant: str,
                    signature: Optional[str] = None) -> Optional[_Worker]:
        """The tenant's ring owner among live slots — or, when a batch
        ``signature`` is given (batching enabled), the SIGNATURE's ring
        owner, so co-batchable queries from different tenants land on one
        worker and meet in its coalescing window.  The load signal is
        deliberately coarse for a closed-loop dispatcher: ring ownership
        keeps warm capacity caches on one worker; ledger/heartbeat load
        (queries served, lease age) surfaces in statusz for operators and
        re-balances only through membership changes."""
        slot = route_tenant(signature or tenant, self.routable_slots())
        if slot is not None:
            self.batch_affinity[tenant] = slot
        return self.workers[slot] if slot is not None else None

    # ------------------------------------------------------------- dispatch
    def _gauge_depth(self) -> None:
        depth = self.journal.depth()
        if depth > self.peak_depth:
            self.peak_depth = depth
            m = self.measurements
            if m is not None:
                # gauge discipline (MEPOCH-style): counter holds the max
                cur = int(m.counters.get(JDEPTH, 0))
                if depth > cur:
                    m.incr(JDEPTH, depth - cur)

    def _classified_failure(self, request: dict, detail: str) -> dict:
        return {"query_id": request.get("query_id"),
                "tenant": request.get("tenant", "default"),
                "status": "failed", "failure_class": BACKEND_UNAVAILABLE,
                "latency_ms": 0.0, "matches": None, "expected": None,
                "engine": "fleet", "degraded": True, "warm": False,
                "breaker_state": "open", "detail": detail}

    def _kill(self, w: _Worker, sig=signal.SIGKILL) -> None:
        if w.proc is not None and w.proc.poll() is None:
            try:
                os.kill(w.proc.pid, sig)
            except OSError:
                pass

    def kill_worker(self, slot: int) -> None:
        """SIGKILL one worker — the ``fleet.worker_kill`` chaos action
        and the bench's failover victim."""
        self._kill(self.workers[slot])

    def _on_death(self, w: _Worker, why: str) -> None:
        self._kill(w)                       # hung counts as dead: finish it
        try:
            w.proc.wait(timeout=10.0)
        except (subprocess.TimeoutExpired, OSError):
            pass
        w.deaths += 1
        # exponential backoff before the next incarnation; the breaker
        # additionally quarantines a crash-looping slot outright
        w.backoff_s = (self.restart_backoff_s if not w.backoff_s
                       else min(w.backoff_s * 2.0,
                                self.restart_backoff_max_s))
        w.not_before = self._clock() + w.backoff_s
        w.breaker.record_failure(BACKEND_UNAVAILABLE)
        m = self.measurements
        if m is not None:
            m.event("worker_death", slot=w.slot,
                    incarnation=w.incarnation_id, why=why,
                    deaths=w.deaths, backoff_s=round(w.backoff_s, 3),
                    quarantined=w.quarantined)

    def dispatch(self, request: dict, replayed: bool = False,
                 fp: Optional[str] = None) -> dict:
        """Serve one request exactly once; returns the outcome dict.

        The full WAL discipline: dedup against journaled outcomes first
        (a re-submitted or replayed query whose outcome exists is served
        from the journal, never re-executed), then the supervisor-side
        result cache (a content hit is journaled intent+outcome under the
        submission fingerprint and answered without touching a worker),
        then intent-journal, dispatch, outcome-journal.  A worker death
        mid-query fails the query over to a healthy worker (``FAILOVER``
        + ``REPLAYN``); only when every slot is down/quarantined past the
        dispatch deadline does the query end as a *classified* failure —
        still exactly one outcome.

        ``fp`` overrides the computed submission fingerprint — the replay
        path passes the journaled intent's fp verbatim so a replayed
        query's outcome always lands under the intent it acknowledges,
        even across builds whose canonicalization differs."""
        if self.draining:
            return self._classified_failure(request, "fleet draining: "
                                            "admission stopped")
        self.queries += 1
        fp = fp or request_fingerprint(request)
        prior = self.journal.outcome_for(fp)
        if prior is not None:
            # journaled-outcome/lost-response dedup: the answer exists,
            # the execution must not happen again
            self.journal_served += 1
            out = dict(prior)
            out["fleet"] = {"served_from_journal": True, "fp": fp}
            return out
        cached = self._try_cache(request, fp)
        if cached is not None:
            return cached
        deadline = self._clock() + max(
            self.dispatch_timeout_s,
            float(request.get("deadline_s") or 0.0))
        m = self.measurements
        attempt = 0
        max_attempts = self.num_workers + _MAX_ATTEMPTS_SLACK
        while True:
            attempt += 1
            if attempt > max_attempts or self._clock() >= deadline:
                out = self._classified_failure(
                    request, f"fleet exhausted {attempt - 1} dispatch "
                             f"attempt(s); no worker completed the query")
                self.journal.append_outcome(fp, out)
                self._gauge_depth()
                return out
            slot = self._ensure_capacity(deadline)
            if slot is None:
                out = self._classified_failure(
                    request, "no healthy worker (all dead or quarantined)")
                self.journal.append_outcome(fp, out)
                self._gauge_depth()
                return out
            w = self.pick_worker(request.get("tenant", "default"),
                                 signature=self._batch_signature(request))
            if w is None:
                continue
            self.journal.append_intent(request, fp=fp, worker=w.slot,
                                       incarnation=w.incarnation_id,
                                       attempt=attempt)
            if attempt > 1:
                self.replays += 1
                if m is not None:
                    m.incr(REPLAYN)
            self._gauge_depth()
            try:
                w.proc.stdin.write(json.dumps(request) + "\n")
                w.proc.stdin.flush()
            except (OSError, ValueError):
                self._on_death(w, "stdin_broken")
                self._count_failover(m)
                continue
            # chaos: SIGKILL the routed worker mid-query — the request is
            # on its pipe, the outcome must come from a survivor instead
            if faults.fires(faults.FLEET_WORKER_KILL, m):
                self.kill_worker(w.slot)
            out = self._await_outcome(w, request, deadline)
            if out is None:
                self._on_death(w, "died_mid_query")
                self._count_failover(m)
                continue
            self.journal.append_outcome(fp, out, worker=w.slot)
            w.queries_served += 1
            w.breaker.record_success()
            w.backoff_s = 0.0
            self._gauge_depth()
            self._cache_put(request, out)
            out = dict(out)
            out["fleet"] = {"worker": w.slot,
                            "incarnation": w.incarnation_id,
                            "attempts": attempt, "replayed": replayed
                            or attempt > 1}
            return out

    def dispatch_batch(self, requests: List[dict]) -> List[dict]:
        """Serve a co-batchable group through ONE worker: every request is
        intent-journaled and written to the signature's ring owner
        back-to-back — so the worker's serve loop sees the whole group
        pending and coalesces it into a fused device program — then the
        outcomes are awaited and journaled in order.  A worker death
        mid-batch (the ``fleet.worker_kill`` chaos site fires per written
        query) fails the UNANSWERED remainder over through the normal
        one-query path under the same fingerprints — already-journaled
        outcomes dedup, so every query still gets exactly one outcome and
        ``double_exec`` stays 0."""
        if len(requests) <= 1 or self.batch_window_ms <= 0:
            return [self.dispatch(r) for r in requests]
        m = self.measurements
        outs: Dict[int, dict] = {}
        pend: List[tuple] = []           # (index, request, fp) to execute
        for i, request in enumerate(requests):
            if self.draining:
                outs[i] = self._classified_failure(
                    request, "fleet draining: admission stopped")
                continue
            self.queries += 1
            fp = request_fingerprint(request)
            prior = self.journal.outcome_for(fp)
            if prior is not None:
                self.journal_served += 1
                out = dict(prior)
                out["fleet"] = {"served_from_journal": True, "fp": fp}
                outs[i] = out
                continue
            cached = self._try_cache(request, fp)
            if cached is not None:
                outs[i] = cached
                continue
            pend.append((i, request, fp))
        if pend:
            deadline = self._clock() + self.dispatch_timeout_s
            slot = self._ensure_capacity(deadline)
            w = (self.pick_worker(
                    pend[0][1].get("tenant", "default"),
                    signature=self._batch_signature(pend[0][1]))
                 if slot is not None else None)
            alive = w is not None
            if alive:
                for i, request, fp in pend:
                    self.journal.append_intent(request, fp=fp, worker=w.slot,
                                               incarnation=w.incarnation_id,
                                               attempt=1)
                    try:
                        w.proc.stdin.write(json.dumps(request) + "\n")
                        w.proc.stdin.flush()
                    except (OSError, ValueError):
                        alive = False
                        break
                    if faults.fires(faults.FLEET_WORKER_KILL, m):
                        self.kill_worker(w.slot)
                self._gauge_depth()
            died = not alive
            for i, request, fp in pend:
                out = (self._await_outcome(w, request, deadline)
                       if not died else None)
                if out is None:
                    # worker lost mid-batch: the batch retries UNBATCHED —
                    # each unanswered query fails over individually, its
                    # journaled fp riding along so dedup and the audit
                    # see one submission, one outcome
                    if not died:
                        died = True
                        self._on_death(w, "died_mid_batch")
                        self._count_failover(m)
                    outs[i] = self.dispatch(request, replayed=True, fp=fp)
                    continue
                self.journal.append_outcome(fp, out, worker=w.slot)
                w.queries_served += 1
                w.breaker.record_success()
                w.backoff_s = 0.0
                self._cache_put(request, out)
                out = dict(out)
                out["fleet"] = {"worker": w.slot,
                                "incarnation": w.incarnation_id,
                                "attempts": 1, "replayed": False,
                                "batched": len(pend)}
                outs[i] = out
            self._gauge_depth()
        return [outs[i] for i in range(len(requests))]

    # ---------------------------------------------------------- result cache
    def _content_fp(self, request: dict) -> str:
        # worker_args ARE the fleet's join config: every worker is spawned
        # from them, so they are the config component of content identity
        return content_fingerprint(request,
                                   config_fp={"worker_args":
                                              list(self.worker_args)})

    def _try_cache(self, request: dict, fp: str) -> Optional[dict]:
        """Answer ``request`` from the supervisor-side result cache, or
        None.  A hit is journaled intent+outcome under the submission
        fingerprint ``fp`` — the WAL sees the same accepted/answered pair
        as an executed query, so replay, dedup, and the double_exec audit
        are oblivious to where the answer came from."""
        if self.result_cache.max_entries == 0:
            return None
        payload = self.result_cache.get(self._content_fp(request))
        if payload is None:
            return None
        out = {"query_id": request.get("query_id"),
               "tenant": request.get("tenant", "default"),
               "status": "ok", "failure_class": "ok", "latency_ms": 0.0,
               "matches": payload.get("matches"),
               "expected": payload.get("expected"),
               "engine": payload.get("engine", "primary"),
               "degraded": False, "warm": True,
               "breaker_state": "closed", "detail": "result cache hit",
               "served_by": "cache_hit"}
        self.journal.append_intent(request, fp=fp)
        self.journal.append_outcome(fp, out)
        out = dict(out)
        out["fleet"] = {"served_from_cache": True, "fp": fp}
        return out

    def _cache_put(self, request: dict, out: dict) -> None:
        if (self.result_cache.max_entries == 0
                or out.get("status") != "ok" or out.get("degraded")
                or out.get("matches") is None
                or request.get("delta_tuples_per_node")):
            return
        self.result_cache.put(
            self._content_fp(request),
            {"matches": out.get("matches"), "expected": out.get("expected"),
             "engine": out.get("engine", "primary")})

    def _count_failover(self, m) -> None:
        self.failovers += 1
        if m is not None:
            m.incr(FAILOVER)

    def _await_outcome(self, w: _Worker, request: dict,
                       deadline: float) -> Optional[dict]:
        """The worker's outcome event for this request, or None when the
        worker died (EOF) or went silent past the deadline (hung ==
        dead: crash-only has no third state)."""
        qid = request.get("query_id")
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                return None
            ev = w.next_event(min(remaining, 0.5))
            if ev is None:
                if not w.alive:
                    return None          # EOF sentinel or dead process
                continue                 # idle tick; keep waiting
            kind = ev.get("event")
            if kind == "outcome" and ev.get("query_id") == qid:
                out = {k: v for k, v in ev.items() if k != "event"}
                return out
            if kind == "request_error" and ev.get("query_id") == qid:
                # the worker refused the line: classify, don't retry —
                # a malformed request is the client's bug on any worker
                return {"query_id": qid,
                        "tenant": request.get("tenant", "default"),
                        "status": "failed",
                        "failure_class": REQUEST_ERROR,
                        "latency_ms": 0.0,
                        "detail": str(ev.get("error"))}
            # stale outcome from a superseded attempt, summary chatter,
            # etc. — not ours, keep reading

    # --------------------------------------------------------------- replay
    def replay_unacknowledged(
            self, emit: Optional[Callable[[dict], None]] = None
            ) -> List[dict]:
        """Serve every unacknowledged journal intent (a previous
        incarnation's accepted-but-unanswered queries) on the current
        pool — the restart half of exactly-once.  Queries whose outcome
        IS journaled are skipped here; they re-serve through the dedup
        path when the client re-submits."""
        outs = []
        m = self.measurements
        for row in self.journal.unacknowledged():
            request = row.get("request") or {}
            self.replays += 1
            if m is not None:
                m.incr(REPLAYN)
            # the intent row's fp rides through verbatim: the replayed
            # outcome must acknowledge THAT intent even if this build's
            # canonicalization would fingerprint the request differently
            out = self.dispatch(request, replayed=True, fp=row.get("fp"))
            outs.append(out)
            if emit:
                emit(out)
        return outs

    # ---------------------------------------------------------------- drain
    def drain(self, timeout_s: float = 60.0) -> dict:
        """Graceful shutdown: stop admission, close every worker's stdin
        (the serve loop's EOF -> summary -> clean exit -> lease
        withdrawal path), wait for exits, and report the final journal
        audit.  In-flight queries finished before drain was called —
        the dispatcher is single-threaded, so reaching here means no
        query is mid-pipe."""
        self.draining = True
        for w in self.workers.values():
            if w.alive:
                try:
                    w.proc.stdin.close()
                except OSError:
                    pass
        deadline = self._clock() + timeout_s
        for w in self.workers.values():
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.1, deadline - self._clock()))
            except subprocess.TimeoutExpired:
                self._kill(w)          # a worker that ignores EOF is hung
                try:
                    w.proc.wait(timeout=5.0)
                except (subprocess.TimeoutExpired, OSError):
                    pass
        # a cleanly-exited worker withdrew its own lease (main.py's
        # finally); what remains is the stale lease of a killed
        # incarnation — every process is dead now, so the supervisor
        # sweeps them: no lease left to lapse
        swept = []
        for s, w in self.workers.items():
            lease = os.path.join(w.lease_dir(), "lease_r0.json")
            if os.path.exists(lease):
                try:
                    os.remove(lease)
                    swept.append(s)
                except OSError:
                    pass
        audit = self.journal.audit()
        m = self.measurements
        if m is not None and audit.double_exec:
            m.incr(DOUBLEEXEC, audit.double_exec)
        leases = [s for s, w in self.workers.items()
                  if os.path.exists(os.path.join(w.lease_dir(),
                                                 "lease_r0.json"))]
        if m is not None:
            m.event("fleet_drain", unacked=audit.unacked,
                    double_exec=audit.double_exec,
                    leases_left=leases, leases_swept=swept)
        return {"unacked": audit.unacked,
                "double_exec": audit.double_exec,
                "leases_left": leases,
                "leases_swept": swept}

    def close(self) -> None:
        """Hard stop (idempotent): drain if not already, then make sure
        nothing is left running."""
        if not self.draining:
            self.drain()
        for w in self.workers.values():
            self._kill(w)

    # -------------------------------------------------------------- statusz
    def statusz_section(self) -> dict:
        """The ``--statusz`` fleet section: per-worker health /
        incarnation / backoff / breaker, journal depth, replay
        counters."""
        audit = self.journal.audit()
        workers = {}
        for slot, w in sorted(self.workers.items()):
            age = w.lease_age_s()
            workers[f"w{slot}"] = {
                "state": self.worker_state(w),
                "pid": w.proc.pid if w.proc is not None else None,
                "incarnation": w.incarnations,
                "incarnation_id": w.incarnation_id,
                "deaths": w.deaths,
                "backoff_s": round(w.backoff_s, 3),
                "breaker": w.breaker.snapshot(),
                "queries_served": w.queries_served,
                "lease_age_s": round(age, 3) if age is not None else None}
        out = {"workers": workers,
               "routable": self.routable_slots(),
               "draining": self.draining,
               "journal": {"depth": audit.unacked,
                           "peak_depth": self.peak_depth,
                           "path": self.journal.path,
                           **audit.to_json()},
               "queries": self.queries,
               "failovers": self.failovers,
               "replays": self.replays,
               "restarts": self.restarts,
               "journal_served": self.journal_served}
        if self.result_cache.max_entries:
            out["cache"] = self.result_cache.stats()
        if self.batch_window_ms > 0:
            out["batch"] = {"window_ms": self.batch_window_ms,
                            "affinity": dict(self.batch_affinity)}
        return out

    def readiness(self) -> dict:
        """``/healthz`` provider: the fleet is ready while it admits work
        and at least one worker can take a query."""
        if self.draining:
            return {"ok": False, "reason": "draining"}
        if not self.routable_slots():
            return {"ok": False, "reason": "no_healthy_worker"}
        return {"ok": True}

    # -------------------------------------------------------------- summary
    def summary(self) -> dict:
        audit = self.journal.audit()
        return {"workers": self.num_workers,
                "queries": self.queries,
                "failover": self.failovers,
                "replayn": self.replays,
                "worker_restarts": self.restarts,
                "incarnations": sum(w.incarnations
                                    for w in self.workers.values()),
                "journal_served": self.journal_served,
                "jdepth": self.peak_depth,
                "unacked": audit.unacked,
                "double_exec": audit.double_exec,
                "cache_hits": self.result_cache.hits,
                "cache_hit_rate": self.result_cache.stats()["hit_rate"],
                "quarantined": [s for s, w in self.workers.items()
                                if w.quarantined]}
