"""Inter-query micro-batching: bounded-window coalescing of small joins.

Many serving workloads are storms of SMALL joins — each one pays the
full dispatch floor (planner/profile.py ``dispatch_floor_ms``) for a
program that runs microseconds of real work.  The coalescer holds
arriving queries for at most ``batch_window_ms``, groups the ones whose
key lanes can legally share one device program, and fuses each group
into ONE sort + ONE probe via
:func:`~tpu_radix_join.ops.merge_delta.batched_merge_count` — the
composite-key trick of ``ops/radix.py scatter_to_blocks_grouped`` lifted
to serving scope.  Q dispatch floors become one.

Two queries may share a batch only when they agree on
:func:`batch_signature` — the request fields that change the *key
distribution or lane shapes* (tuples_per_node, outer_kind, modulo,
zipf_theta, repeats).  Seeds and query ids may differ freely: the
composite query tag keeps every query's keys in a disjoint range, so
fused counts are exact per query, not approximations.

Failure isolation contract (service/session.py `_drain_batch`):

  * per-query deadlines survive batching — a query whose deadline would
    expire inside the window is dispatched immediately, alone;
  * a fused batch that FAILS is retried unbatched, one query at a time,
    so a poisoned query classifies alone and healthy co-batched queries
    still succeed (the batch is an optimization, never a blast radius).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from tpu_radix_join.ops.merge_delta import batch_feasible

#: request fields that must agree for two queries to share one fused
#: device program (they shape the generated lanes / key distribution)
SIGNATURE_FIELDS = ("tuples_per_node", "outer_kind", "modulo", "zipf_theta",
                    "repeats")


def batch_signature(request) -> Tuple:
    """The co-batchability class of one request: the tuple of fields two
    queries must share to fuse into one program.  Also the fleet router's
    affinity key (service/fleet.py ``pick_worker``) — same signature,
    same worker, so co-batchable tenants actually meet in one window."""
    return tuple(getattr(request, f) for f in SIGNATURE_FIELDS)


class MicroBatcher:
    """Bounded-window query coalescer.

    Owns NO threads: the serving loop calls :meth:`offer` as queries
    arrive and :meth:`due` before blocking, and flushes the returned
    groups itself — single-threaded like the session, deterministic
    under test (inject ``clock``).

    ``window_ms == 0`` disables coalescing: every offer is immediately
    due as a singleton group, so the caller needs no mode switch.
    """

    def __init__(self, window_ms: float, max_queries: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        if window_ms < 0:
            raise ValueError("window_ms must be >= 0")
        if max_queries < 2:
            raise ValueError("max_queries must be >= 2")
        self.window_ms = window_ms
        self.max_queries = max_queries
        self._clock = clock
        #: signature -> (window-open timestamp, pending requests)
        self._pending: Dict[Tuple, Tuple[float, List]] = {}
        self.offered = 0
        self.fused_batches = 0
        self.fused_queries = 0
        self.solo = 0

    # ------------------------------------------------------------- intake
    def offer(self, request, key_bound: int) -> Optional[List]:
        """Admit one request to its signature window.  Returns a ready
        group (list of requests) the caller must dispatch NOW, or None
        if the request is parked awaiting the window:

          * coalescing disabled, batch infeasible for the key bound, or
            a deadline too tight for the window -> ``[request]`` alone;
          * the window hit ``max_queries`` -> the full group, fused.
        """
        self.offered += 1
        if self.window_ms == 0 or not batch_feasible(self.max_queries,
                                                     key_bound):
            self.solo += 1
            return [request]
        deadline = getattr(request, "deadline_s", None)
        if deadline is not None and deadline * 1000.0 <= self.window_ms:
            # the window would eat the whole deadline: serve it alone now
            self.solo += 1
            return [request]
        sig = batch_signature(request)
        opened, group = self._pending.get(sig, (self._clock(), []))
        group.append(request)
        if len(group) >= self.max_queries:
            del self._pending[sig]
            self._note_flush(group)
            return group
        self._pending[sig] = (opened, group)
        return None

    # -------------------------------------------------------------- flush
    def due(self, now: Optional[float] = None) -> List[List]:
        """Groups whose window has expired (possibly singletons), in
        window-open order.  The serving loop calls this before blocking
        on input and after the wait hinted by :meth:`next_deadline_s`."""
        now = self._clock() if now is None else now
        ready: List[Tuple[float, List]] = []
        for sig in list(self._pending):
            opened, group = self._pending[sig]
            if (now - opened) * 1000.0 >= self.window_ms:
                del self._pending[sig]
                ready.append((opened, group))
        ready.sort(key=lambda t: t[0])
        for _, group in ready:
            self._note_flush(group)
        return [group for _, group in ready]

    def flush(self) -> List[List]:
        """Every pending group regardless of window age — drain/shutdown
        path, so no parked query is ever lost to a closing session."""
        groups = [group for _, group in sorted(self._pending.values(),
                                               key=lambda t: t[0])]
        self._pending.clear()
        for group in groups:
            self._note_flush(group)
        return groups

    def next_deadline_s(self) -> Optional[float]:
        """Seconds until the oldest open window expires (<= 0 = overdue),
        or None when nothing is parked — the serving loop's poll timeout."""
        if not self._pending:
            return None
        oldest = min(opened for opened, _ in self._pending.values())
        return (self.window_ms / 1000.0) - (self._clock() - oldest)

    def _note_flush(self, group: List) -> None:
        if len(group) >= 2:
            self.fused_batches += 1
            self.fused_queries += len(group)
        else:
            self.solo += 1

    # ---------------------------------------------------------- reporting
    def pending(self) -> int:
        return sum(len(g) for _, g in self._pending.values())

    def stats(self) -> dict:
        """The ``/statusz`` batch payload."""
        fused = self.fused_queries
        total = fused + self.solo
        return {"window_ms": self.window_ms,
                "max_queries": self.max_queries,
                "pending": self.pending(),
                "offered": self.offered,
                "fused_batches": self.fused_batches,
                "fused_queries": fused,
                "solo": self.solo,
                "fuse_ratio": round(fused / total, 4) if total else 0.0}
