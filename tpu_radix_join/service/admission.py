"""Admission-controlled query queue with per-tenant quotas.

The resident session's front door: a bounded FIFO that classifies every
refusal instead of blocking or dropping.  Two admission rules, checked in
order at :meth:`AdmissionQueue.submit`:

  * **depth** — at most ``max_depth`` queries pending across all tenants
    (a full queue means the session is saturated; unbounded queueing just
    converts overload into deadline misses later);
  * **quota** — at most ``tenant_quota`` pending queries per tenant, so
    one chatty tenant cannot occupy the whole queue (the failure-isolation
    half of multi-tenancy: the noisy neighbor is rejected, the quiet one
    still admits).

A refusal raises :class:`AdmissionRejected` carrying the
``admission_rejected`` failure class and a machine-readable ``reason``
(``queue_full`` | ``tenant_quota``) — the serve loop turns it into a
classified outcome JSON, never a hang or a silent drop.

Thread-safe (one lock around the deque + per-tenant counts): the serve
loop is single-threaded today, but the closed-loop bench submits from a
generator thread and the session drains from the main one.
"""

from __future__ import annotations

import collections
import threading
from typing import Deque, Dict, Optional

from tpu_radix_join.performance.measurements import QADMIT, QREJECT
from tpu_radix_join.robustness.retry import ADMISSION_REJECTED

QUEUE_FULL = "queue_full"
TENANT_QUOTA = "tenant_quota"


class AdmissionRejected(RuntimeError):
    """Query refused at the door (never started executing)."""

    failure_class = ADMISSION_REJECTED

    def __init__(self, reason: str, detail: str):
        super().__init__(f"admission rejected ({reason}): {detail}")
        self.reason = reason


class AdmissionQueue:
    """Bounded FIFO of pending requests with per-tenant quotas.

    ``submit`` admits or raises; ``pop`` hands the oldest pending request
    to the session; ``done`` releases the tenant's slot once the query's
    outcome is recorded (a popped-but-running query still counts against
    its tenant — the quota bounds *in-flight* work, not just queue
    residency, or a tenant could dodge it by keeping exactly one query
    running).
    """

    def __init__(self, max_depth: int = 64, tenant_quota: int = 8,
                 measurements=None):
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1")
        if tenant_quota < 1:
            raise ValueError("tenant_quota must be >= 1")
        self.max_depth = max_depth
        self.tenant_quota = tenant_quota
        self.measurements = measurements
        self._lock = threading.Lock()
        self._pending: Deque[object] = collections.deque()
        self._in_flight: Dict[str, int] = collections.defaultdict(int)
        self.admitted = 0
        self.rejected = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    def depth(self) -> int:
        return len(self)

    def tenant_load(self, tenant: str) -> int:
        with self._lock:
            return self._in_flight[tenant]

    def submit(self, request) -> None:
        """Admit ``request`` (anything with a ``tenant`` attribute) or
        raise :class:`AdmissionRejected`.  The rejection is recorded as a
        counter + trace event before raising, so dashboards see rejections
        even when the caller swallows the exception."""
        tenant = getattr(request, "tenant", "default")
        m = self.measurements
        with self._lock:
            if len(self._pending) >= self.max_depth:
                reason, detail = QUEUE_FULL, (
                    f"queue depth {len(self._pending)} at max_depth "
                    f"{self.max_depth}")
            elif self._in_flight[tenant] >= self.tenant_quota:
                reason, detail = TENANT_QUOTA, (
                    f"tenant {tenant!r} has {self._in_flight[tenant]} "
                    f"in-flight queries at quota {self.tenant_quota}")
            else:
                self._pending.append(request)
                self._in_flight[tenant] += 1
                self.admitted += 1
                if m is not None:
                    m.incr(QADMIT)
                return
            self.rejected += 1
        if m is not None:
            m.incr(QREJECT)
            m.event("admission_rejected", tenant=tenant, reason=reason,
                    query_id=getattr(request, "query_id", None))
        raise AdmissionRejected(reason, detail)

    def pop(self) -> Optional[object]:
        """Oldest pending request, or None when the queue is empty.  The
        tenant's slot stays held until :meth:`done`."""
        with self._lock:
            return self._pending.popleft() if self._pending else None

    def pop_matching(self, pred, limit: int) -> list:
        """Up to ``limit`` pending requests satisfying ``pred``, removed
        in FIFO order; non-matching requests keep their relative order.
        The micro-batch coalescer's group-pull (service/session.py
        ``run_next_batch``): tenant slots stay held until :meth:`done`,
        exactly as with :meth:`pop`."""
        if limit <= 0:
            return []
        taken: list = []
        with self._lock:
            keep = collections.deque()
            while self._pending:
                request = self._pending.popleft()
                if len(taken) < limit and pred(request):
                    taken.append(request)
                else:
                    keep.append(request)
            self._pending = keep
        return taken

    def done(self, request) -> None:
        """Release the tenant slot taken at submit (call exactly once per
        popped request, on every outcome path)."""
        tenant = getattr(request, "tenant", "default")
        with self._lock:
            if self._in_flight[tenant] > 0:
                self._in_flight[tenant] -= 1

    def rejection_rate(self) -> float:
        total = self.admitted + self.rejected
        return self.rejected / total if total else 0.0
