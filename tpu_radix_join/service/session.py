"""JoinSession: the resident, admission-controlled join service.

The one-shot driver (main.py) pays mesh bring-up, XLA compilation, the
JHIST sizing pre-pass, and a ~5-8 ms dispatch tunnel round-trip on EVERY
invocation, and a backend outage mid-run can only be reported, not
absorbed.  A :class:`JoinSession` keeps all of that warm across many
queries:

  * the **mesh and compiled executables** — ``HashJoin`` caches compiled
    programs per (shape, capacity) key, so same-shape queries after the
    first skip compilation entirely;
  * the **plan cache** (planner/cache.py) — the first query's converged
    window capacities warm-start every later same-shape query past the
    sizing pre-pass (no JHIST dispatch), via the cache's new in-process
    hot layer;
  * **placed relations** — a small LRU of device-resident inputs, so the
    closed-loop bench's repeated workloads skip generation + transfer.

In front of the engine sit the robustness pieces this module composes
(each one classified, none of them able to take the session down):

  * :class:`~tpu_radix_join.service.admission.AdmissionQueue` — bounded
    depth + per-tenant quotas -> ``admission_rejected``;
  * :class:`~tpu_radix_join.service.deadline.Deadline` — per-query
    budgets enforced cooperatively between phases (the engine's
    ``cancel`` hook) -> ``deadline_exceeded``;
  * :class:`~tpu_radix_join.service.breaker.CircuitBreaker` — consecutive
    backend failures trip the session onto the degraded CPU engine
    (robustness/degrade.py machinery); half-open probes recover it;
  * per-query **failure isolation** — every exception inside a query is
    caught, classified via the ``failure_class`` taxonomy, and turned
    into a :class:`QueryOutcome`; only session-construction errors and
    interrupts propagate.

``main.py --serve`` feeds it from a JSONL request file; ``bench.py
--serve-bench`` closes the loop and gates the SLO tags.
"""

from __future__ import annotations

import collections
import dataclasses
import sys
import time
from typing import Callable, Dict, List, Optional

from tpu_radix_join.core.config import JoinConfig, ServiceConfig
from tpu_radix_join.performance.measurements import (BATCHN, BATCHQ,
                                                     COMPILEMS, DELTAMERGE,
                                                     JHIST, MEPOCH, NCOMPILE,
                                                     QDEADLINE, QDEGRADED,
                                                     QWARM, RANKLOST,
                                                     RECOVERMS, RECOVERN)
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness.retry import (BACKEND_UNAVAILABLE,
                                             DEADLINE_EXCEEDED, OK)
from tpu_radix_join.service.admission import AdmissionQueue, AdmissionRejected
from tpu_radix_join.service.breaker import HALF_OPEN, CircuitBreaker
from tpu_radix_join.service.deadline import Deadline, DeadlineExceeded
from tpu_radix_join.service.slo import SLORecorder

#: unclassified-exception sentinel: a query that dies without a
#: failure_class still yields a terminal outcome (the session survives),
#: but chaos/soak treats this string as an isolation violation
UNCLASSIFIED = "unclassified"


class BackendUnavailable(ConnectionError):
    """The chip backend failed a query-time dispatch (tunnel outage)."""

    failure_class = BACKEND_UNAVAILABLE


@dataclasses.dataclass(frozen=True)
class QueryRequest:
    """One join request as the serve loop admits it (JSONL line shape)."""

    query_id: str
    tenant: str = "default"
    tuples_per_node: int = 1 << 16
    outer_kind: str = "unique"          # unique | modulo | zipf
    modulo: Optional[int] = None
    zipf_theta: float = 0.75
    seed: int = 1234
    repeats: int = 1
    deadline_s: Optional[float] = None  # None -> ServiceConfig default
    #: incremental query: this many NEW tuples per node appended to the
    #: session-resident inner relation since the last query — served by
    #: the O(N+Δ) delta-merge fast path when residency is enabled
    #: (ServiceConfig.resident_budget_bytes > 0), full path otherwise
    delta_tuples_per_node: int = 0

    @classmethod
    def from_json(cls, obj: dict) -> "QueryRequest":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(obj) - fields
        if unknown:
            raise ValueError(f"unknown request fields: {sorted(unknown)}")
        if "query_id" not in obj:
            raise ValueError("request needs a query_id")
        return cls(**obj)


@dataclasses.dataclass
class QueryOutcome:
    """Terminal, classified verdict for one submitted query."""

    query_id: str
    tenant: str
    status: str                     # ok | failed | rejected
    failure_class: str              # "ok" when status == "ok"
    latency_ms: float
    matches: Optional[int] = None
    expected: Optional[int] = None
    engine: str = "primary"         # primary | cpu_fallback
    degraded: bool = False
    warm: bool = False              # sizing pre-pass skipped (cache hit)
    breaker_state: str = "closed"
    detail: str = ""
    bundle: Optional[str] = None    # forensics bundle path, failed queries
    #: which serving path produced the answer: execute (full engine run),
    #: cache_hit (result cache, no execution), batched (fused multi-query
    #: program), delta_merge (O(N+Δ) incremental path)
    served_by: str = "execute"

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["latency_ms"] = round(self.latency_ms, 3)
        if out.get("bundle") is None:
            # successful queries keep the pre-forensics line shape
            out.pop("bundle", None)
        return out


class JoinSession:
    """Resident engine + admission queue + breaker + SLO accounting.

    Single-threaded by design: one mesh, one query at a time (the
    micro-batching direction in ROADMAP item 1 layers onto this API).
    Construction builds the primary engine once; ``submit``/``run_next``/
    ``drain`` serve queries; ``close`` releases everything the session
    owns (and is idempotent).
    """

    def __init__(self, config: JoinConfig,
                 service: Optional[ServiceConfig] = None,
                 measurements=None, plan_cache=None, profile: str = "v5e_lite",
                 clock: Callable[[], float] = time.monotonic,
                 forensics_dir: Optional[str] = None,
                 ledger=None, membership=None, elastic: bool = False,
                 partition_manifest=None, elastic_grow: bool = False,
                 hedge: str = "off", hedge_threshold: float = 0.5):
        from tpu_radix_join.operators.hash_join import HashJoin

        self.config = config
        #: elastic mesh recovery services (robustness/membership +
        #: checkpoint.PartitionManifest), threaded onto every engine the
        #: session builds: the session SURVIVES a mesh change — a
        #: mid-query rank loss recovers inside join_arrays (classified
        #: ``recovered`` diagnostics, exact count), later queries compile
        #: against the new epoch (the engine's compile keys and capacity
        #: fingerprints carry it), and the breaker keeps serving —
        #: degraded if it was already open — instead of the whole session
        #: dying with the rank
        self.membership = membership
        self.elastic = elastic
        self.partition_manifest = partition_manifest
        #: growth + hedging posture, threaded like membership: a session
        #: can admit ranks (elastic_grow) and speculate on stragglers
        #: (hedge/hedge_threshold) on any engine it builds
        self.elastic_grow = elastic_grow
        self.hedge = hedge
        self.hedge_threshold = hedge_threshold
        self.service = service or ServiceConfig()
        self.measurements = measurements
        #: cross-run telemetry ledger (observability/ledger.py): when set,
        #: every executed query appends one ``kind="query"`` row — the
        #: per-query evidence stream a one-shot driver can't produce
        self.ledger = ledger
        self._recompile_storms = 0
        #: when set, every executed-and-failed query (deadline expiry,
        #: backend outage, breaker trip, corruption) drops a forensics
        #: bundle here (observability/postmortem.py), stamped with the
        #: query_id the flight-recorder context carried during the query
        self.forensics_dir = forensics_dir
        self._cache_tmp = None
        if plan_cache is None:
            # a resident session warms by default: without a caller-provided
            # cache dir, own an ephemeral one (first same-shape query pays
            # the sizing pre-pass, every later one skips it via the hot
            # layer; the tempdir dies with the session)
            import tempfile

            from tpu_radix_join.planner import PlanCache, load_profile
            self._cache_tmp = tempfile.TemporaryDirectory(
                prefix="join_session_plan_cache_")
            plan_cache = PlanCache(self._cache_tmp.name,
                                   load_profile(profile),
                                   measurements=measurements)
        self.plan_cache = plan_cache
        self._clock = clock
        self.queue = AdmissionQueue(self.service.max_queue_depth,
                                    self.service.tenant_quota,
                                    measurements=measurements)
        self.breaker = CircuitBreaker(self.service.breaker_threshold,
                                      self.service.breaker_cooldown_s,
                                      clock=clock,
                                      measurements=measurements)
        self.slo = SLORecorder()
        self.engine = HashJoin(config, measurements=measurements,
                               plan_cache=plan_cache)
        self._wire_elastic(self.engine)
        self._cpu_engine = None         # built lazily on first open-state query
        self._place_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        # ------------------------------------------------ serving fast paths
        from tpu_radix_join.service.resident import ResidentStateManager
        from tpu_radix_join.service.resultcache import ResultCache
        #: whole-query reuse keyed by content fingerprint (tier 1; disabled
        #: unless ServiceConfig.result_cache_max > 0)
        self.result_cache = ResultCache(self.service.result_cache_max,
                                        self.service.result_cache_ttl_s,
                                        measurements=measurements,
                                        clock=clock)
        #: device-resident sorted inner lanes for delta-merge (tier 3;
        #: disabled unless ServiceConfig.resident_budget_bytes > 0)
        self.resident = ResidentStateManager(
            self.service.resident_budget_bytes, measurements=measurements)
        #: host mirror of each resident lane's key multiset — the exactness
        #: oracle for incremental queries (base ∪ all absorbed deltas has no
        #: closed-form expected count once the session has grown it)
        self._resident_host: Dict = {}
        #: per-relation incremental-probe state: the outer-spec fingerprint
        #: the running totals were accumulated under, the running device
        #: total and host-oracle expected, and the HOST-sorted outer lane
        #: (the device twin lives in ``self.resident`` under a ("probe",…)
        #: key so it shares the HBM budget and eviction discipline).  Counts
        #: over multisets are additive, so while the outer spec is unchanged
        #: each delta query only counts its Δ — the full-lane probe drops
        #: off the hot path (ops/merge_delta.delta_merge_increment)
        self._resident_probe: Dict = {}
        self.batches_fused = 0          # fused device programs dispatched
        self.batch_queries_fused = 0    # queries served through them
        self._sampler = None            # attached heartbeat, owned if set
        self._closed = False
        #: recent outcomes only (maxlen = service.outcomes_keep): the SLO
        #: recorder is the source of truth for aggregates, so a long-lived
        #: serve worker keeps a bounded window, not its whole history
        self.outcomes: "collections.deque" = collections.deque(
            maxlen=self.service.outcomes_keep)
        #: last N per-query critical paths (observability/critpath.py),
        #: window-sliced from the attached tracer around each executed
        #: query — the ``/statusz`` critical_paths section reads this
        self.recent_critical_paths: "collections.deque" = \
            collections.deque(maxlen=8)

    # ----------------------------------------------------------- admission
    def submit(self, request: QueryRequest) -> None:
        """Admit ``request`` or raise :class:`AdmissionRejected` (already
        SLO-accounted; callers turn it into a rejected outcome via
        :meth:`rejection_outcome`)."""
        if self._closed:
            raise RuntimeError("session is closed")
        try:
            self.queue.submit(request)
        except AdmissionRejected:
            self.slo.record_rejection()
            raise

    def rejection_outcome(self, request: QueryRequest,
                          exc: AdmissionRejected) -> QueryOutcome:
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status="rejected", failure_class=exc.failure_class,
            latency_ms=0.0, breaker_state=self.breaker.state,
            detail=f"{exc.reason}: {exc}")
        self.outcomes.append(out)
        return out

    # ------------------------------------------------------------- serving
    def run_next(self) -> Optional[QueryOutcome]:
        """Execute the oldest admitted query; None when the queue is
        empty.  The tenant's quota slot is released on every outcome
        path.  Consults the fast-path tiers in price order: result cache
        (no execution), delta merge (O(N+Δ)), full engine execution."""
        request = self.queue.pop()
        if request is None:
            return None
        try:
            return self._serve_one(request)
        finally:
            self.queue.done(request)

    def _serve_one(self, request: QueryRequest) -> QueryOutcome:
        hit = self.try_cache(request)
        if hit is not None:
            return hit
        if request.delta_tuples_per_node > 0:
            # incremental query: delta-merge when residency holds the
            # relation, full re-sort otherwise (budget 0 -> every query
            # pays the full sort — the A/B baseline posture)
            return self._execute_delta(request)
        out = self._execute(request)
        self._cache_put(request, out)
        return out

    def drain(self, on_outcome: Optional[Callable] = None,
              batched: Optional[bool] = None) -> List[QueryOutcome]:
        """Serve every admitted query.  ``batched`` (default: whether
        ServiceConfig enables a batch window) groups co-batchable queued
        queries into fused device programs via :meth:`run_next_batch`."""
        if batched is None:
            batched = self.service.batch_window_ms > 0
        outs = []
        while True:
            batch = (self.run_next_batch() if batched
                     else _as_list(self.run_next()))
            if not batch:
                return outs
            for out in batch:
                outs.append(out)
                if on_outcome is not None:
                    on_outcome(out)

    def run_next_batch(self) -> List[QueryOutcome]:
        """Pop the oldest admitted query PLUS every queued query that can
        legally share its fused program (same :func:`batch_signature`, up
        to ``batch_max_queries``) and serve them as one device dispatch.
        Singletons fall through to the normal serving tiers; [] when the
        queue is empty."""
        from tpu_radix_join.service.microbatch import batch_signature
        first = self.queue.pop()
        if first is None:
            return []
        group = [first]
        try:
            if (self.service.batch_window_ms > 0
                    and first.delta_tuples_per_node == 0):
                sig = batch_signature(first)
                group += self.queue.pop_matching(
                    lambda r: (batch_signature(r) == sig
                               and r.delta_tuples_per_node == 0),
                    self.service.batch_max_queries - 1)
            if len(group) == 1:
                return [self._serve_one(first)]
            return self._execute_batched(group)
        finally:
            for request in group:
                self.queue.done(request)

    # ----------------------------------------------------- result cache tier
    def _epoch(self) -> Optional[int]:
        return self.membership.epoch if self.membership is not None else None

    def _content_fp(self, request: QueryRequest) -> str:
        from tpu_radix_join.service.resultcache import content_fingerprint
        return content_fingerprint(
            request, config_fp=dataclasses.asdict(self.config),
            epoch=self._epoch())

    def try_cache(self, request: QueryRequest) -> Optional[QueryOutcome]:
        """Serve ``request`` from the result cache without executing, or
        None on a miss.  Public so callers (the serve loop, the fleet
        supervisor) can short-circuit BEFORE admission — a hit never
        occupies a queue slot or a tenant quota.  Incremental queries
        never cache-serve: their answer depends on session-grown state,
        not the request alone."""
        if (self.result_cache.max_entries == 0
                or request.delta_tuples_per_node > 0):
            return None
        t0 = time.perf_counter()
        payload = self.result_cache.get(self._content_fp(request),
                                        epoch=self._epoch())
        if payload is None:
            return None
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status="ok", failure_class=OK,
            latency_ms=(time.perf_counter() - t0) * 1e3,
            matches=payload.get("matches"), expected=payload.get("expected"),
            engine=payload.get("engine", "primary"),
            warm=True, breaker_state=self.breaker.state,
            detail="result cache hit", served_by="cache_hit")
        self.slo.record(request.tenant, out.latency_ms, ok=True)
        self.outcomes.append(out)
        return out

    def _cache_put(self, request: QueryRequest, out: QueryOutcome) -> None:
        """Store one freshly-executed outcome for future content hits —
        only clean primary successes (a degraded or failed answer is
        evidence about THIS attempt, not the content)."""
        if (self.result_cache.max_entries == 0
                or request.delta_tuples_per_node > 0
                or out.status != "ok" or out.degraded
                or out.matches is None):
            return
        self.result_cache.put(
            self._content_fp(request),
            {"matches": out.matches, "expected": out.expected,
             "engine": out.engine},
            epoch=self._epoch())

    # ------------------------------------------------------ micro-batch tier
    def _host_lanes(self, request: QueryRequest):
        """Host key lanes + exact expected count for one request's
        workload — the serving fast paths run on key lanes through one
        fused program, not the full distributed pipeline, so generation
        stays on host (data/relation.py's bit-identical numpy path)."""
        from tpu_radix_join.data.relation import host_join_count
        inner, outer, expected = self._relations(request)
        r_keys = inner.fill_np(0, inner.global_size)[0]
        s_keys = outer.fill_np(0, outer.global_size)[0]
        if expected is None:
            expected = host_join_count(r_keys, s_keys)
        return r_keys, s_keys, expected, max(inner.key_bound(),
                                             outer.key_bound())

    def _execute_batched(self, group: List[QueryRequest]
                         ) -> List[QueryOutcome]:
        """Serve ``group`` (>= 2 same-signature queries) through ONE fused
        device program (ops/merge_delta.batched_merge_count): Q dispatch
        floors collapse to one, per-query counts stay exact via the
        composite query tag.  Failure isolation: ANY error inside the
        fused path retries the whole group unbatched, one query at a
        time, so a poisoned query classifies alone and its batch-mates
        still succeed."""
        import numpy as np

        from tpu_radix_join.ops.merge_delta import (batch_feasible,
                                                    compiled_batched_merge_count)
        m = self.measurements
        svc = self.service
        t0 = time.perf_counter()
        try:
            lanes = [self._host_lanes(r) for r in group]
            key_bound = max(kb for _, _, _, kb in lanes)
            if not batch_feasible(len(group), key_bound):
                raise ValueError(
                    f"batch of {len(group)} at key_bound {key_bound} "
                    f"overflows the composite word")
            deadlines = []
            for request in group:
                budget = (request.deadline_s if request.deadline_s is not None
                          else svc.default_deadline_s)
                deadline = Deadline(budget, clock=self._clock)
                deadline.check("admitted")
                deadlines.append(deadline)
            r_sizes = tuple(int(rk.shape[0]) for rk, _, _, _ in lanes)
            s_sizes = tuple(int(sk.shape[0]) for _, sk, _, _ in lanes)
            import jax.numpy as jnp
            fn = compiled_batched_merge_count(r_sizes, s_sizes, key_bound)
            r_cat = jnp.asarray(np.concatenate([rk for rk, _, _, _ in lanes]))
            s_cat = jnp.asarray(np.concatenate([sk for _, sk, _, _ in lanes]))
            for _ in range(max(1, group[0].repeats)):
                counts = fn(r_cat, s_cat)
            counts = np.asarray(counts)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:           # noqa: BLE001 — isolation boundary
            if m is not None:
                m.event("batch_fallback", size=len(group),
                        error=repr(e)[:200])
            return [self._serve_one(r) for r in group]
        latency_ms = (time.perf_counter() - t0) * 1e3
        self.batches_fused += 1
        self.batch_queries_fused += len(group)
        if m is not None:
            m.incr(BATCHN)
            m.incr(BATCHQ, len(group))
        outs = []
        for request, (_, _, expected, _), deadline, n in zip(
                group, lanes, deadlines, counts):
            status, cls, detail = "ok", OK, f"fused batch of {len(group)}"
            try:
                deadline.check("batched")
            except DeadlineExceeded as e:
                status, cls, detail = "failed", DEADLINE_EXCEEDED, str(e)
                if m is not None:
                    m.incr(QDEADLINE)
            out = QueryOutcome(
                query_id=request.query_id, tenant=request.tenant,
                status=status, failure_class=cls, latency_ms=latency_ms,
                matches=int(n), expected=int(expected),
                breaker_state=self.breaker.state, detail=detail,
                served_by="batched")
            self.slo.record(request.tenant, latency_ms,
                            ok=(status == "ok"),
                            failure_class=None if cls == OK else cls)
            self.outcomes.append(out)
            if status == "ok":
                self._cache_put(request, out)
            outs.append(out)
        return outs

    # ------------------------------------------------------ delta-merge tier
    def _delta_keys(self, start: int, count: int, seed: int):
        """The Δ new inner keys appended at mirror length ``start`` —
        fresh keys in [start, start+count), deterministically shuffled,
        disjoint from everything the resident union already holds (the
        base is a unique permutation of [0, N), deltas extend it)."""
        import numpy as np

        from tpu_radix_join.ops.merge_delta import MAX_SERVE_KEY
        if start + count > MAX_SERVE_KEY:
            raise ValueError(
                f"resident union would reach {start + count}, past the "
                f"presorted-probe key ceiling {MAX_SERVE_KEY}")
        keys = np.arange(start, start + count, dtype=np.uint32)
        np.random.default_rng(seed + start).shuffle(keys)
        return keys

    def _execute_delta(self, request: QueryRequest) -> QueryOutcome:
        """Serve one incremental query: sort only the Δ delta lane, merge
        it into the device-resident sorted union, probe — O(N+Δ) instead
        of a full re-sort (served_by="delta_merge").  A cold relation
        (first sight, or evicted under the HBM budget) pays one full sort
        and seeds residency for the next delta (served_by="execute")."""
        import numpy as np

        import jax.numpy as jnp
        from tpu_radix_join.data.relation import host_join_count
        from tpu_radix_join.ops.merge_count import (merge_count_presorted,
                                                    presort_keys)
        from tpu_radix_join.ops.merge_delta import (
            compiled_delta_merge_count, compiled_delta_merge_increment)
        m = self.measurements
        svc = self.service
        t0 = time.perf_counter()
        status, cls, detail, served_by = "ok", OK, "", "execute"
        matches = expected = None
        try:
            budget = (request.deadline_s if request.deadline_s is not None
                      else svc.default_deadline_s)
            deadline = Deadline(budget, clock=self._clock)
            deadline.check("admitted")
            inner, outer, _ = self._relations(request)
            nodes = self.config.num_nodes
            delta_n = request.delta_tuples_per_node * nodes
            rkey = ("delta", inner.global_size, request.seed,
                    request.tuples_per_node)
            epoch = self._epoch()
            rprobe = ("probe", inner.global_size, request.seed,
                      request.tuples_per_node)
            outer_fp = (request.outer_kind, request.modulo,
                        request.zipf_theta, request.repeats,
                        outer.global_size)
            lane = self.resident.get(rkey, epoch)
            mirror = self._resident_host.get(rkey)
            if lane is None and mirror is not None:
                # lane evicted under the byte budget but the host mirror
                # survives: rebuild residency with one full sort (and drop
                # the running probe totals — they describe the grown union)
                mirror = None
                self._resident_host.pop(rkey, None)
                self._resident_probe.pop(rkey, None)
            base_len = len(mirror) if mirror is not None else inner.global_size
            delta_np = self._delta_keys(base_len, delta_n, request.seed)
            s_keys = outer.fill_np(0, outer.global_size)[0]
            deadline.check("generated")
            seed_probe = True
            if lane is None:
                base_np = inner.fill_np(0, inner.global_size)[0]
                mirror = np.concatenate([base_np, delta_np])
                union = presort_keys(jnp.asarray(mirror))
                matches = int(merge_count_presorted(union,
                                                    jnp.asarray(s_keys)))
                expected = host_join_count(mirror, s_keys)
                detail = "cold relation: full sort seeded residency"
            else:
                mirror = np.concatenate([mirror, delta_np])
                probe = self._resident_probe.get(rkey)
                s_lane = self.resident.get(rprobe, epoch)
                if (probe is not None and probe["outer_fp"] == outer_fp
                        and probe["union_len"] == base_len
                        and s_lane is not None):
                    # unchanged outer: probe ONLY the Δ against the
                    # resident sorted outer lane; totals are additive over
                    # the multiset union, so the M·log N full-lane probe
                    # (as costly as the re-sort it replaced) never runs
                    fn = compiled_delta_merge_increment(
                        int(lane.shape[0]), int(delta_np.shape[0]),
                        int(s_lane.shape[0]))
                    union, inc = fn(lane, jnp.asarray(delta_np), s_lane)
                    matches = probe["total"] + int(inc)
                    # host oracle stays independent of the device path:
                    # numpy binary search of the Δ in the HOST-sorted outer
                    ds = np.sort(delta_np)
                    sh = probe["s_sorted_host"]
                    expected = probe["expected"] + int(
                        (np.searchsorted(sh, ds, side="right")
                         - np.searchsorted(sh, ds, side="left")).sum())
                    seed_probe = False
                    detail = ("incremental probe: Δ counted against the "
                              "resident sorted outer lane")
                else:
                    fn = compiled_delta_merge_count(int(lane.shape[0]),
                                                    int(delta_np.shape[0]),
                                                    int(s_keys.shape[0]))
                    union, total = fn(lane, jnp.asarray(delta_np),
                                      jnp.asarray(s_keys))
                    matches = int(total)
                    expected = host_join_count(mirror, s_keys)
                self.resident.note_merge(rkey)
                served_by = "delta_merge"
                if m is not None:
                    m.incr(DELTAMERGE)
            deadline.check("merged")
            self.resident.put(rkey, union, epoch)
            self._resident_host[rkey] = mirror
            if seed_probe and self.resident.budget_bytes:
                # (re)seed the incremental-probe state under the same HBM
                # budget; when the outer lane is not admitted (budget too
                # tight) the next query simply pays the full probe.  With
                # residency disabled entirely (budget 0) we must not even
                # sort the outer here — that would tax the full-re-sort
                # baseline with work only the resident tier can use
                s_lane = presort_keys(jnp.asarray(s_keys))
                if self.resident.put(rprobe, s_lane, epoch):
                    self._resident_probe[rkey] = {
                        "outer_fp": outer_fp, "union_len": len(mirror),
                        "total": matches, "expected": expected,
                        "s_sorted_host": np.sort(s_keys)}
                else:
                    self._resident_probe.pop(rkey, None)
            elif not seed_probe:
                probe["union_len"] = len(mirror)
                probe["total"] = matches
                probe["expected"] = expected
        except DeadlineExceeded as e:
            status, cls, detail = "failed", DEADLINE_EXCEEDED, str(e)
            if m is not None:
                m.incr(QDEADLINE)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:           # noqa: BLE001 — isolation boundary
            status = "failed"
            cls = getattr(e, "failure_class", None) or UNCLASSIFIED
            detail = repr(e)[:500]
            if m is not None:
                m.event("query_failed", query_id=request.query_id,
                        failure_class=cls, error=repr(e)[:200])
        latency_ms = (time.perf_counter() - t0) * 1e3
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status=status, failure_class=cls, latency_ms=latency_ms,
            matches=matches, expected=expected,
            breaker_state=self.breaker.state, detail=detail,
            served_by=served_by)
        self.slo.record(request.tenant, latency_ms, ok=(status == "ok"),
                        failure_class=None if cls == OK else cls)
        self.outcomes.append(out)
        return out

    # ------------------------------------------------------------ internals
    def _wire_elastic(self, engine) -> None:
        """Attach the session's elastic-recovery services to an engine
        (primary at construction, CPU fallback on first build) — both
        must agree on membership so a rank loss observed on either path
        fences the same epoch."""
        engine.membership = self.membership
        engine.elastic = self.elastic
        engine.partition_manifest = self.partition_manifest
        engine.elastic_grow = self.elastic_grow
        engine.hedge = self.hedge
        engine.hedge_threshold = self.hedge_threshold

    def _degraded_engine(self):
        """The CPU fallback engine, built once on first use (the breaker's
        open-state serving path — robustness/degrade.py's construction
        recipe, reused here for query-time degradation)."""
        if self._cpu_engine is None:
            from tpu_radix_join.robustness.degrade import build_cpu_engine
            self._cpu_engine, info = build_cpu_engine(
                self.config, measurements=self.measurements,
                plan_cache=self.plan_cache)
            self._wire_elastic(self._cpu_engine)
            m = self.measurements
            if m is not None:
                m.event("degrade", to="cpu", num_nodes=info["num_nodes"],
                        reason="breaker_open")
        return self._cpu_engine

    def _relations(self, request: QueryRequest):
        """(inner, outer, expected) for the request's workload — the same
        construction main.py's one-shot driver uses, sized by the
        *session* config so primary and degraded engines agree on the
        global shape."""
        from tpu_radix_join.data.relation import Relation

        nodes = self.config.num_nodes
        global_size = request.tuples_per_node * nodes
        inner = Relation(global_size, nodes, "unique", seed=request.seed)
        outer_kw = {}
        if request.outer_kind == "modulo":
            outer_kw["modulo"] = request.modulo or max(1, global_size // 4)
        elif request.outer_kind == "zipf":
            outer_kw["zipf_theta"] = request.zipf_theta
            outer_kw["key_domain"] = global_size
        outer = Relation(global_size, nodes, request.outer_kind,
                         seed=request.seed + 1, **outer_kw)
        return inner, outer, inner.expected_matches(outer)

    def _place(self, engine, rel, tag: str, request: QueryRequest):
        """Placed-batch LRU: a resident session re-serving the same
        workload skips generation + host->device transfer."""
        key = (id(engine), tag, rel.global_size, rel.kind, request.seed,
               request.outer_kind, request.modulo, request.zipf_theta)
        if key in self._place_cache:
            self._place_cache.move_to_end(key)
            return self._place_cache[key]
        batch = engine.place(rel)
        self._place_cache[key] = batch
        while len(self._place_cache) > self.service.place_cache_max:
            self._place_cache.popitem(last=False)
        return batch

    def placed_bytes(self) -> int:
        """Device bytes held by the placed-relation LRU (key + rid + wide
        lanes of every cached batch) — the heartbeat/statusz gauge that
        makes the ``place_cache_max`` knob observable."""
        total = 0
        for batch in self._place_cache.values():
            for lane in batch:
                if lane is not None and hasattr(lane, "nbytes"):
                    total += int(lane.nbytes)
        return total

    def _execute(self, request: QueryRequest) -> QueryOutcome:
        m = self.measurements
        svc = self.service
        budget = (request.deadline_s if request.deadline_s is not None
                  else svc.default_deadline_s)
        deadline = Deadline(budget, clock=self._clock)
        primary = self.breaker.allow_primary()
        probing = primary and self.breaker.state == HALF_OPEN
        engine = self.engine if primary else self._degraded_engine()
        tracer = m.tracer if m is not None else None
        win0_us = tracer.now_us() if tracer is not None else None
        t0 = time.perf_counter()
        jhist0 = m.times_us.get(JHIST, 0.0) if m is not None else 0.0
        nc0 = m.counters.get(NCOMPILE, 0) if m is not None else 0
        completed_before = self.slo.completed
        span = (m.span("query", query_id=request.query_id,
                       tenant=request.tenant,
                       engine="primary" if primary else "cpu_fallback",
                       probe=probing)
                if m is not None else _null_ctx())
        engine.cancel = deadline.check
        if m is not None:
            # every ring record and counter delta inside this query carries
            # the query_id: a bundle cut mid-serve attributes its evidence
            m.flightrec.set_context(query_id=request.query_id,
                                    tenant=request.tenant)
        status, cls, detail = "ok", OK, ""
        matches = expected = None
        try:
            with span:
                if primary and _faults.fires(_faults.BACKEND_DISPATCH, m):
                    # injectable per-query tunnel outage (chaos / tests):
                    # the production twin is the except-clause mapping of
                    # raw connection errors below
                    raise BackendUnavailable(
                        f"injected backend outage (query "
                        f"{request.query_id})")
                deadline.check("admitted")
                inner, outer, expected = self._relations(request)
                deadline.check("generated")
                r_batch = self._place(engine, inner, "r", request)
                s_batch = self._place(engine, outer, "s", request)
                deadline.check("placed")
                result = engine.join_arrays(r_batch, s_batch,
                                            repeats=request.repeats)
                matches = result.matches
                cls = (result.diagnostics or {}).get(
                    "failure_class") or (OK if result.ok else UNCLASSIFIED)
                status = "ok" if result.ok else "failed"
                if (result.diagnostics or {}).get("recovered"):
                    # a mid-query rank loss was absorbed by the elastic
                    # path: the outcome is ok with the exact count, but
                    # the mesh change is first-class evidence
                    if m is not None:
                        m.event("query_recovered",
                                query_id=request.query_id,
                                epoch=result.diagnostics.get(
                                    "membership_epoch"),
                                lost_ranks=result.diagnostics.get(
                                    "lost_ranks"))
                    detail = ("recovered from rank loss: "
                              + str(result.diagnostics.get(
                                    "lost_ranks")))[:500]
                if status == "failed":
                    detail = str({k: v for k, v in
                                  (result.diagnostics or {}).items()
                                  if k != "failure_class"})[:500]
        except DeadlineExceeded as e:
            status, cls, detail = "failed", DEADLINE_EXCEEDED, str(e)
            if m is not None:
                m.incr(QDEADLINE)
        except (KeyboardInterrupt, SystemExit):
            raise                        # the operator's kill stays a kill
        except Exception as e:           # noqa: BLE001 — isolation boundary
            status = "failed"
            cls = getattr(e, "failure_class", None)
            if cls is None and isinstance(
                    e, (ConnectionError, TimeoutError, OSError)):
                # a raw transport error from a dead tunnel is the
                # production form of backend_unavailable
                cls = BACKEND_UNAVAILABLE
            if cls is None:
                cls = UNCLASSIFIED
            detail = repr(e)[:500]
            if m is not None:
                m.event("query_failed", query_id=request.query_id,
                        failure_class=cls, error=repr(e)[:200])
        finally:
            engine.cancel = None
        latency_ms = (time.perf_counter() - t0) * 1e3
        trips0 = self.breaker.trips
        # warm = the sizing pre-pass did not run this query (plan-cache /
        # hot-layer capacity hit): the observable the acceptance criteria
        # gate on, measured from the JHIST column's delta
        warm = (status == "ok" and m is not None
                and m.times_us.get(JHIST, 0.0) == jhist0
                and self.slo.completed > 0)
        if m is not None:
            if warm:
                m.incr(QWARM)
            if not primary:
                m.incr(QDEGRADED)
        if primary:
            if cls == OK:
                self.breaker.record_success()
            else:
                self.breaker.record_failure(cls)
        bundle = None
        if status == "failed" and self.forensics_dir:
            reason = ("breaker_trip" if self.breaker.trips > trips0
                      else ("deadline_exceeded" if cls == DEADLINE_EXCEEDED
                            else "query_failed"))
            bundle = self._write_bundle(request, reason, cls, detail)
        # recompile-storm canary: NCOMPILE rising after the session has
        # completed queries means XLA is recompiling warm shapes — the
        # amortization win a resident session exists for is leaking
        nc_delta = (m.counters.get(NCOMPILE, 0) - nc0) if m is not None else 0
        if nc_delta and completed_before > 0:
            self._recompile_storms += 1
            if m is not None:
                m.event("recompile_storm", query_id=request.query_id,
                        ncompile_delta=nc_delta,
                        completed=completed_before)
            if self._recompile_storms <= 3:      # warn loudly, don't spam
                print(f"[OBS] recompile storm: query {request.query_id} "
                      f"triggered {nc_delta} backend compile(s) after "
                      f"{completed_before} completed queries",
                      file=sys.stderr)
        if m is not None:
            m.flightrec.clear_context("query_id", "tenant")
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status=status, failure_class=cls, latency_ms=latency_ms,
            matches=matches, expected=expected,
            engine="primary" if primary else "cpu_fallback",
            degraded=not primary, warm=warm,
            breaker_state=self.breaker.state, detail=detail,
            bundle=bundle)
        self.slo.record(request.tenant, latency_ms, ok=(status == "ok"),
                        failure_class=None if cls == OK else cls,
                        degraded=not primary)
        self.outcomes.append(out)
        if tracer is not None:
            # per-query critical path: slice this query's window out of
            # the resident tracer stream so each query gets its own
            # attribution (read by /statusz; a path failure is evidence,
            # never a new failure for the query)
            try:
                from tpu_radix_join.observability.critpath import (
                    critical_path_from_tracer)
                cp = critical_path_from_tracer(
                    tracer, window_us=(win0_us, tracer.now_us()))
                cp["query_id"] = request.query_id
                self.recent_critical_paths.append(cp)
            except Exception as e:   # noqa: BLE001 — isolation boundary
                m.event("critpath_error", error=repr(e)[:200])
        if self.ledger is not None:
            # one ledger row per executed query; a ledger write failure is
            # an event, never a new failure for the query
            try:
                self.ledger.append("query", {
                    "query_id": request.query_id, "tenant": request.tenant,
                    "trace_id": (m.meta.get("trace_id")
                                 if m is not None else None),
                    "status": status, "failure_class": cls,
                    "latency_ms": round(latency_ms, 3),
                    "warm": warm, "engine": out.engine,
                    "tuples_per_node": request.tuples_per_node,
                    "repeats": request.repeats,
                    "ncompile": nc_delta or None})
            except Exception as e:   # noqa: BLE001 — isolation boundary
                if m is not None:
                    m.event("ledger_error", error=repr(e)[:200])
        return out

    def _write_bundle(self, request: QueryRequest, reason: str,
                      cls: str, detail: str) -> Optional[str]:
        """Forensics bundle for one failed query.  Must never escalate:
        a bundle-write error is an event on the registry, not a new
        failure for the query (the isolation boundary stays sealed)."""
        try:
            from tpu_radix_join.observability.postmortem import write_bundle
            return write_bundle(
                self.forensics_dir, self.measurements, reason=reason,
                failure_class=cls, config=self.config,
                extra={"query_id": request.query_id,
                       "tenant": request.tenant,
                       "breaker_state": self.breaker.state,
                       "detail": detail})
        except Exception as e:     # noqa: BLE001 — forensics must not mask
            if self.measurements is not None:
                self.measurements.event("bundle_error", error=repr(e)[:200])
            return None

    # ----------------------------------------------------------- lifecycle
    def attach_heartbeat(self, path: str, interval_s: float):
        """Start a metrics heartbeat owned by this session (stopped by
        :meth:`close`): every tick carries the SLO snapshot next to the
        counter registry, so ``tail -f`` shows live percentiles."""
        from tpu_radix_join.observability import MetricsSampler
        self._sampler = MetricsSampler(path, interval_s,
                                       measurements=self.measurements,
                                       extra=self._heartbeat_extra)
        self._sampler.start()
        return self._sampler

    def fastpath_stats(self) -> dict:
        """Live fast-path state for ``/statusz``: result-cache hit rates,
        residency bytes, and fused-batch totals (the serve loop's
        MicroBatcher contributes window occupancy on top)."""
        return {"cache": self.result_cache.stats(),
                "resident": self.resident.stats(),
                "batch": {"fused_batches": self.batches_fused,
                          "fused_queries": self.batch_queries_fused},
                "placed_bytes": self.placed_bytes(),
                "place_cache_entries": len(self._place_cache),
                "place_cache_max": self.service.place_cache_max}

    def _heartbeat_extra(self) -> dict:
        out = {"slo": self.slo.snapshot(),
               "breaker": self.breaker.snapshot(),
               "queue_depth": self.queue.depth(),
               "placed_bytes": self.placed_bytes()}
        if self.result_cache.max_entries:
            out["result_cache"] = self.result_cache.stats()
        if self.resident.budget_bytes:
            out["resident"] = self.resident.stats()
        if self.membership is not None:
            out["membership"] = {"epoch": self.membership.epoch,
                                 "lost": sorted(self.membership.lost),
                                 "survivors": self.membership.survivors}
        return out

    def summary(self) -> dict:
        """Final serve report: SLO tags + breaker/queue/cache state."""
        out = self.slo.snapshot()
        out.update(breaker_state=self.breaker.state,
                   breaker_trips=self.breaker.trips,
                   breaker_probes=self.breaker.probes,
                   queue_rejected=self.queue.rejected,
                   placed_bytes=self.placed_bytes())
        if self.result_cache.max_entries:
            cache = self.result_cache.stats()
            out["cache_hits"] = cache["hits"]
            out["cache_hit_rate"] = cache["hit_rate"]
        if self.batches_fused:
            out["fused_batches"] = self.batches_fused
            out["fused_queries"] = self.batch_queries_fused
        if self.resident.budget_bytes:
            res = self.resident.stats()
            out["resident_bytes"] = res["resident_bytes"]
            out["delta_merges"] = res["merges"]
        m = self.measurements
        if m is not None:
            out["warm_queries"] = int(m.counters.get(QWARM, 0))
            out["degraded_queries"] = int(m.counters.get(QDEGRADED, 0))
            out["ncompile"] = int(m.counters.get(NCOMPILE, 0))
            out["compile_ms"] = int(m.counters.get(COMPILEMS, 0))
            out["recompile_storms"] = self._recompile_storms
            if m.counters.get(RANKLOST):
                out["ranks_lost"] = int(m.counters.get(RANKLOST, 0))
                out["membership_epoch"] = int(m.counters.get(MEPOCH, 0))
                out["recovered_partitions"] = int(m.counters.get(RECOVERN, 0))
                out["recover_ms"] = int(m.counters.get(RECOVERMS, 0))
        return out

    def close(self) -> None:
        """Release everything the session owns: the heartbeat sampler
        thread, placed-batch device references, and the engines' compile
        caches.  Idempotent; the session refuses new submissions after."""
        if self._closed:
            return
        self._closed = True
        if self._sampler is not None:
            self._sampler.stop()
            self._sampler = None
        self._place_cache.clear()
        self.result_cache.invalidate()
        self.resident.invalidate()
        self._resident_host.clear()
        self._resident_probe.clear()
        for eng in (self.engine, self._cpu_engine):
            if eng is not None:
                eng._compiled.clear()
        self._cpu_engine = None
        if self._cache_tmp is not None:
            self._cache_tmp.cleanup()
            self._cache_tmp = None

    def __enter__(self) -> "JoinSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()


def _as_list(out: Optional[QueryOutcome]) -> List[QueryOutcome]:
    return [out] if out is not None else []
