"""JoinSession: the resident, admission-controlled join service.

The one-shot driver (main.py) pays mesh bring-up, XLA compilation, the
JHIST sizing pre-pass, and a ~5-8 ms dispatch tunnel round-trip on EVERY
invocation, and a backend outage mid-run can only be reported, not
absorbed.  A :class:`JoinSession` keeps all of that warm across many
queries:

  * the **mesh and compiled executables** — ``HashJoin`` caches compiled
    programs per (shape, capacity) key, so same-shape queries after the
    first skip compilation entirely;
  * the **plan cache** (planner/cache.py) — the first query's converged
    window capacities warm-start every later same-shape query past the
    sizing pre-pass (no JHIST dispatch), via the cache's new in-process
    hot layer;
  * **placed relations** — a small LRU of device-resident inputs, so the
    closed-loop bench's repeated workloads skip generation + transfer.

In front of the engine sit the robustness pieces this module composes
(each one classified, none of them able to take the session down):

  * :class:`~tpu_radix_join.service.admission.AdmissionQueue` — bounded
    depth + per-tenant quotas -> ``admission_rejected``;
  * :class:`~tpu_radix_join.service.deadline.Deadline` — per-query
    budgets enforced cooperatively between phases (the engine's
    ``cancel`` hook) -> ``deadline_exceeded``;
  * :class:`~tpu_radix_join.service.breaker.CircuitBreaker` — consecutive
    backend failures trip the session onto the degraded CPU engine
    (robustness/degrade.py machinery); half-open probes recover it;
  * per-query **failure isolation** — every exception inside a query is
    caught, classified via the ``failure_class`` taxonomy, and turned
    into a :class:`QueryOutcome`; only session-construction errors and
    interrupts propagate.

``main.py --serve`` feeds it from a JSONL request file; ``bench.py
--serve-bench`` closes the loop and gates the SLO tags.
"""

from __future__ import annotations

import collections
import dataclasses
import sys
import time
from typing import Callable, Dict, List, Optional

from tpu_radix_join.core.config import JoinConfig, ServiceConfig
from tpu_radix_join.performance.measurements import (COMPILEMS, JHIST,
                                                     MEPOCH, NCOMPILE,
                                                     QDEADLINE, QDEGRADED,
                                                     QWARM, RANKLOST,
                                                     RECOVERMS, RECOVERN)
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness.retry import (BACKEND_UNAVAILABLE,
                                             DEADLINE_EXCEEDED, OK)
from tpu_radix_join.service.admission import AdmissionQueue, AdmissionRejected
from tpu_radix_join.service.breaker import HALF_OPEN, CircuitBreaker
from tpu_radix_join.service.deadline import Deadline, DeadlineExceeded
from tpu_radix_join.service.slo import SLORecorder

#: unclassified-exception sentinel: a query that dies without a
#: failure_class still yields a terminal outcome (the session survives),
#: but chaos/soak treats this string as an isolation violation
UNCLASSIFIED = "unclassified"

_PLACE_CACHE_MAX = 8     # placed-relation LRU entries (device memory bound)


class BackendUnavailable(ConnectionError):
    """The chip backend failed a query-time dispatch (tunnel outage)."""

    failure_class = BACKEND_UNAVAILABLE


@dataclasses.dataclass(frozen=True)
class QueryRequest:
    """One join request as the serve loop admits it (JSONL line shape)."""

    query_id: str
    tenant: str = "default"
    tuples_per_node: int = 1 << 16
    outer_kind: str = "unique"          # unique | modulo | zipf
    modulo: Optional[int] = None
    zipf_theta: float = 0.75
    seed: int = 1234
    repeats: int = 1
    deadline_s: Optional[float] = None  # None -> ServiceConfig default

    @classmethod
    def from_json(cls, obj: dict) -> "QueryRequest":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(obj) - fields
        if unknown:
            raise ValueError(f"unknown request fields: {sorted(unknown)}")
        if "query_id" not in obj:
            raise ValueError("request needs a query_id")
        return cls(**obj)


@dataclasses.dataclass
class QueryOutcome:
    """Terminal, classified verdict for one submitted query."""

    query_id: str
    tenant: str
    status: str                     # ok | failed | rejected
    failure_class: str              # "ok" when status == "ok"
    latency_ms: float
    matches: Optional[int] = None
    expected: Optional[int] = None
    engine: str = "primary"         # primary | cpu_fallback
    degraded: bool = False
    warm: bool = False              # sizing pre-pass skipped (cache hit)
    breaker_state: str = "closed"
    detail: str = ""
    bundle: Optional[str] = None    # forensics bundle path, failed queries

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["latency_ms"] = round(self.latency_ms, 3)
        if out.get("bundle") is None:
            # successful queries keep the pre-forensics line shape
            out.pop("bundle", None)
        return out


class JoinSession:
    """Resident engine + admission queue + breaker + SLO accounting.

    Single-threaded by design: one mesh, one query at a time (the
    micro-batching direction in ROADMAP item 1 layers onto this API).
    Construction builds the primary engine once; ``submit``/``run_next``/
    ``drain`` serve queries; ``close`` releases everything the session
    owns (and is idempotent).
    """

    def __init__(self, config: JoinConfig,
                 service: Optional[ServiceConfig] = None,
                 measurements=None, plan_cache=None, profile: str = "v5e_lite",
                 clock: Callable[[], float] = time.monotonic,
                 forensics_dir: Optional[str] = None,
                 ledger=None, membership=None, elastic: bool = False,
                 partition_manifest=None, elastic_grow: bool = False,
                 hedge: str = "off", hedge_threshold: float = 0.5):
        from tpu_radix_join.operators.hash_join import HashJoin

        self.config = config
        #: elastic mesh recovery services (robustness/membership +
        #: checkpoint.PartitionManifest), threaded onto every engine the
        #: session builds: the session SURVIVES a mesh change — a
        #: mid-query rank loss recovers inside join_arrays (classified
        #: ``recovered`` diagnostics, exact count), later queries compile
        #: against the new epoch (the engine's compile keys and capacity
        #: fingerprints carry it), and the breaker keeps serving —
        #: degraded if it was already open — instead of the whole session
        #: dying with the rank
        self.membership = membership
        self.elastic = elastic
        self.partition_manifest = partition_manifest
        #: growth + hedging posture, threaded like membership: a session
        #: can admit ranks (elastic_grow) and speculate on stragglers
        #: (hedge/hedge_threshold) on any engine it builds
        self.elastic_grow = elastic_grow
        self.hedge = hedge
        self.hedge_threshold = hedge_threshold
        self.service = service or ServiceConfig()
        self.measurements = measurements
        #: cross-run telemetry ledger (observability/ledger.py): when set,
        #: every executed query appends one ``kind="query"`` row — the
        #: per-query evidence stream a one-shot driver can't produce
        self.ledger = ledger
        self._recompile_storms = 0
        #: when set, every executed-and-failed query (deadline expiry,
        #: backend outage, breaker trip, corruption) drops a forensics
        #: bundle here (observability/postmortem.py), stamped with the
        #: query_id the flight-recorder context carried during the query
        self.forensics_dir = forensics_dir
        self._cache_tmp = None
        if plan_cache is None:
            # a resident session warms by default: without a caller-provided
            # cache dir, own an ephemeral one (first same-shape query pays
            # the sizing pre-pass, every later one skips it via the hot
            # layer; the tempdir dies with the session)
            import tempfile

            from tpu_radix_join.planner import PlanCache, load_profile
            self._cache_tmp = tempfile.TemporaryDirectory(
                prefix="join_session_plan_cache_")
            plan_cache = PlanCache(self._cache_tmp.name,
                                   load_profile(profile),
                                   measurements=measurements)
        self.plan_cache = plan_cache
        self._clock = clock
        self.queue = AdmissionQueue(self.service.max_queue_depth,
                                    self.service.tenant_quota,
                                    measurements=measurements)
        self.breaker = CircuitBreaker(self.service.breaker_threshold,
                                      self.service.breaker_cooldown_s,
                                      clock=clock,
                                      measurements=measurements)
        self.slo = SLORecorder()
        self.engine = HashJoin(config, measurements=measurements,
                               plan_cache=plan_cache)
        self._wire_elastic(self.engine)
        self._cpu_engine = None         # built lazily on first open-state query
        self._place_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._sampler = None            # attached heartbeat, owned if set
        self._closed = False
        #: recent outcomes only (maxlen = service.outcomes_keep): the SLO
        #: recorder is the source of truth for aggregates, so a long-lived
        #: serve worker keeps a bounded window, not its whole history
        self.outcomes: "collections.deque" = collections.deque(
            maxlen=self.service.outcomes_keep)
        #: last N per-query critical paths (observability/critpath.py),
        #: window-sliced from the attached tracer around each executed
        #: query — the ``/statusz`` critical_paths section reads this
        self.recent_critical_paths: "collections.deque" = \
            collections.deque(maxlen=8)

    # ----------------------------------------------------------- admission
    def submit(self, request: QueryRequest) -> None:
        """Admit ``request`` or raise :class:`AdmissionRejected` (already
        SLO-accounted; callers turn it into a rejected outcome via
        :meth:`rejection_outcome`)."""
        if self._closed:
            raise RuntimeError("session is closed")
        try:
            self.queue.submit(request)
        except AdmissionRejected:
            self.slo.record_rejection()
            raise

    def rejection_outcome(self, request: QueryRequest,
                          exc: AdmissionRejected) -> QueryOutcome:
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status="rejected", failure_class=exc.failure_class,
            latency_ms=0.0, breaker_state=self.breaker.state,
            detail=f"{exc.reason}: {exc}")
        self.outcomes.append(out)
        return out

    # ------------------------------------------------------------- serving
    def run_next(self) -> Optional[QueryOutcome]:
        """Execute the oldest admitted query; None when the queue is
        empty.  The tenant's quota slot is released on every outcome
        path."""
        request = self.queue.pop()
        if request is None:
            return None
        try:
            return self._execute(request)
        finally:
            self.queue.done(request)

    def drain(self, on_outcome: Optional[Callable] = None
              ) -> List[QueryOutcome]:
        outs = []
        while True:
            out = self.run_next()
            if out is None:
                return outs
            outs.append(out)
            if on_outcome is not None:
                on_outcome(out)

    # ------------------------------------------------------------ internals
    def _wire_elastic(self, engine) -> None:
        """Attach the session's elastic-recovery services to an engine
        (primary at construction, CPU fallback on first build) — both
        must agree on membership so a rank loss observed on either path
        fences the same epoch."""
        engine.membership = self.membership
        engine.elastic = self.elastic
        engine.partition_manifest = self.partition_manifest
        engine.elastic_grow = self.elastic_grow
        engine.hedge = self.hedge
        engine.hedge_threshold = self.hedge_threshold

    def _degraded_engine(self):
        """The CPU fallback engine, built once on first use (the breaker's
        open-state serving path — robustness/degrade.py's construction
        recipe, reused here for query-time degradation)."""
        if self._cpu_engine is None:
            from tpu_radix_join.robustness.degrade import build_cpu_engine
            self._cpu_engine, info = build_cpu_engine(
                self.config, measurements=self.measurements,
                plan_cache=self.plan_cache)
            self._wire_elastic(self._cpu_engine)
            m = self.measurements
            if m is not None:
                m.event("degrade", to="cpu", num_nodes=info["num_nodes"],
                        reason="breaker_open")
        return self._cpu_engine

    def _relations(self, request: QueryRequest):
        """(inner, outer, expected) for the request's workload — the same
        construction main.py's one-shot driver uses, sized by the
        *session* config so primary and degraded engines agree on the
        global shape."""
        from tpu_radix_join.data.relation import Relation

        nodes = self.config.num_nodes
        global_size = request.tuples_per_node * nodes
        inner = Relation(global_size, nodes, "unique", seed=request.seed)
        outer_kw = {}
        if request.outer_kind == "modulo":
            outer_kw["modulo"] = request.modulo or max(1, global_size // 4)
        elif request.outer_kind == "zipf":
            outer_kw["zipf_theta"] = request.zipf_theta
            outer_kw["key_domain"] = global_size
        outer = Relation(global_size, nodes, request.outer_kind,
                         seed=request.seed + 1, **outer_kw)
        return inner, outer, inner.expected_matches(outer)

    def _place(self, engine, rel, tag: str, request: QueryRequest):
        """Placed-batch LRU: a resident session re-serving the same
        workload skips generation + host->device transfer."""
        key = (id(engine), tag, rel.global_size, rel.kind, request.seed,
               request.outer_kind, request.modulo, request.zipf_theta)
        if key in self._place_cache:
            self._place_cache.move_to_end(key)
            return self._place_cache[key]
        batch = engine.place(rel)
        self._place_cache[key] = batch
        while len(self._place_cache) > _PLACE_CACHE_MAX:
            self._place_cache.popitem(last=False)
        return batch

    def _execute(self, request: QueryRequest) -> QueryOutcome:
        m = self.measurements
        svc = self.service
        budget = (request.deadline_s if request.deadline_s is not None
                  else svc.default_deadline_s)
        deadline = Deadline(budget, clock=self._clock)
        primary = self.breaker.allow_primary()
        probing = primary and self.breaker.state == HALF_OPEN
        engine = self.engine if primary else self._degraded_engine()
        tracer = m.tracer if m is not None else None
        win0_us = tracer.now_us() if tracer is not None else None
        t0 = time.perf_counter()
        jhist0 = m.times_us.get(JHIST, 0.0) if m is not None else 0.0
        nc0 = m.counters.get(NCOMPILE, 0) if m is not None else 0
        completed_before = self.slo.completed
        span = (m.span("query", query_id=request.query_id,
                       tenant=request.tenant,
                       engine="primary" if primary else "cpu_fallback",
                       probe=probing)
                if m is not None else _null_ctx())
        engine.cancel = deadline.check
        if m is not None:
            # every ring record and counter delta inside this query carries
            # the query_id: a bundle cut mid-serve attributes its evidence
            m.flightrec.set_context(query_id=request.query_id,
                                    tenant=request.tenant)
        status, cls, detail = "ok", OK, ""
        matches = expected = None
        try:
            with span:
                if primary and _faults.fires(_faults.BACKEND_DISPATCH, m):
                    # injectable per-query tunnel outage (chaos / tests):
                    # the production twin is the except-clause mapping of
                    # raw connection errors below
                    raise BackendUnavailable(
                        f"injected backend outage (query "
                        f"{request.query_id})")
                deadline.check("admitted")
                inner, outer, expected = self._relations(request)
                deadline.check("generated")
                r_batch = self._place(engine, inner, "r", request)
                s_batch = self._place(engine, outer, "s", request)
                deadline.check("placed")
                result = engine.join_arrays(r_batch, s_batch,
                                            repeats=request.repeats)
                matches = result.matches
                cls = (result.diagnostics or {}).get(
                    "failure_class") or (OK if result.ok else UNCLASSIFIED)
                status = "ok" if result.ok else "failed"
                if (result.diagnostics or {}).get("recovered"):
                    # a mid-query rank loss was absorbed by the elastic
                    # path: the outcome is ok with the exact count, but
                    # the mesh change is first-class evidence
                    if m is not None:
                        m.event("query_recovered",
                                query_id=request.query_id,
                                epoch=result.diagnostics.get(
                                    "membership_epoch"),
                                lost_ranks=result.diagnostics.get(
                                    "lost_ranks"))
                    detail = ("recovered from rank loss: "
                              + str(result.diagnostics.get(
                                    "lost_ranks")))[:500]
                if status == "failed":
                    detail = str({k: v for k, v in
                                  (result.diagnostics or {}).items()
                                  if k != "failure_class"})[:500]
        except DeadlineExceeded as e:
            status, cls, detail = "failed", DEADLINE_EXCEEDED, str(e)
            if m is not None:
                m.incr(QDEADLINE)
        except (KeyboardInterrupt, SystemExit):
            raise                        # the operator's kill stays a kill
        except Exception as e:           # noqa: BLE001 — isolation boundary
            status = "failed"
            cls = getattr(e, "failure_class", None)
            if cls is None and isinstance(
                    e, (ConnectionError, TimeoutError, OSError)):
                # a raw transport error from a dead tunnel is the
                # production form of backend_unavailable
                cls = BACKEND_UNAVAILABLE
            if cls is None:
                cls = UNCLASSIFIED
            detail = repr(e)[:500]
            if m is not None:
                m.event("query_failed", query_id=request.query_id,
                        failure_class=cls, error=repr(e)[:200])
        finally:
            engine.cancel = None
        latency_ms = (time.perf_counter() - t0) * 1e3
        trips0 = self.breaker.trips
        # warm = the sizing pre-pass did not run this query (plan-cache /
        # hot-layer capacity hit): the observable the acceptance criteria
        # gate on, measured from the JHIST column's delta
        warm = (status == "ok" and m is not None
                and m.times_us.get(JHIST, 0.0) == jhist0
                and self.slo.completed > 0)
        if m is not None:
            if warm:
                m.incr(QWARM)
            if not primary:
                m.incr(QDEGRADED)
        if primary:
            if cls == OK:
                self.breaker.record_success()
            else:
                self.breaker.record_failure(cls)
        bundle = None
        if status == "failed" and self.forensics_dir:
            reason = ("breaker_trip" if self.breaker.trips > trips0
                      else ("deadline_exceeded" if cls == DEADLINE_EXCEEDED
                            else "query_failed"))
            bundle = self._write_bundle(request, reason, cls, detail)
        # recompile-storm canary: NCOMPILE rising after the session has
        # completed queries means XLA is recompiling warm shapes — the
        # amortization win a resident session exists for is leaking
        nc_delta = (m.counters.get(NCOMPILE, 0) - nc0) if m is not None else 0
        if nc_delta and completed_before > 0:
            self._recompile_storms += 1
            if m is not None:
                m.event("recompile_storm", query_id=request.query_id,
                        ncompile_delta=nc_delta,
                        completed=completed_before)
            if self._recompile_storms <= 3:      # warn loudly, don't spam
                print(f"[OBS] recompile storm: query {request.query_id} "
                      f"triggered {nc_delta} backend compile(s) after "
                      f"{completed_before} completed queries",
                      file=sys.stderr)
        if m is not None:
            m.flightrec.clear_context("query_id", "tenant")
        out = QueryOutcome(
            query_id=request.query_id, tenant=request.tenant,
            status=status, failure_class=cls, latency_ms=latency_ms,
            matches=matches, expected=expected,
            engine="primary" if primary else "cpu_fallback",
            degraded=not primary, warm=warm,
            breaker_state=self.breaker.state, detail=detail,
            bundle=bundle)
        self.slo.record(request.tenant, latency_ms, ok=(status == "ok"),
                        failure_class=None if cls == OK else cls,
                        degraded=not primary)
        self.outcomes.append(out)
        if tracer is not None:
            # per-query critical path: slice this query's window out of
            # the resident tracer stream so each query gets its own
            # attribution (read by /statusz; a path failure is evidence,
            # never a new failure for the query)
            try:
                from tpu_radix_join.observability.critpath import (
                    critical_path_from_tracer)
                cp = critical_path_from_tracer(
                    tracer, window_us=(win0_us, tracer.now_us()))
                cp["query_id"] = request.query_id
                self.recent_critical_paths.append(cp)
            except Exception as e:   # noqa: BLE001 — isolation boundary
                m.event("critpath_error", error=repr(e)[:200])
        if self.ledger is not None:
            # one ledger row per executed query; a ledger write failure is
            # an event, never a new failure for the query
            try:
                self.ledger.append("query", {
                    "query_id": request.query_id, "tenant": request.tenant,
                    "trace_id": (m.meta.get("trace_id")
                                 if m is not None else None),
                    "status": status, "failure_class": cls,
                    "latency_ms": round(latency_ms, 3),
                    "warm": warm, "engine": out.engine,
                    "tuples_per_node": request.tuples_per_node,
                    "repeats": request.repeats,
                    "ncompile": nc_delta or None})
            except Exception as e:   # noqa: BLE001 — isolation boundary
                if m is not None:
                    m.event("ledger_error", error=repr(e)[:200])
        return out

    def _write_bundle(self, request: QueryRequest, reason: str,
                      cls: str, detail: str) -> Optional[str]:
        """Forensics bundle for one failed query.  Must never escalate:
        a bundle-write error is an event on the registry, not a new
        failure for the query (the isolation boundary stays sealed)."""
        try:
            from tpu_radix_join.observability.postmortem import write_bundle
            return write_bundle(
                self.forensics_dir, self.measurements, reason=reason,
                failure_class=cls, config=self.config,
                extra={"query_id": request.query_id,
                       "tenant": request.tenant,
                       "breaker_state": self.breaker.state,
                       "detail": detail})
        except Exception as e:     # noqa: BLE001 — forensics must not mask
            if self.measurements is not None:
                self.measurements.event("bundle_error", error=repr(e)[:200])
            return None

    # ----------------------------------------------------------- lifecycle
    def attach_heartbeat(self, path: str, interval_s: float):
        """Start a metrics heartbeat owned by this session (stopped by
        :meth:`close`): every tick carries the SLO snapshot next to the
        counter registry, so ``tail -f`` shows live percentiles."""
        from tpu_radix_join.observability import MetricsSampler
        self._sampler = MetricsSampler(path, interval_s,
                                       measurements=self.measurements,
                                       extra=self._heartbeat_extra)
        self._sampler.start()
        return self._sampler

    def _heartbeat_extra(self) -> dict:
        out = {"slo": self.slo.snapshot(),
               "breaker": self.breaker.snapshot(),
               "queue_depth": self.queue.depth()}
        if self.membership is not None:
            out["membership"] = {"epoch": self.membership.epoch,
                                 "lost": sorted(self.membership.lost),
                                 "survivors": self.membership.survivors}
        return out

    def summary(self) -> dict:
        """Final serve report: SLO tags + breaker/queue/cache state."""
        out = self.slo.snapshot()
        out.update(breaker_state=self.breaker.state,
                   breaker_trips=self.breaker.trips,
                   breaker_probes=self.breaker.probes,
                   queue_rejected=self.queue.rejected)
        m = self.measurements
        if m is not None:
            out["warm_queries"] = int(m.counters.get(QWARM, 0))
            out["degraded_queries"] = int(m.counters.get(QDEGRADED, 0))
            out["ncompile"] = int(m.counters.get(NCOMPILE, 0))
            out["compile_ms"] = int(m.counters.get(COMPILEMS, 0))
            out["recompile_storms"] = self._recompile_storms
            if m.counters.get(RANKLOST):
                out["ranks_lost"] = int(m.counters.get(RANKLOST, 0))
                out["membership_epoch"] = int(m.counters.get(MEPOCH, 0))
                out["recovered_partitions"] = int(m.counters.get(RECOVERN, 0))
                out["recover_ms"] = int(m.counters.get(RECOVERMS, 0))
        return out

    def close(self) -> None:
        """Release everything the session owns: the heartbeat sampler
        thread, placed-batch device references, and the engines' compile
        caches.  Idempotent; the session refuses new submissions after."""
        if self._closed:
            return
        self._closed = True
        if self._sampler is not None:
            self._sampler.stop()
            self._sampler = None
        self._place_cache.clear()
        for eng in (self.engine, self._cpu_engine):
            if eng is not None:
                eng._compiled.clear()
        self._cpu_engine = None
        if self._cache_tmp is not None:
            self._cache_tmp.cleanup()
            self._cache_tmp = None

    def __enter__(self) -> "JoinSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()
