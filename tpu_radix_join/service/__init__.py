"""Resident join service: admission-controlled sessions with deadlines,
a backend circuit breaker, and per-query failure isolation.

Public surface:

  * :class:`JoinSession` / :class:`QueryRequest` / :class:`QueryOutcome`
    — the resident engine and its per-query verdicts (session.py);
  * :class:`AdmissionQueue` / :class:`AdmissionRejected` — the bounded,
    per-tenant front door (admission.py);
  * :class:`Deadline` / :class:`DeadlineExceeded` — cooperative
    per-query budgets (deadline.py);
  * :class:`CircuitBreaker` — closed/open/half-open routing over the
    chip backend (breaker.py);
  * :class:`SLORecorder` — per-tenant latency percentiles and outcome
    rates (slo.py);
  * :class:`QueryJournal` / :func:`request_fingerprint` — the durable
    intent/outcome WAL behind exactly-once fleet serving (journal.py);
  * :class:`FleetSupervisor` — crash-only supervision of N serve
    workers: consistent-hash routing, heartbeat health checks, backoff
    restarts, crash-loop quarantine, journal replay, graceful drain
    (fleet.py);
  * :class:`ResultCache` / :func:`content_fingerprint` — whole-query
    reuse keyed by relation content (resultcache.py);
  * :class:`MicroBatcher` / :func:`batch_signature` — bounded-window
    inter-query coalescing into fused device programs (microbatch.py);
  * :class:`ResidentStateManager` — HBM-budgeted device-resident sorted
    unions behind the O(N+Δ) delta-merge path (resident.py).
"""

from tpu_radix_join.service.admission import (AdmissionQueue,
                                              AdmissionRejected)
from tpu_radix_join.service.breaker import (CLOSED, HALF_OPEN, OPEN,
                                            CircuitBreaker)
from tpu_radix_join.service.deadline import Deadline, DeadlineExceeded
from tpu_radix_join.service.fleet import (FleetSupervisor, ring_points,
                                          route_tenant)
from tpu_radix_join.service.journal import (JournalAudit, QueryJournal,
                                            request_fingerprint)
from tpu_radix_join.service.microbatch import MicroBatcher, batch_signature
from tpu_radix_join.service.resident import ResidentStateManager
from tpu_radix_join.service.resultcache import (ResultCache,
                                                content_fingerprint)
from tpu_radix_join.service.session import (BackendUnavailable, JoinSession,
                                            QueryOutcome, QueryRequest,
                                            UNCLASSIFIED)
from tpu_radix_join.service.slo import SLORecorder, nearest_rank

__all__ = [
    "AdmissionQueue", "AdmissionRejected",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "Deadline", "DeadlineExceeded",
    "FleetSupervisor", "ring_points", "route_tenant",
    "JournalAudit", "QueryJournal", "request_fingerprint",
    "JoinSession", "QueryRequest", "QueryOutcome", "BackendUnavailable",
    "UNCLASSIFIED",
    "MicroBatcher", "batch_signature",
    "ResidentStateManager",
    "ResultCache", "content_fingerprint",
    "SLORecorder", "nearest_rank",
]
