"""Per-query latency budgets with cooperative cancellation.

A :class:`Deadline` is armed when the query is admitted and consulted
*between* pipeline phases (the engine's ``cancel`` hook,
operators/hash_join.py, and the session's own phase boundaries) — never
mid-dispatch, so a cancelled query leaves no half-written device state.
An expired check raises :class:`DeadlineExceeded`, which carries the
``deadline_exceeded`` failure class so the session's outcome record and
the chaos invariant treat the abort as classified, not as a crash.

The clock is injectable: tests drive expiry mid-phase with a fake clock
instead of sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from tpu_radix_join.robustness.retry import DEADLINE_EXCEEDED


class DeadlineExceeded(RuntimeError):
    """A query's latency budget expired between phases."""

    failure_class = DEADLINE_EXCEEDED

    def __init__(self, budget_s: float, elapsed_s: float, phase: str):
        super().__init__(
            f"deadline {budget_s:.3f}s exceeded after {elapsed_s:.3f}s "
            f"(at phase {phase!r})")
        self.budget_s = budget_s
        self.elapsed_s = elapsed_s
        self.phase = phase


class Deadline:
    """Wall-clock budget for one query; ``budget_s=None`` never expires.

    ``check(phase)`` is the cooperative cancellation point — cheap enough
    to call between every phase (one clock read), and a no-op object
    (:func:`Deadline.unlimited`) keeps call sites branch-free.
    """

    def __init__(self, budget_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        if budget_s is not None and budget_s < 0:
            raise ValueError("deadline budget must be >= 0 (or None)")
        self.budget_s = budget_s
        self._clock = clock
        self._t0 = clock()

    @classmethod
    def unlimited(cls) -> "Deadline":
        return cls(None)

    def elapsed_s(self) -> float:
        return self._clock() - self._t0

    def remaining_s(self) -> Optional[float]:
        """Seconds left (never negative), or None when unlimited."""
        if self.budget_s is None:
            return None
        return max(0.0, self.budget_s - self.elapsed_s())

    def expired(self) -> bool:
        return (self.budget_s is not None
                and self.elapsed_s() >= self.budget_s)

    def check(self, phase: str = "") -> None:
        """Raise :class:`DeadlineExceeded` when the budget is spent.
        Signature matches the engine's ``cancel(phase)`` hook, so a
        Deadline plugs in directly as the cancellation callable."""
        if self.expired():
            raise DeadlineExceeded(self.budget_s, self.elapsed_s(), phase)
