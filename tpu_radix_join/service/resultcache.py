"""Relation-fingerprint result cache: whole-query reuse before admission.

The journal's request fingerprint (service/journal.py) answers "is this
the SAME SUBMISSION" — it includes the query_id, because exactly-once is
a per-submission contract.  The content fingerprint here answers "is
this the same WORK": it hashes only the fields that determine the
answer (the relation specs — sizes, kinds, seeds, skew knobs — plus the
join-config fingerprint and the membership epoch) and drops the
submission envelope (query_id, tenant, deadline).  Two different
clients asking the same question on unchanged inputs therefore hit the
same entry, and any spec/epoch/config change lands on a NEW fingerprint
— stale entries are unreachable by construction, and the LRU ages them
out.

Serving discipline (service/session.py + service/fleet.py):

  * a hit short-circuits BEFORE admission: the stored outcome is
    re-stamped with the new submission's query_id/tenant and marked
    ``served_by="cache_hit"`` — the client sees a normal outcome line;
  * under the fleet supervisor a hit is still intent+outcome JOURNALED
    under the per-submission fingerprint, so the exactly-once audit
    (``double_exec == 0``) holds unchanged through failover and replay;
  * every stored entry carries a sha256 digest of its payload and the
    epoch it was computed under; :meth:`ResultCache.get` re-verifies
    both on every read, so a corrupted or stale entry is DROPPED (a
    miss, re-executed) rather than served — the ``serve.cache_poison``
    chaos site (robustness/faults.py) injects exactly that corruption
    and the soak invariant holds the line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from collections import OrderedDict
from typing import Callable, Optional

from tpu_radix_join.performance.measurements import RCHIT, RCMISS
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.service.journal import _canonical

#: submission-envelope fields the content fingerprint must NOT see: they
#: change who asked / when we give up, never the answer
_ENVELOPE_FIELDS = ("query_id", "tenant", "tenant_name", "display_name",
                    "deadline_s")


def content_fingerprint(request, config_fp: Optional[dict] = None,
                        epoch: Optional[int] = None) -> str:
    """Content identity of one query: sha256 over the canonicalized
    request MINUS the submission envelope, the join-config fingerprint,
    and the membership epoch.  Equal fingerprints mean "the same answer"
    — the invalidation rule is that there is no invalidation, only new
    fingerprints."""
    if dataclasses.is_dataclass(request) and not isinstance(request, type):
        request = dataclasses.asdict(request)
    spec = {k: v for k, v in request.items() if k not in _ENVELOPE_FIELDS}
    blob = json.dumps({"spec": _canonical(spec), "config": config_fp,
                       "epoch": epoch}, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _digest(payload: dict) -> str:
    return hashlib.sha256(json.dumps(payload, sort_keys=True,
                                     default=str).encode()).hexdigest()


@dataclasses.dataclass
class _Entry:
    payload: dict                  # the stored outcome fields (JSON shape)
    digest: str                    # sha256 over payload at store time
    epoch: Optional[int]           # membership epoch at store time
    stored_at: float               # clock() timestamp for TTL expiry
    hits: int = 0


class ResultCache:
    """LRU + TTL result cache keyed by :func:`content_fingerprint`.

    ``max_entries == 0`` is the disabled posture: every get misses
    without counting, every put is dropped — callers need no gate of
    their own.  Single-threaded like the session that owns it.
    """

    def __init__(self, max_entries: int, ttl_s: Optional[float] = None,
                 measurements=None,
                 clock: Callable[[], float] = time.monotonic):
        if max_entries < 0:
            raise ValueError("max_entries must be >= 0")
        self.max_entries = max_entries
        self.ttl_s = ttl_s
        self.measurements = measurements
        self._clock = clock
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.expired = 0
        self.dropped_stale = 0     # digest/epoch verification drops

    # ------------------------------------------------------------- serving
    def get(self, fp: str, epoch: Optional[int] = None) -> Optional[dict]:
        """The stored payload for ``fp`` (a COPY — callers re-stamp their
        own envelope), or None.  Verifies TTL, payload digest, and epoch
        on every read; any failure drops the entry and counts a miss —
        a stale or damaged entry is never served."""
        if self.max_entries == 0:
            return None
        m = self.measurements
        entry = self._entries.get(fp)
        if entry is not None and _faults.fires(_faults.CACHE_POISON, m):
            # chaos: corrupt the stored entry in place — the digest check
            # below must catch it (the production twin is heap rot or a
            # stale epoch surviving an invalidation bug)
            entry.payload = dict(entry.payload, matches=-1)
        if entry is None:
            self.misses += 1
            if m is not None:
                m.incr(RCMISS)
            return None
        if (self.ttl_s is not None
                and self._clock() - entry.stored_at > self.ttl_s):
            del self._entries[fp]
            self.expired += 1
            self.misses += 1
            if m is not None:
                m.incr(RCMISS)
            return None
        if _digest(entry.payload) != entry.digest or entry.epoch != epoch:
            # poisoned payload or an epoch the entry was not computed
            # under: drop loudly, re-execute
            del self._entries[fp]
            self.dropped_stale += 1
            self.misses += 1
            if m is not None:
                m.incr(RCMISS)
                m.event("result_cache_drop", fp=fp,
                        reason=("epoch" if entry.epoch != epoch
                                else "digest"))
            return None
        self._entries.move_to_end(fp)
        entry.hits += 1
        self.hits += 1
        if m is not None:
            m.incr(RCHIT)
        return dict(entry.payload)

    def put(self, fp: str, payload: dict,
            epoch: Optional[int] = None) -> None:
        """Store one ok outcome's payload under its content fingerprint
        (callers only cache ``status == "ok"`` outcomes — a failure is
        evidence, not an answer)."""
        if self.max_entries == 0:
            return
        payload = dict(payload)
        self._entries[fp] = _Entry(payload=payload, digest=_digest(payload),
                                   epoch=epoch, stored_at=self._clock())
        self._entries.move_to_end(fp)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)

    # ---------------------------------------------------------- lifecycle
    def invalidate(self, fp: Optional[str] = None) -> int:
        """Drop one entry (or all, fp=None); returns how many went."""
        if fp is not None:
            return 1 if self._entries.pop(fp, None) is not None else 0
        n = len(self._entries)
        self._entries.clear()
        return n

    def __len__(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        """The ``/statusz`` cache section payload."""
        total = self.hits + self.misses
        return {"entries": len(self._entries),
                "max_entries": self.max_entries,
                "ttl_s": self.ttl_s,
                "hits": self.hits, "misses": self.misses,
                "expired": self.expired,
                "dropped_stale": self.dropped_stale,
                "hit_rate": round(self.hits / total, 4) if total else 0.0}
