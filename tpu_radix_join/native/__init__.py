"""Native host runtime: C++ sources compiled on demand (see build.py)."""
