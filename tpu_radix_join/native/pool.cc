// Host memory pool: aligned bump allocator.
//
// Native replacement for the reference's memory/Pool.{h,cpp}: one
// posix_memalign'd region (Pool.cpp:25-38), 64B-aligned bump allocation
// (:40-64), overflow fallback to fresh aligned allocations (:55-59), and
// reset/free-all (:66-79).  Fixes the reference's Pool::free self-recursion
// bug (Pool.cpp:66-70) by construction.  Exposed to Python via ctypes
// (tpu_radix_join/memory/pool.py); used to back pinned host staging buffers
// for relation generation and device transfer.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr std::size_t kAlignment = 64;

inline std::size_t round_up(std::size_t n) {
  return (n + kAlignment - 1) & ~(kAlignment - 1);
}

struct Pool {
  std::uint8_t* base = nullptr;
  std::size_t capacity = 0;
  std::size_t offset = 0;
  std::vector<void*> overflow;  // fallback allocations (freed on reset)
  std::mutex mu;
};

}  // namespace

extern "C" {

// Returns an opaque pool handle, or null on allocation failure.
void* pool_create(std::size_t capacity) {
  void* mem = nullptr;
  capacity = round_up(capacity);
  if (posix_memalign(&mem, kAlignment, capacity) != 0) return nullptr;
  Pool* p = new Pool();
  p->base = static_cast<std::uint8_t*>(mem);
  p->capacity = capacity;
  return p;
}

// Bump-allocate `size` bytes (64B-aligned).  Falls back to a fresh aligned
// allocation when the region is exhausted, as the reference does.
void* pool_get_memory(void* handle, std::size_t size) {
  Pool* p = static_cast<Pool*>(handle);
  size = round_up(size);
  std::lock_guard<std::mutex> lock(p->mu);
  if (p->offset + size <= p->capacity) {
    void* out = p->base + p->offset;
    p->offset += size;
    return out;
  }
  void* mem = nullptr;
  if (posix_memalign(&mem, kAlignment, size) != 0) return nullptr;
  p->overflow.push_back(mem);
  return mem;
}

// Rewind the bump pointer and release overflow allocations (Pool::reset).
void pool_reset(void* handle) {
  Pool* p = static_cast<Pool*>(handle);
  std::lock_guard<std::mutex> lock(p->mu);
  p->offset = 0;
  for (void* mem : p->overflow) free(mem);
  p->overflow.clear();
}

std::size_t pool_used(void* handle) {
  Pool* p = static_cast<Pool*>(handle);
  std::lock_guard<std::mutex> lock(p->mu);
  return p->offset;
}

std::size_t pool_capacity(void* handle) {
  return static_cast<Pool*>(handle)->capacity;
}

void pool_destroy(void* handle) {
  Pool* p = static_cast<Pool*>(handle);
  pool_reset(p);
  free(p->base);
  delete p;
}

}  // extern "C"
