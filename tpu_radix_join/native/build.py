"""Compile-on-demand loader for the native runtime library.

The reference builds its C++ runtime with CMake into static libs
(CMakeLists.txt:16-23); here the native pieces compile once into a shared
library next to the sources (g++ -O3 -shared) and load via ctypes.  If no
toolchain is available — or an existing .so is stale/foreign — the callers
fall back to pure-numpy implementations: the framework stays functional, just
with slower host-side generation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["pool.cc", "datagen.cc"]
_LIB_NAME = "libtrj_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _newer_than(a: str, b: str) -> bool:
    return os.path.getmtime(a) > os.path.getmtime(b)


def _compile() -> Optional[str]:
    out = os.path.join(_DIR, _LIB_NAME)
    srcs = [os.path.join(_DIR, s) for s in _SOURCES]
    if os.path.exists(out) and not any(_newer_than(s, out) for s in srcs):
        return out
    # Compile to a temp path and rename into place so concurrent processes
    # never load a half-written library.
    tmp = f"{out}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", tmp, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare signatures; raises AttributeError on missing symbols."""
    u64, u32, i32 = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int
    p_u32 = ctypes.POINTER(ctypes.c_uint32)
    lib.pool_create.restype = ctypes.c_void_p
    lib.pool_create.argtypes = [ctypes.c_size_t]
    lib.pool_get_memory.restype = ctypes.c_void_p
    lib.pool_get_memory.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.pool_reset.argtypes = [ctypes.c_void_p]
    lib.pool_used.restype = ctypes.c_size_t
    lib.pool_used.argtypes = [ctypes.c_void_p]
    lib.pool_capacity.restype = ctypes.c_size_t
    lib.pool_capacity.argtypes = [ctypes.c_void_p]
    lib.pool_destroy.argtypes = [ctypes.c_void_p]
    lib.fill_unique.argtypes = [p_u32, u64, u64, u64, u32, p_u32, i32]
    lib.fill_modulo.argtypes = [p_u32, u64, u64, u32, i32]
    lib.fill_zipf.argtypes = [p_u32, u64, u64, p_u32, u64, p_u32, u64,
                              u64, i32]
    lib.fill_rids.argtypes = [p_u32, u64, u64, i32]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable (numpy fallbacks apply)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _compile()
        if path is None:
            return None
        try:
            # stale/foreign-arch .so or missing symbols: honor the numpy
            # fallback contract instead of crashing every caller
            _lib = _bind(ctypes.CDLL(path))
        except (OSError, AttributeError):
            _lib = None
        return _lib
