// Multithreaded host-side relation generation.
//
// Native replacement for the reference's data/Relation.cpp generators:
// fillUniqueValues (dense unique keys + shuffle, Relation.cpp:63-73,87-97),
// fillModuloValues (:75-85), plus the Zipf skew capability of the GPU data
// model (data/data.hpp:88).  The unique generator implements the same seeded
// Feistel-network bijection + cycle-walking as the JAX/numpy implementations
// (data/relation.py) — round keys are supplied by the caller so all three
// produce bit-identical permutations.  Parallelised with std::thread: every
// output index is independent, so this scales to 1B-tuple relations where a
// host Fisher-Yates shuffle (reference style) would serialize.

#include <cmath>
#include <cstdint>
#include <functional>
#include <thread>
#include <vector>

namespace {

constexpr int kFeistelRounds = 6;

struct FeistelParams {
  std::uint32_t keys[kFeistelRounds];
  std::uint32_t half_bits;
  std::uint64_t domain;       // 2**(2*half_bits)
  std::uint64_t global_size;  // cycle-walk target range
};

inline std::uint64_t feistel_once(std::uint64_t x, const FeistelParams& fp) {
  const std::uint64_t mask = (1ull << fp.half_bits) - 1;
  std::uint64_t l = x >> fp.half_bits;
  std::uint64_t r = x & mask;
  for (int i = 0; i < kFeistelRounds; ++i) {
    // Must match _feistel_round_np / _feistel_jax in data/relation.py:
    // f = ((r * 0x9E3779B1 + k) ^ (r >> 7)) & mask  (uint32 wrap-around)
    std::uint64_t f =
        ((static_cast<std::uint32_t>(r * 0x9E3779B1u + fp.keys[i])) ^ (r >> 7)) &
        mask;
    std::uint64_t nl = r;
    r = (l ^ f) & mask;
    l = nl;
  }
  return (l << fp.half_bits) | r;
}

inline std::uint64_t permute(std::uint64_t idx, const FeistelParams& fp) {
  std::uint64_t v = feistel_once(idx, fp);
  while (v >= fp.global_size) v = feistel_once(v, fp);  // cycle-walk
  return v;
}

void run_threads(std::uint64_t count, int num_threads,
                 const std::function<void(std::uint64_t, std::uint64_t)>& fn) {
  if (num_threads <= 1) {
    fn(0, count);
    return;
  }
  std::vector<std::thread> ts;
  std::uint64_t chunk = (count + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    std::uint64_t lo = t * chunk;
    std::uint64_t hi = lo + chunk < count ? lo + chunk : count;
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// keys_out[i] = perm(start + i) for a seeded bijection of [0, global_size).
// round_keys: 6 uint32 Feistel round keys (from the caller's seeded RNG).
void fill_unique(std::uint32_t* keys_out, std::uint64_t start,
                 std::uint64_t count, std::uint64_t global_size,
                 std::uint32_t half_bits, const std::uint32_t* round_keys,
                 int num_threads) {
  FeistelParams fp;
  for (int i = 0; i < kFeistelRounds; ++i) fp.keys[i] = round_keys[i];
  fp.half_bits = half_bits;
  fp.domain = 1ull << (2 * half_bits);
  fp.global_size = global_size;
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      keys_out[i] = static_cast<std::uint32_t>(permute(start + i, fp));
    }
  });
}

// keys_out[i] = (start + i) % modulo  (Relation::fillModuloValues).
void fill_modulo(std::uint32_t* keys_out, std::uint64_t start,
                 std::uint64_t count, std::uint32_t modulo, int num_threads) {
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      keys_out[i] = static_cast<std::uint32_t>((start + i) % modulo);
    }
  });
}

// Zipf(theta) draw over [0, domain) via inverse-CDF on a caller-provided
// rank table (the Python layer builds it so native and numpy paths share the
// exact float64 table and produce bit-identical keys).  splitmix64 seeded by
// the *global* tuple index keeps shards/threads independent and the stream
// deterministic in (seed, index).
static inline std::uint64_t splitmix64(std::uint64_t x) {
  x += 0x9E3779B97f4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

void fill_zipf(std::uint32_t* keys_out, std::uint64_t start,
               std::uint64_t count, const double* cdf,
               std::uint64_t table_size, std::uint64_t domain, double theta,
               std::uint64_t seed, int num_threads) {
  const double head = cdf[table_size - 1];
  // Ranks past the table follow the continuous power-law tail:
  // integral of x^-(1+theta) over [table_size, domain].
  const double t_pow = std::pow(static_cast<double>(table_size), -theta);
  const double d_pow = std::pow(static_cast<double>(domain), -theta);
  const double tail = domain > table_size ? (t_pow - d_pow) / theta : 0.0;
  const double total = head + tail;
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      double u =
          (splitmix64(seed ^ (start + i)) >> 11) * (1.0 / 9007199254740992.0);
      double target = u * total;
      if (target > head) {
        // inverse-CDF of the continuous tail
        double frac = (target - head) / tail;
        double x = std::pow(t_pow - frac * (t_pow - d_pow), -1.0 / theta);
        std::uint64_t k = static_cast<std::uint64_t>(x);
        if (k < table_size) k = table_size;
        if (k >= domain) k = domain - 1;
        keys_out[i] = static_cast<std::uint32_t>(k);
        continue;
      }
      // lower_bound: first rank with cdf >= target (== np.searchsorted left)
      std::uint64_t a = 0, b = table_size - 1;
      while (a < b) {
        std::uint64_t m = (a + b) / 2;
        if (cdf[m] < target) a = m + 1; else b = m;
      }
      keys_out[i] = static_cast<std::uint32_t>(a);
    }
  });
}

void fill_rids(std::uint32_t* rids_out, std::uint64_t start,
               std::uint64_t count, int num_threads) {
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      rids_out[i] = static_cast<std::uint32_t>(start + i);
    }
  });
}

}  // extern "C"
