// Multithreaded host-side relation generation.
//
// Native replacement for the reference's data/Relation.cpp generators:
// fillUniqueValues (dense unique keys + shuffle, Relation.cpp:63-73,87-97),
// fillModuloValues (:75-85), plus the Zipf skew capability of the GPU data
// model (data/data.hpp:88).  The unique generator implements the same seeded
// Feistel-network bijection + cycle-walking as the JAX/numpy implementations
// (data/relation.py) — round keys are supplied by the caller so all three
// produce bit-identical permutations.  Parallelised with std::thread: every
// output index is independent, so this scales to 1B-tuple relations where a
// host Fisher-Yates shuffle (reference style) would serialize.

#include <cmath>
#include <cstdint>
#include <functional>
#include <thread>
#include <vector>

namespace {

constexpr int kFeistelRounds = 6;

struct FeistelParams {
  std::uint32_t keys[kFeistelRounds];
  std::uint32_t half_bits;
  std::uint64_t domain;       // 2**(2*half_bits)
  std::uint64_t global_size;  // cycle-walk target range
};

inline std::uint64_t feistel_once(std::uint64_t x, const FeistelParams& fp) {
  const std::uint64_t mask = (1ull << fp.half_bits) - 1;
  std::uint64_t l = x >> fp.half_bits;
  std::uint64_t r = x & mask;
  for (int i = 0; i < kFeistelRounds; ++i) {
    // Must match _feistel_round_np / _feistel_jax in data/relation.py:
    // f = ((r * 0x9E3779B1 + k) ^ (r >> 7)) & mask  (uint32 wrap-around)
    std::uint64_t f =
        ((static_cast<std::uint32_t>(r * 0x9E3779B1u + fp.keys[i])) ^ (r >> 7)) &
        mask;
    std::uint64_t nl = r;
    r = (l ^ f) & mask;
    l = nl;
  }
  return (l << fp.half_bits) | r;
}

inline std::uint64_t permute(std::uint64_t idx, const FeistelParams& fp) {
  std::uint64_t v = feistel_once(idx, fp);
  while (v >= fp.global_size) v = feistel_once(v, fp);  // cycle-walk
  return v;
}

void run_threads(std::uint64_t count, int num_threads,
                 const std::function<void(std::uint64_t, std::uint64_t)>& fn) {
  if (num_threads <= 1) {
    fn(0, count);
    return;
  }
  std::vector<std::thread> ts;
  std::uint64_t chunk = (count + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    std::uint64_t lo = t * chunk;
    std::uint64_t hi = lo + chunk < count ? lo + chunk : count;
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// keys_out[i] = perm(start + i) for a seeded bijection of [0, global_size).
// round_keys: 6 uint32 Feistel round keys (from the caller's seeded RNG).
void fill_unique(std::uint32_t* keys_out, std::uint64_t start,
                 std::uint64_t count, std::uint64_t global_size,
                 std::uint32_t half_bits, const std::uint32_t* round_keys,
                 int num_threads) {
  FeistelParams fp;
  for (int i = 0; i < kFeistelRounds; ++i) fp.keys[i] = round_keys[i];
  fp.half_bits = half_bits;
  fp.domain = 1ull << (2 * half_bits);
  fp.global_size = global_size;
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      keys_out[i] = static_cast<std::uint32_t>(permute(start + i, fp));
    }
  });
}

// keys_out[i] = (start + i) % modulo  (Relation::fillModuloValues).
void fill_modulo(std::uint32_t* keys_out, std::uint64_t start,
                 std::uint64_t count, std::uint32_t modulo, int num_threads) {
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      keys_out[i] = static_cast<std::uint32_t>((start + i) % modulo);
    }
  });
}

// Zipf draw over [0, domain) from the integer-scaled tables the Python
// layer builds (data/relation.py zipf_tables): head ranks by upper-bound
// search of the 2^32-scaled uint32 CDF, tail ranks by linear interpolation
// of the 4097-entry inverse-CDF key table.  Every operation below is uint32
// arithmetic mirrored EXACTLY by zipf_keys_np (numpy) and _zipf_range
// (device), so all three samplers are bit-identical — including on TPU,
// which has no float64 (the f64 runs once, host-side, at table build).
// mix32 must match utils/hashing.py.
static inline std::uint32_t mix32(std::uint32_t x) {
  x ^= x >> 16;
  x *= 0x7FEB352Du;
  x ^= x >> 15;
  x *= 0x846CA68Bu;
  return x ^ (x >> 16);
}

void fill_zipf(std::uint32_t* keys_out, std::uint64_t start,
               std::uint64_t count, const std::uint32_t* head_cdf,
               std::uint64_t table_size, const std::uint32_t* tail_keys,
               std::uint64_t domain, std::uint64_t seed, int num_threads) {
  const std::uint32_t seed_mix =
      mix32(static_cast<std::uint32_t>(seed & 0xFFFFFFFFull));
  const std::uint32_t head_end = head_cdf[table_size - 1];
  const std::uint32_t dom_max = static_cast<std::uint32_t>(domain - 1);
  const bool has_tail = domain > table_size;
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      const std::uint32_t u =
          mix32(static_cast<std::uint32_t>(start + i) ^ seed_mix);
      if (has_tail && u >= head_end) {
        // tail: second mixed draw supplies (segment, fraction) bits
        const std::uint32_t v = mix32(u ^ 0x9E3779B9u);
        const std::uint32_t j = v >> 20;
        const std::uint32_t frac = (v >> 8) & 0xFFFu;
        const std::uint32_t tk = tail_keys[j];
        const std::uint32_t d = tail_keys[j + 1] - tk;
        const std::uint32_t interp =
            (d >> 12) * frac + (((d & 0xFFFu) * frac) >> 12);
        const std::uint32_t s = tk + interp;   // may wrap near 2^32
        keys_out[i] = (s < tk) ? dom_max : (s < dom_max ? s : dom_max);
        continue;
      }
      // upper_bound: #{k : head_cdf[k] <= u} (== np.searchsorted right)
      std::uint64_t a = 0, b = table_size;
      while (a < b) {
        std::uint64_t m = (a + b) / 2;
        if (head_cdf[m] <= u) a = m + 1; else b = m;
      }
      if (a >= table_size) a = table_size - 1;
      keys_out[i] = static_cast<std::uint32_t>(a);
    }
  });
}

void fill_rids(std::uint32_t* rids_out, std::uint64_t start,
               std::uint64_t count, int num_threads) {
  run_threads(count, num_threads, [&](std::uint64_t lo, std::uint64_t hi) {
    for (std::uint64_t i = lo; i < hi; ++i) {
      rids_out[i] = static_cast<std::uint32_t>(start + i);
    }
  });
}

}  // extern "C"
