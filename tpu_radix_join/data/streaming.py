"""Streaming relation loader: pool-backed, prefetching chunk generation.

The reference's large-data (LD) path assumes the host feeds the accelerator in
chunks while the previous chunk computes — its drivers overlap H2D copies with
kernels on multiple CUDA streams (``small_data.cu:85-159``) and its relations
live in Pool memory (``Relation.cpp:33``).  The TPU-host analog here:

  * chunk buffers come from the native bump-pool allocator
    (``memory/pool.py`` -> ``native/pool.cc``) — two pairs, reused for the
    whole stream, so host memory stays O(chunk) for arbitrarily large
    relations;
  * generation of chunk ``k+1`` runs on a background thread (which itself
    fans out over ``std::thread`` workers in ``native/datagen.cc``) while
    chunk ``k`` is transferred and consumed — the host-side copy/compute
    overlap the reference gets from stream double-buffering;
  * each yielded ``TupleBatch`` holds *device* arrays, transferred and fenced
    before the backing buffer is handed back to the filler, so buffer reuse
    can never corrupt an in-flight chunk.

Feeds ``ops/chunked.chunked_join_grid`` (both-sides-streamed joins) and any
driver that wants relations larger than host or device memory.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.relation import Relation, device_range, key_hi_lane
from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.memory.pool import Pool
from tpu_radix_join.robustness import faults as _faults


def _maybe_corrupt(key: jnp.ndarray) -> jnp.ndarray:
    """Fault site ``stream.corrupt_lane``: when armed, smash the chunk's
    first key to the reserved sentinel 0xFFFFFFFF — the damage a flipped
    bit or torn read would do.  Downstream key-contract checks (chunked
    auto-range probe, engine key-width guard) must detect it loudly; the
    site exists so tier-1 can prove they do."""
    if _faults.fires(_faults.STREAM_CORRUPT):
        key = key.at[0].set(jnp.uint32(0xFFFFFFFF))
    return key


def stream_chunks(rel: Relation, node: int, chunk_tuples: int,
                  pool: Optional[Pool] = None,
                  num_threads: int = 0) -> Iterator[TupleBatch]:
    """Yield one node's shard as device TupleBatches of ``chunk_tuples``
    (final chunk may be short), generated with double-buffered prefetch.

    ``pool``: optional ``memory.Pool`` to draw the four chunk buffers from
    (it needs ``8 * 2 * chunk_tuples`` bytes + 64B-alignment headroom);
    default is a private pool sized exactly for that.
    """
    if chunk_tuples < 1:
        raise ValueError("chunk_tuples must be >= 1")
    local = rel.local_size
    base = node * local
    num_chunks = -(-local // chunk_tuples)
    own_pool = pool is None
    if own_pool:
        pool = Pool(2 * 2 * chunk_tuples * 4 + 4 * 64)
    bufs = [(pool.get_array((chunk_tuples,)), pool.get_array((chunk_tuples,)))
            for _ in range(2)]

    def fill(i: int) -> int:
        start = base + i * chunk_tuples
        n = min(chunk_tuples, base + local - start)
        key_buf, rid_buf = bufs[i % 2]
        rel.fill_np(start, n, num_threads=num_threads,
                    out_key=key_buf[:n], out_rid=rid_buf[:n])
        return n

    ex = ThreadPoolExecutor(max_workers=1)
    try:
        fut = ex.submit(fill, 0)
        for i in range(num_chunks):
            n = fut.result()
            if i + 1 < num_chunks:
                # prefetch immediately: fill(i+1) writes bufs[(i+1) % 2],
                # whose previous chunk was copied and fenced last iteration,
                # so generation overlaps this chunk's transfer.
                fut = ex.submit(fill, i + 1)
            key_buf, rid_buf = bufs[i % 2]
            # copy=True: on the CPU backend jnp.asarray would zero-copy-alias
            # the pool buffer, and the fence below must guarantee the chunk
            # is independent of the buffer before fill(i+2) rewrites it.
            key = jnp.array(key_buf[:n], copy=True)
            rid = jnp.array(rid_buf[:n], copy=True)
            # wide relations: the hi lane is a pure on-device function of the
            # lo lane (relation.key_hi_lane), so the wire/pool format stays
            # two uint32 buffers regardless of key width
            hi = key_hi_lane(key) if rel.key_bits == 64 else None
            jax.block_until_ready((key, rid))
            yield TupleBatch(key=_maybe_corrupt(key), rid=rid, key_hi=hi)
    finally:
        ex.shutdown(wait=True)
        if own_pool:
            pool.close()


def stream_chunks_device(rel: Relation, node: int,
                         chunk_tuples: int) -> Iterator[TupleBatch]:
    """Yield one node's shard as **device-generated** TupleBatches — the
    at-scale twin of :func:`stream_chunks`: each chunk's keys are computed
    on device from its global index range (unique/modulo: same Feistel walk
    / residues; zipf since r4: the integer-table sampler — all bit-identical
    to the host stream), so the host materializes and transfers nothing
    (SURVEY.md §7.4 item 5).  Out-of-core grid joins stay compute-bound even
    on transfer-starved attachments.
    """
    if chunk_tuples < 1:
        raise ValueError("chunk_tuples must be >= 1")
    local = rel.local_size
    base = node * local
    num_chunks = -(-local // chunk_tuples)
    wide = rel.key_bits == 64
    modulo = rel.modulo if rel.kind == "modulo" else None
    for i in range(num_chunks):
        start = base + i * chunk_tuples
        n = min(chunk_tuples, base + local - start)
        if rel.kind == "zipf":
            out = rel.zipf_range_device(start, n)
        else:
            out = device_range(start, n, rel.global_size, rel.seed, modulo,
                               wide)
        if wide:
            key, hi, rid = out
            yield TupleBatch(key=_maybe_corrupt(key), rid=rid, key_hi=hi)
        else:
            key, rid = out
            yield TupleBatch(key=_maybe_corrupt(key), rid=rid, key_hi=None)
