"""Relations and seeded data generation with closed-form correctness oracles.

Replaces ``data/Relation.{h,cpp}``:

  * ``fill_unique``  -> ``Relation::fillUniqueValues`` (Relation.cpp:63-73,87-97):
    every key in ``0..global_size-1`` appears exactly once across all shards, so
    the exact expected match count of R ⋈ S (both unique over the same range) is
    ``global_size`` — the oracle the reference checks manually via the
    ``[RESULTS] Tuples:`` line (Measurements.cpp:599-606, main.cpp:94-98).
  * ``fill_modulo``  -> ``Relation::fillModuloValues`` (Relation.cpp:75-85):
    key = rid % modulo, giving closed-form match-rate control.
  * ``fill_zipf``    -> the Zipf ``zFactor`` capability of the GPU data model
    (data/data.hpp:88) exercised by the skew benchmark config.
  * ``Relation::distribute`` (Relation.cpp:99-141): the reference
    pairwise-exchanges random blocks so each rank holds a random slice of the
    key space; here the generator IS globally shuffled (a seeded permutation
    sharded contiguously), so the join pipeline needs no network pre-step.
    For shards that DO arrive with locality, ``parallel/distribute.py``
    provides the explicit all_to_all + local-reshuffle equivalent.

TPU-first scale path: host-side ``np.random.permutation`` caps out around a
few hundred million tuples, so ``fill_unique`` can also run **on device** via a
seeded Feistel-network bijection over the key domain with vectorized
cycle-walking (``feistel_permutation``) — each shard computes its own slice of
the global permutation with no host materialization (SURVEY.md §7.4 item 5).
"""

from __future__ import annotations

import ctypes
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.native.build import load as _load_native
from tpu_radix_join.utils.hashing import mix32, mix32_np

_FEISTEL_ROUNDS = 6
_ZIPF_TABLE_MAX = 65536

# 64-bit key spread (key_bits=64): the upper lane is a fixed mix of the
# 32-bit logical key, shared by every relation (NOT seeded) so equal logical
# keys always map to equal wide keys — every closed-form oracle carries over
# unchanged, and the hi lane is a deterministic function of the lo lane, so
# the streaming loader can derive it per chunk.  The mix lands in
# [2**30, 2**31): every generated wide key exceeds 2**62 (a genuinely >32-bit
# domain, like the reference's uint64 keys, Tuple.h:19-20) and the sentinel
# lane (tuples.py: key_hi for wide batches) can never collide with the
# 0xFFFFFFFE/0xFFFFFFFF padding sentinels.  Injectivity is by the lo lane:
# the logical-key generators already guarantee it for the "unique" kind.
_HI_LANE_LOW = np.uint32(0x40000000)
_HI_LANE_MASK = np.uint32(0x3FFFFFFF)


def key_hi_lane_np(key: np.ndarray) -> np.ndarray:
    """uint32 hi lane for wide keys — numpy twin of :func:`key_hi_lane`."""
    return (mix32_np(key) & _HI_LANE_MASK) | _HI_LANE_LOW


@jax.jit
def key_hi_lane(key: jnp.ndarray) -> jnp.ndarray:
    """Device twin of :func:`key_hi_lane_np` (bit-identical)."""
    return ((mix32(key) & jnp.uint32(_HI_LANE_MASK))
            | jnp.uint32(_HI_LANE_LOW))


ZIPF_TAIL_POINTS = 4096
_ZIPF_V_SALT = 0x9E3779B9   # second-draw salt for the tail interpolation


def zipf_tables(theta: float, domain: int):
    """Integer-scaled Zipf(1+theta) sampling tables, shared VERBATIM by the
    numpy, native (datagen.cc), and device samplers — after this point every
    sampler runs identical uint32 arithmetic, so all three are bit-identical
    (including on TPU, which has no f64: the f64 below runs once, on host,
    at table-build time).

      head_cdf: uint32 [min(domain, 65536)] — rank CDF scaled to 2**32
        (head-rank probabilities exact to 2**-32).
      tail_keys: uint32 [4097] — piecewise-linear inverse CDF of the
        continuous power-law tail for ranks past the head table (the same
        tail the r3 f64 sampler inverted exactly; the 4096-segment linear
        approximation error is < one segment width, on ranks whose
        individual probabilities are < 65536**-(1+theta)).
    """
    table = min(domain, _ZIPF_TABLE_MAX)
    ranks = np.arange(1, table + 1, dtype=np.float64)
    cdf = np.cumsum(1.0 / np.power(ranks, 1.0 + theta))
    head = cdf[-1]
    t_pow = float(table) ** -theta
    d_pow = float(domain) ** -theta
    tail = (t_pow - d_pow) / theta if domain > table else 0.0
    total = head + tail
    head_cdf = np.minimum(np.floor(cdf / total * 4294967296.0),
                          4294967295.0).astype(np.uint32)
    if domain > table:
        f = (np.arange(ZIPF_TAIL_POINTS + 1, dtype=np.float64)
             / ZIPF_TAIL_POINTS)
        x = np.power(t_pow - f * (t_pow - d_pow), -1.0 / theta)
        tail_keys = np.clip(np.floor(x), table, domain - 1).astype(np.uint32)
    else:
        # unused (no tail); a constant table keeps every sampler shape-stable
        tail_keys = np.full(ZIPF_TAIL_POINTS + 1, table - 1, np.uint32)
    return head_cdf, tail_keys


def zipf_keys_np(start: int, count: int, head_cdf: np.ndarray,
                 tail_keys: np.ndarray, domain: int, seed: int) -> np.ndarray:
    """numpy Zipf sampler twin (of datagen.cc fill_zipf and
    :func:`_zipf_range`): pure uint32 ops on the shared tables.

    Draw: u = mix32(index ^ mix32(seed)); head ranks by upper-bound search
    of the scaled CDF; tail ranks by linear interpolation of ``tail_keys``
    with a second mixed draw supplying (segment, fraction) bits."""
    table = len(head_cdf)
    idx = np.arange(start, start + count, dtype=np.uint32)
    with np.errstate(over="ignore"):
        u = mix32_np(idx ^ mix32_np(np.uint32(seed & 0xFFFFFFFF)))
        key = np.minimum(
            np.searchsorted(head_cdf, u, side="right"),
            table - 1).astype(np.uint32)
        if domain > table:
            v = mix32_np(u ^ np.uint32(_ZIPF_V_SALT))
            j = (v >> np.uint32(20)).astype(np.int64)
            frac = (v >> np.uint32(8)) & np.uint32(0xFFF)
            tk = tail_keys[j]
            d = tail_keys[j + 1] - tk
            interp = ((d >> np.uint32(12)) * frac
                      + (((d & np.uint32(0xFFF)) * frac) >> np.uint32(12)))
            s = tk + interp
            # uint32-wrap clamp (domain may sit within 4093 of 2**32):
            # a wrapped sum is detectable as s < tk — same test on device
            k_tail = np.where(s < tk, np.uint32(domain - 1),
                              np.minimum(s, np.uint32(domain - 1)))
            key = np.where(u >= head_cdf[-1], k_tail, key)
    return key


@functools.partial(jax.jit,
                   static_argnames=("n", "domain", "seed", "wide"))
def _zipf_range(start, n: int, head_cdf: jnp.ndarray, tail_keys: jnp.ndarray,
                domain: int, seed: int, wide: bool):
    """Device Zipf sampler twin — bit-identical to :func:`zipf_keys_np`
    (same tables, same uint32 ops; ``searchsorted`` results are
    method-independent).  ``start`` may be a Python int or traced uint32.
    Returns ``(key[, key_hi], rid)`` like ``_device_range``."""
    rid = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(start)
    table = head_cdf.shape[0]
    u = mix32(rid ^ mix32(jnp.uint32(seed & 0xFFFFFFFF)))
    # method="sort": one combined sort instead of per-element binary-search
    # gathers — the TPU-friendly lowering (result is exact either way)
    key = jnp.minimum(
        jnp.searchsorted(head_cdf, u, side="right", method="sort"),
        table - 1).astype(jnp.uint32)
    if domain > table:
        v = mix32(u ^ jnp.uint32(_ZIPF_V_SALT))
        j = (v >> jnp.uint32(20)).astype(jnp.int32)
        frac = (v >> jnp.uint32(8)) & jnp.uint32(0xFFF)
        tk = tail_keys[j]
        d = tail_keys[j + 1] - tk
        interp = ((d >> jnp.uint32(12)) * frac
                  + (((d & jnp.uint32(0xFFF)) * frac) >> jnp.uint32(12)))
        s = tk + interp
        # uint32-wrap clamp, twin of the numpy sampler's
        k_tail = jnp.where(s < tk, jnp.uint32(domain - 1),
                           jnp.minimum(s, jnp.uint32(domain - 1)))
        key = jnp.where(u >= head_cdf[table - 1], k_tail, key)
    return (key, key_hi_lane(key), rid) if wide else (key, rid)


def _feistel_round_np(l, r, k, half_bits):
    mask = (1 << half_bits) - 1
    # Simple multiplicative hash round function (xxhash-style constants).
    f = ((r * 0x9E3779B1 + k) ^ (r >> 7)) & mask
    return r, (l ^ f) & mask


def feistel_permutation_np(idx: np.ndarray, domain_bits: int, seed: int) -> np.ndarray:
    """Seeded bijection on [0, 2**domain_bits) — numpy reference implementation."""
    half = (domain_bits + 1) // 2
    mask = (1 << half) - 1
    l = (idx >> half).astype(np.uint64)
    r = (idx & mask).astype(np.uint64)
    keys = np.random.default_rng(seed).integers(0, 1 << 31, size=_FEISTEL_ROUNDS, dtype=np.uint64)
    for i in range(_FEISTEL_ROUNDS):
        l, r = _feistel_round_np(l, r, keys[i], half)
    out = (l << half) | r
    return out & ((1 << (2 * half)) - 1)


def _feistel_keys(seed: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(0, 1 << 31, size=_FEISTEL_ROUNDS, dtype=np.uint32)


@functools.partial(jax.jit, static_argnames=("domain_bits",))
def _feistel_jax(idx: jnp.ndarray, round_keys: jnp.ndarray, domain_bits: int) -> jnp.ndarray:
    half = (domain_bits + 1) // 2
    mask = jnp.uint32((1 << half) - 1)
    l = (idx >> half).astype(jnp.uint32)
    r = (idx & mask).astype(jnp.uint32)
    for i in range(_FEISTEL_ROUNDS):
        f = ((r * jnp.uint32(0x9E3779B1) + round_keys[i]) ^ (r >> 7)) & mask
        l, r = r, (l ^ f) & mask
    return (l.astype(jnp.uint32) << half) | r


def unique_keys_device(start, count: int, global_size: int, seed: int) -> jnp.ndarray:
    """Shard [start, start+count) of a seeded permutation of [0, global_size),
    computed entirely on device via Feistel + cycle-walking.  ``start`` may be
    a Python int or a traced uint32 scalar (generate_sharded passes the
    per-device ``axis_index``-derived offset).

    Requires domain 2**b >= global_size; indices mapping outside
    [0, global_size) are re-walked until they land inside (expected <= 2 steps
    since the pow2 domain is < 2x the target)."""
    domain_bits = max(2, (global_size - 1).bit_length())
    rk = jnp.asarray(_feistel_keys(seed))
    idx = (jnp.arange(count, dtype=jnp.uint32) + jnp.uint32(start))
    # bind as uint32: a bare Python int >= 2**31 (global_size caps at
    # 2**32 - 1) would overflow JAX's weak-int32 scalar promotion
    gs = jnp.uint32(global_size)

    def body(v):
        out = _feistel_jax(v, rk, domain_bits)
        return jnp.where(v < gs, v, out)  # only walk still-outside values

    def cond(v):
        return jnp.any(v >= gs)

    v = _feistel_jax(idx, rk, domain_bits)
    v = jax.lax.while_loop(cond, body, v)
    return v


def _device_range(start, n: int, global_size: int, seed: int,
                  modulo: Optional[int], wide: bool):
    """Core on-device generator for the global index range
    [start, start+n): ``(key[, key_hi], rid)`` uint32 lanes.  ``modulo=None``
    selects the unique Feistel walk; a value selects dense-rid residues.
    ``start`` may be a Python int or a traced uint32 scalar.  The single
    source of truth for on-device generation — ``Relation.shard``,
    ``Relation.generate_sharded`` and ``streaming.stream_chunks_device`` all
    call it, so the bit-identity contract with the host generators lives in
    one place."""
    rid = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(start)
    if modulo is None:
        key = unique_keys_device(start, n, global_size, seed)
    else:
        key = rid % jnp.uint32(modulo)
    return (key, key_hi_lane(key), rid) if wide else (key, rid)


# NOTE: every distinct (n, global_size, seed, modulo, wide) tuple — i.e.
# every relation spec and every ragged tail-chunk size — compiles its own
# XLA program (the Feistel round-key table is baked in at trace time, which
# is what makes the device twin bit-identical to the host path).  Expected
# and acceptable: sweeps over many tiny relation specs pay a per-spec
# compile; production-shape runs reuse one or two entries (ADVICE r3).
_device_range_jit = jax.jit(
    _device_range,
    static_argnames=("n", "global_size", "seed", "modulo", "wide"))


def device_range(start, n: int, global_size: int, seed: int,
                 modulo: Optional[int], wide: bool):
    """Jitted :func:`_device_range`.  ``start`` is coerced to uint32 before
    the jit boundary: a bare Python int above 2**31 - 1 (reachable — node
    offsets run up to ``global_size``, capped at 2**32 - 1) would otherwise
    overflow JAX's default int32 argument parsing."""
    return _device_range_jit(np.uint32(start), n, global_size, seed,
                             modulo, wide)


class Relation:
    """A logical relation: a global keyspace spec + per-shard generators.

    The reference's ``Relation`` owns one rank's tuple shard backed by ``Pool``
    memory (Relation.cpp:26-37); here the object is a *spec* and ``shard_np`` /
    ``shard`` materialize a given node's slice (host numpy / device jax).
    ``rid`` is the global tuple index, as in the reference where rid is dense
    (Relation.cpp:63-73).
    """

    def __init__(
        self,
        global_size: int,
        num_nodes: int = 1,
        kind: str = "unique",
        seed: int = 1234,
        key_bits: int = 32,
        modulo: Optional[int] = None,
        zipf_theta: Optional[float] = None,
        key_domain: Optional[int] = None,
    ):
        if global_size % num_nodes != 0:
            raise ValueError("global_size must divide evenly across nodes")
        if kind not in ("unique", "modulo", "zipf"):
            raise ValueError(f"unknown relation kind {kind!r}")
        if kind == "modulo" and not modulo:
            raise ValueError("modulo kind requires modulo=")
        if kind == "zipf" and (zipf_theta is None or zipf_theta <= 0):
            raise ValueError("zipf kind requires zipf_theta= > 0")
        if key_bits not in (32, 64):
            raise ValueError("key_bits must be 32 or 64")
        # Deliberate contract: benchmark relations stay within the merge-probe
        # key range so every probe discipline accepts them interchangeably.
        if key_bits == 32 and global_size > (1 << 31) - 2:
            raise ValueError(
                "32-bit keys cap global_size at 2**31 - 2 (31-bit merge-count "
                "packing + sentinel headroom); use key_bits=64 beyond that")
        if key_bits == 64 and global_size > (1 << 32) - 1:
            raise ValueError(
                "global_size caps at 2**32 - 1 (dense uint32 rids)")
        self.global_size = int(global_size)
        self.num_nodes = int(num_nodes)
        self.kind = kind
        self.seed = int(seed)
        self.key_bits = int(key_bits)
        self.modulo = modulo
        self.zipf_theta = zipf_theta
        self.key_domain = int(key_domain) if key_domain else self.global_size
        self._zipf_cache = None   # (head_cdf, tail_keys), built on first use

    def _zipf_tables_cached(self):
        if self._zipf_cache is None:
            self._zipf_cache = zipf_tables(self.zipf_theta, self.key_domain)
        return self._zipf_cache

    @property
    def local_size(self) -> int:
        return self.global_size // self.num_nodes

    def key_bound(self) -> int:
        """Exclusive static upper bound on generated key values — the input
        to the engine's automatic key-range routing (config.key_range
        "auto": bounds <= 2**31-2 keep the packed 31-bit count path).
        unique: a permutation of [0, global_size); modulo: residues below
        min(modulo, global_size); zipf: draws over [0, key_domain).  Wide
        (64-bit) relations report 2**64: they never use the 32-bit packing."""
        if self.key_bits == 64:
            return 1 << 64
        if self.kind == "unique":
            return self.global_size
        if self.kind == "modulo":
            return min(self.modulo, self.global_size)
        return self.key_domain

    # ------------------------------------------------------------------ host
    def fill_np(self, start: int, count: int, num_threads: int = 0,
                out_key: Optional[np.ndarray] = None,
                out_rid: Optional[np.ndarray] = None,
                ) -> Tuple[np.ndarray, np.ndarray]:
        """(keys, rids) for the global index range [start, start+count).

        Uses the native multithreaded generators (native/datagen.cc) when the
        toolchain produced the shared library; the numpy fallbacks are
        bit-identical (same Feistel rounds / same Zipf table + hashing).
        ``out_key``/``out_rid`` (uint32 [count], e.g. memory-pool views from
        ``memory.Pool.get_array``) are filled in place when given — the
        streaming loader reuses two such buffer pairs for arbitrarily large
        relations (data/streaming.py)."""
        lo, n = int(start), int(count)
        lib = _load_native()
        if num_threads <= 0:
            num_threads = min(16, os.cpu_count() or 1)

        def buf(out):
            if out is None:
                return np.empty(n, dtype=np.uint32)
            if (out.shape != (n,) or out.dtype != np.uint32
                    or not out.flags.c_contiguous):
                raise ValueError(f"out buffer must be contiguous uint32 [{n}]")
            return out

        key, rid = buf(out_key), buf(out_rid)
        if lib is not None:
            kp = key.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))
            lib.fill_rids(rid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                          lo, n, num_threads)
        else:
            rid[:] = np.arange(lo, lo + n, dtype=np.uint32)

        if self.kind == "unique":
            domain_bits = max(2, (self.global_size - 1).bit_length())
            if lib is not None:
                rk = np.ascontiguousarray(_feistel_keys(self.seed))
                lib.fill_unique(
                    kp, lo, n, self.global_size, (domain_bits + 1) // 2,
                    rk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                    num_threads)
                return key, rid
            idx = np.arange(lo, lo + n, dtype=np.uint64)
            k = feistel_permutation_np(idx, domain_bits, self.seed)
            while (k >= self.global_size).any():
                out = k >= self.global_size
                k[out] = feistel_permutation_np(k[out], domain_bits, self.seed)
            key[:] = k.astype(np.uint32)
            return key, rid

        if self.kind == "modulo":
            if lib is not None:
                lib.fill_modulo(kp, lo, n, self.modulo, num_threads)
                return key, rid
            key[:] = rid % np.uint32(self.modulo)
            return key, rid

        # zipf: skewed draw over [0, key_domain) — integer tables shared
        # verbatim with the native and device samplers (zipf_tables)
        head_cdf, tail_keys = self._zipf_tables_cached()
        if lib is not None:
            p_u32 = ctypes.POINTER(ctypes.c_uint32)
            lib.fill_zipf(
                kp, lo, n, head_cdf.ctypes.data_as(p_u32), len(head_cdf),
                tail_keys.ctypes.data_as(p_u32), self.key_domain,
                self.seed, num_threads)
            return key, rid
        key[:] = zipf_keys_np(lo, n, head_cdf, tail_keys, self.key_domain,
                              self.seed)
        return key, rid

    def shard_np(self, node: int, num_threads: int = 0) -> Tuple[np.ndarray, ...]:
        """One node's shard as numpy uint32 arrays.

        Contract (the driver's ``HashJoin._place`` consumes this): a 2-tuple
        ``(keys, rids)`` when ``key_bits == 32``; a 3-tuple
        ``(keys_lo, keys_hi, rids)`` when ``key_bits == 64`` — the wide analog
        of the reference's uint64 keys (Tuple.h:19-20) as two uint32 lanes.
        """
        key, rid = self.fill_np(node * self.local_size, self.local_size,
                                num_threads)
        if self.key_bits == 64:
            return key, key_hi_lane_np(key), rid
        return key, rid

    # ---------------------------------------------------------------- device
    def zipf_range_device(self, start, n: int):
        """Device Zipf lanes for the global index range [start, start+n)
        (``(key[, key_hi], rid)``), bit-identical to the host sampler —
        the tables are host-built once (cached) and shipped as uint32
        constants; all sampling arithmetic runs on device."""
        head_cdf, tail_keys = self._zipf_tables_cached()
        return _zipf_range(np.uint32(start), n, jnp.asarray(head_cdf),
                           jnp.asarray(tail_keys), self.key_domain,
                           self.seed, self.key_bits == 64)

    def shard(self, node: int) -> TupleBatch:
        """One node's shard as a device TupleBatch — every kind generates on
        device (unique/modulo: Feistel walk / residues; zipf since r4: the
        integer-table sampler)."""
        lo = node * self.local_size
        if self.kind == "zipf":
            out = self.zipf_range_device(lo, self.local_size)
        else:
            out = device_range(
                lo, self.local_size, self.global_size, self.seed,
                self.modulo if self.kind == "modulo" else None,
                self.key_bits == 64)
        if self.key_bits == 64:
            key, hi, rid = out
            return TupleBatch(key=key, rid=rid, key_hi=hi)
        key, rid = out
        return TupleBatch(key=key, rid=rid, key_hi=None)

    def generate_sharded(self, mesh, axes) -> Optional[TupleBatch]:
        """The whole relation generated **on device**, sharded over ``mesh``
        along ``axes`` (device i holds node i's slice) — no host
        materialization and no host->device transfer (SURVEY.md §7.4 item 5:
        "generate sharded on-device rather than host-side like
        Relation::fillUniqueValues").

        Bit-identical to the ``shard_np`` host path for every kind
        ("unique": same Feistel rounds + cycle walk; "modulo": same
        dense-rid residues; "zipf" since r4: the integer-table sampler —
        host-built uint32 tables, device uint32 arithmetic).  Returns
        ``None`` only for kinds without a device generator (none today;
        the hook remains for future kinds)."""
        if self.kind not in ("unique", "modulo", "zipf"):
            return None
        n = int(np.prod(mesh.devices.shape))
        if n != self.num_nodes:
            raise ValueError(
                f"mesh has {n} devices, relation expects {self.num_nodes}")
        local = self.local_size
        wide = self.key_bits == 64
        gs = self.global_size
        seed = self.seed
        kind = self.kind
        modulo = self.modulo if self.kind == "modulo" else None
        if kind == "zipf":
            head_cdf, tail_keys = self._zipf_tables_cached()
            c_dev = jnp.asarray(head_cdf)
            tk_dev = jnp.asarray(tail_keys)
            domain = self.key_domain
        from jax.sharding import PartitionSpec

        def gen():
            i = jax.lax.axis_index(axes)   # flat rank over the (maybe
            lo = i.astype(jnp.uint32) * jnp.uint32(local)   # hierarchical) mesh
            if kind == "zipf":
                return _zipf_range(lo, local, c_dev, tk_dev, domain, seed,
                                   wide)
            return _device_range(lo, local, gs, seed, modulo, wide)

        spec = PartitionSpec(axes)
        out_specs = (spec, spec, spec) if wide else (spec, spec)
        out = jax.jit(jax.shard_map(
            gen, mesh=mesh, in_specs=(), out_specs=out_specs))()
        if wide:
            key, hi, rid = out
            return TupleBatch(key=key, rid=rid, key_hi=hi)
        key, rid = out
        return TupleBatch(key=key, rid=rid, key_hi=None)

    # ---------------------------------------------------------------- oracle
    def expected_matches(self, outer: "Relation") -> Optional[int]:
        """Closed-form expected |self ⋈ outer| where derivable (SURVEY.md §4.1).

        unique ⋈ unique over the same range -> global_size (the reference's
        oracle, main.cpp:95-98); unique ⋈ modulo/zipf with outer key domain
        covered by the unique range -> outer.global_size.  Returns None when no
        closed form applies (caller should fall back to a host join)."""
        if self.kind != "unique":
            return None
        if outer.kind == "unique" and outer.global_size == self.global_size:
            return self.global_size
        if outer.kind == "modulo" and outer.modulo <= self.global_size:
            return outer.global_size
        if outer.kind == "zipf" and outer.key_domain <= self.global_size:
            return outer.global_size
        return None


def host_join_count(r_keys: np.ndarray, s_keys: np.ndarray) -> int:
    """O((n+m) log) host oracle join count for tests without a closed form."""
    r_sorted = np.sort(r_keys)
    lo = np.searchsorted(r_sorted, s_keys, side="left")
    hi = np.searchsorted(r_sorted, s_keys, side="right")
    return int((hi - lo).sum())
