"""Tuple layout: the TPU-native data model.

Replaces the reference's POD types (``data/Tuple.h:19-20`` — ``{uint64 key;
uint64 rid}`` — and ``data/CompressedTuple.h:18`` — one packed ``uint64``).

TPU-first design: int64 arithmetic is slow/limited on TPU, so tuples are
structure-of-arrays batches of uint32 lanes instead of packed scalars:

  * ``TupleBatch``      — full tuples: ``key`` (low 32 key bits), optional
    ``key_hi`` (upper 32 bits when ``key_bits == 64``), ``rid``.
  * ``CompressedBatch`` — the shuffle wire format.  The reference compresses
    16B -> 8B by dropping the partition bits from the key and packing
    ``value = rid | (key >> FANOUT) << (FANOUT + PAYLOAD_BITS)``
    (``NetworkPartitioning.cpp:128-129``).  We keep the same information
    contract — the partition bits are implied by partition membership and
    reconstructed on unpack — as uint32 lanes: 2 lanes (8B/tuple) for 32-bit
    keys, matching the reference's 8B CompressedTuple on the wire.

Padding sentinels: statically-shaped shuffle blocks carry invalid slots.  A
slot is invalid iff its key lane(s) equal the side's sentinel; inner (R) and
outer (S) sentinels differ so padding can never produce a match.  Real keys
must therefore stay below ``0xFFFFFFFE`` in the top lane (enforced by the
generators in ``relation.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Sentinel key values for padded (invalid) slots, per relation side.
R_PAD_KEY = np.uint32(0xFFFFFFFE)   # inner/build side
S_PAD_KEY = np.uint32(0xFFFFFFFF)   # outer/probe side
PAD_RID = np.uint32(0xFFFFFFFF)


class TupleBatch(NamedTuple):
    """SoA batch of full tuples (analog of ``Tuple[]``, data/Tuple.h)."""

    key: jnp.ndarray                 # uint32 [n] — low 32 key bits
    rid: jnp.ndarray                 # uint32 [n]
    key_hi: Optional[jnp.ndarray] = None   # uint32 [n] when key_bits == 64

    @property
    def size(self) -> int:
        return self.key.shape[-1]


class CompressedBatch(NamedTuple):
    """SoA batch of compressed tuples (analog of ``CompressedTuple[]``).

    ``key_rem`` holds ``key >> network_fanout_bits`` (the surviving key bits,
    BuildProbe.cpp:98-106 compares exactly these); ``key_rem_hi`` the upper
    lane for 64-bit keys.
    """

    key_rem: jnp.ndarray             # uint32 [n]
    rid: jnp.ndarray                 # uint32 [n]
    key_rem_hi: Optional[jnp.ndarray] = None

    @property
    def size(self) -> int:
        return self.key_rem.shape[-1]


def partition_ids(batch: TupleBatch, fanout_bits: int) -> jnp.ndarray:
    """Radix partition id = low ``fanout_bits`` of the key.

    The reference's ``HASH_BIT_MODULO(key, mask, 0)`` (LocalHistogram.cpp:20,
    44-47).  Returns uint32 [n] in [0, 1 << fanout_bits).
    """
    mask = jnp.uint32((1 << fanout_bits) - 1)
    return batch.key & mask


def compress(batch: TupleBatch, fanout_bits: int) -> CompressedBatch:
    """Drop the partition bits from the key (NetworkPartitioning.cpp:128-129).

    The dropped bits are implied by which partition the tuple is routed to and
    are restored by :func:`decompress`.
    """
    f = jnp.uint32(fanout_bits)
    if batch.key_hi is None:
        return CompressedBatch(key_rem=batch.key >> f, rid=batch.rid)
    if fanout_bits == 0:
        return CompressedBatch(batch.key, batch.rid, batch.key_hi)
    lo = (batch.key >> f) | (batch.key_hi << jnp.uint32(32 - fanout_bits))
    hi = batch.key_hi >> f
    return CompressedBatch(key_rem=lo, rid=batch.rid, key_rem_hi=hi)


def decompress(comp: CompressedBatch, pid: jnp.ndarray, fanout_bits: int) -> TupleBatch:
    """Reconstruct full keys from remainder + partition id (inverse of compress)."""
    f = jnp.uint32(fanout_bits)
    if comp.key_rem_hi is None:
        return TupleBatch(key=(comp.key_rem << f) | pid.astype(jnp.uint32), rid=comp.rid)
    if fanout_bits == 0:
        return TupleBatch(comp.key_rem, comp.rid, comp.key_rem_hi)
    lo = (comp.key_rem << f) | pid.astype(jnp.uint32)
    hi = (comp.key_rem_hi << f) | (comp.key_rem >> jnp.uint32(32 - fanout_bits))
    return TupleBatch(key=lo, rid=comp.rid, key_hi=hi)


def probe_key(comp: CompressedBatch) -> jnp.ndarray:
    """The key material compared during probe (``value >> keyShift``,
    BuildProbe.cpp:98-106).  For 64-bit keys returns a [n, 2] (hi, lo) stack
    ordered so lexicographic comparison equals numeric comparison."""
    if comp.key_rem_hi is None:
        return comp.key_rem
    return jnp.stack([comp.key_rem_hi, comp.key_rem], axis=-1)


def pad_sentinel(side: str) -> np.uint32:
    if side == "inner":
        return R_PAD_KEY
    if side == "outer":
        return S_PAD_KEY
    raise ValueError(f"side must be 'inner' or 'outer', got {side!r}")


# TupleBatch and CompressedBatch share a positional layout:
# field 0 = primary key lane, field 1 = rid, field 2 = optional high key lane.
def _sentinel_lane(batch) -> jnp.ndarray:
    return batch[2] if batch[2] is not None else batch[0]


def valid_mask(batch, side: str) -> jnp.ndarray:
    """True for real tuples, False for padding slots (either batch type)."""
    return _sentinel_lane(batch) != pad_sentinel(side)


def make_padding_like(batch, n: int, side: str):
    """A block of n invalid tuples with the same structure as ``batch``."""
    sent = jnp.full((n,), pad_sentinel(side), dtype=jnp.uint32)
    rid = jnp.full((n,), PAD_RID, dtype=jnp.uint32)
    hi = sent if batch[2] is not None else None
    return type(batch)(sent, rid, hi)


def make_padding(n: int, side: str, wide: bool = False) -> CompressedBatch:
    """A block of n invalid compressed tuples."""
    sent = jnp.full((n,), pad_sentinel(side), dtype=jnp.uint32)
    rid = jnp.full((n,), PAD_RID, dtype=jnp.uint32)
    if wide:
        return CompressedBatch(key_rem=sent, rid=rid, key_rem_hi=sent)
    return CompressedBatch(key_rem=sent, rid=rid)


# --------------------------------------------------------------------- wire
# Bounds-aware bit-packed wire format for the shuffle exchange.
#
# After radix partitioning a tuple's low ``fanout_bits`` are implied by its
# partition id, and the sizing pre-pass knows tight key/rid bounds — so most
# shuffles can ship far less than the 8 B/tuple the 2-lane CompressedBatch
# costs (NetworkPartitioning.cpp:128-129 plays the same trick with a fixed
# 64-bit budget; here the budget itself shrinks to the measured bounds).
#
# Block layout (uint32 words), one block per (sender, destination) pair:
#
#   [ header: 2**fanout_bits words — per-partition valid counts ]
#   [ payload: ceil(capacity * tuple_bits / 32) + 1 words        ]
#
# Payload is a dense little-endian bitstream: slot ``s`` occupies bits
# ``[s*T, (s+1)*T)`` with ``T = key_rem_bits + rid_bits``; ``key_rem``
# (the key with fanout bits dropped) sits at offset 0 and ``rid`` at offset
# ``key_rem_bits``.  Senders sort each block by partition id, so the header
# counts let the receiver reconstruct every slot's pid positionally — which
# both restores the dropped key bits exactly and replaces the separate
# valid-count collective (the header IS the count side channel).  Slots at or
# past a block's total count unpack to the side's exact pad sentinels, so
# validity stays decidable from the packed words alone.


class WireSpec(NamedTuple):
    """Static geometry of the packed exchange (host-side, per program)."""

    fanout_bits: int        # radix bits dropped from keys (pid width)
    num_sub: int            # 2**fanout_bits — header words per block
    capacity: int           # tuple slots per block
    wide: bool              # 64-bit keys (key_hi lane present)
    key_rem_bits: int       # bits kept per key after dropping fanout bits
    rid_bits: int           # bits per rid
    tuple_bits: int         # key_rem_bits + rid_bits
    header_words: int       # == num_sub
    payload_words: int      # bitstream words incl. the spill-guard word
    block_words: int        # header_words + payload_words

    @property
    def bytes_per_block(self) -> int:
        return 4 * self.block_words

    @property
    def bytes_per_tuple(self) -> float:
        """Wire bytes per tuple slot (header amortized over the block)."""
        return self.bytes_per_block / self.capacity


def effective_key_bits(key_bound: Optional[int], fanout_bits: int = 0,
                       key_bits: int = 32) -> int:
    """Bits a key can actually occupy given its (exclusive) upper bound.

    ``key_bound`` is exclusive (keys < key_bound); ``None`` means the full
    lane width.  ``fanout_bits`` are the partition-selector bits already
    dropped by the caller (the wire codec shifts them out before packing).
    This is the single source of truth for every bounds-aware width
    decision: the packed exchange codec (``make_wire_spec``) sizes its
    field widths from it, and the Pallas LSD radix sort
    (ops/pallas/radix_sort.py) skips the digit passes it proves constant
    — a 16-bit-bounded key needs 2 of the 4 uint32 passes.
    """
    if not 0 <= fanout_bits < key_bits:
        raise ValueError(
            f"fanout_bits must be in [0, {key_bits}), got {fanout_bits}")
    if key_bound is None:
        return key_bits - fanout_bits
    if key_bound < 1:
        raise ValueError(f"key_bound must be >= 1, got {key_bound}")
    kb = max(1, ((int(key_bound) - 1) >> fanout_bits).bit_length())
    return min(kb, key_bits - fanout_bits)


def make_wire_spec(capacity: int, fanout_bits: int, wide: bool = False,
                   key_bound: Optional[int] = None,
                   rid_bound: Optional[int] = None) -> WireSpec:
    """Derive the packed-block geometry from the (static) bounds.

    ``key_bound``/``rid_bound`` are exclusive upper bounds (keys < key_bound).
    ``None`` falls back to the full lane width — still a win for 32-bit keys
    (the fanout bits drop) and always exact."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    key_bits = 64 if wide else 32
    kb = effective_key_bits(key_bound, fanout_bits, key_bits)
    if rid_bound is None:
        rb = 32
    else:
        if rid_bound < 1:
            raise ValueError(f"rid_bound must be >= 1, got {rid_bound}")
        rb = min(32, max(1, (int(rid_bound) - 1).bit_length()))
    t = kb + rb
    num_sub = 1 << fanout_bits
    # +1 spill-guard word: the last slot's high field may cross into one
    # word past ceil(capacity*T/32) during the shifted scatter-OR
    payload = (capacity * t + 31) // 32 + 1
    return WireSpec(fanout_bits=fanout_bits, num_sub=num_sub,
                    capacity=capacity, wide=wide, key_rem_bits=kb,
                    rid_bits=rb, tuple_bits=t, header_words=num_sub,
                    payload_words=payload,
                    block_words=num_sub + payload)


def _width_mask(width: int) -> jnp.ndarray:
    return jnp.uint32(0xFFFFFFFF if width >= 32 else (1 << width) - 1)


def _wire_fields(spec: WireSpec):
    """(offset_in_tuple, width, lane) triples; lane 0 = key_rem low 32 bits,
    lane 1 = key_rem high bits (wide only), lane 2 = rid.  Every field is
    <= 32 bits so it packs as one shifted uint32 (+ spill into the next
    word)."""
    kb = spec.key_rem_bits
    fields = []
    if kb <= 32:
        fields.append((0, kb, 0))
    else:
        fields.append((0, 32, 0))
        fields.append((32, kb - 32, 1))
    fields.append((kb, spec.rid_bits, 2))
    return fields


def pack_blocks(spec: WireSpec, blocks, group_counts: jnp.ndarray
                ) -> jnp.ndarray:
    """Pack scattered blocks into the wire bitstream.

    ``blocks``: TupleBatch with [num_blocks * capacity] lanes, each block's
    valid tuples contiguous at the front and sorted by partition id (the
    ``scatter_to_blocks_grouped`` contract).  ``group_counts``: uint32
    [num_blocks, 2**fanout_bits] clipped per-(block, pid) counts.  Returns
    uint32 [num_blocks * spec.block_words]."""
    nb = group_counts.shape[0]
    cap = spec.capacity
    f = spec.fanout_bits
    counts = jnp.sum(group_counts.astype(jnp.uint32), axis=1)      # [nb]
    slot = jnp.arange(nb * cap, dtype=jnp.uint32)
    blk = slot // jnp.uint32(cap)
    s_in_blk = slot % jnp.uint32(cap)
    ok = s_in_blk < counts[blk]

    key = blocks.key
    if spec.wide:
        if f:
            lo = (key >> jnp.uint32(f)) | (blocks.key_hi
                                           << jnp.uint32(32 - f))
            hi = blocks.key_hi >> jnp.uint32(f)
        else:
            lo, hi = key, blocks.key_hi
    else:
        lo = key >> jnp.uint32(f) if f else key
        hi = jnp.zeros_like(key)
    lanes = (lo, hi, blocks.rid)

    # init derived from an input lane so the varying-manual-axes type
    # matches inside shard_map bodies (same trick as scatter_to_blocks)
    words = (jnp.zeros((nb * spec.block_words,), jnp.uint32)
             + (key[0] & jnp.uint32(0)))
    # header region: the per-(block, pid) counts
    hidx = (jnp.arange(nb, dtype=jnp.uint32)[:, None]
            * jnp.uint32(spec.block_words)
            + jnp.arange(spec.num_sub, dtype=jnp.uint32)[None, :]).reshape(-1)
    words = words.at[hidx].add(group_counts.astype(jnp.uint32).reshape(-1),
                               mode="drop")
    base = blk * jnp.uint32(spec.block_words) + jnp.uint32(spec.header_words)
    for off, width, lane_i in _wire_fields(spec):
        v = jnp.where(ok, lanes[lane_i] & _width_mask(width), jnp.uint32(0))
        bitpos = s_in_blk * jnp.uint32(spec.tuple_bits) + jnp.uint32(off)
        widx = base + bitpos // jnp.uint32(32)
        boff = bitpos % jnp.uint32(32)
        # disjoint bit ranges make scatter-add equivalent to scatter-OR
        words = words.at[widx].add(v << boff, mode="drop")
        spill = jnp.where(boff == 0, jnp.uint32(0),
                          v >> ((jnp.uint32(32) - boff) & jnp.uint32(31)))
        words = words.at[widx + 1].add(spill, mode="drop")
    return words


def unpack_blocks(spec: WireSpec, words: jnp.ndarray, side: str):
    """Exact inverse of :func:`pack_blocks` on received wire words.

    Returns ``(TupleBatch with [num_blocks * capacity] lanes, counts uint32
    [num_blocks])``.  Valid slots reproduce the packed tuples bit-exactly
    (partition ids reconstructed positionally from the header counts); slots
    at or past each block's count are the side's exact pad sentinels."""
    if words.shape[0] % spec.block_words:
        raise ValueError(
            f"wire buffer of {words.shape[0]} words is not a multiple of "
            f"block_words={spec.block_words}")
    nb = words.shape[0] // spec.block_words
    cap = spec.capacity
    f = spec.fanout_bits
    hidx = (jnp.arange(nb, dtype=jnp.uint32)[:, None]
            * jnp.uint32(spec.block_words)
            + jnp.arange(spec.num_sub, dtype=jnp.uint32)[None, :])
    group_counts = words[hidx]                                   # [nb, P]
    counts = jnp.sum(group_counts, axis=1)                       # [nb]
    # positional pid: slot s of block b belongs to the first partition whose
    # within-block cumulative count exceeds s (blocks are pid-sorted)
    cum = jnp.cumsum(group_counts, axis=1)
    slot_in_blk = jnp.arange(cap, dtype=jnp.uint32)
    pid = jax.vmap(
        lambda c: jnp.searchsorted(c, slot_in_blk, side="right"))(cum)
    pid = jnp.minimum(pid, spec.num_sub - 1).astype(jnp.uint32).reshape(-1)

    slot = jnp.arange(nb * cap, dtype=jnp.uint32)
    blk = slot // jnp.uint32(cap)
    s_in_blk = slot % jnp.uint32(cap)
    ok = s_in_blk < counts[blk]
    base = blk * jnp.uint32(spec.block_words) + jnp.uint32(spec.header_words)
    nwords = jnp.uint32(words.shape[0] - 1)
    lanes = [None, None, None]
    for off, width, lane_i in _wire_fields(spec):
        bitpos = s_in_blk * jnp.uint32(spec.tuple_bits) + jnp.uint32(off)
        widx = base + bitpos // jnp.uint32(32)
        boff = bitpos % jnp.uint32(32)
        lo = words[widx] >> boff
        hi_w = words[jnp.minimum(widx + 1, nwords)]
        hi = jnp.where(boff == 0, jnp.uint32(0),
                       hi_w << ((jnp.uint32(32) - boff) & jnp.uint32(31)))
        lanes[lane_i] = (lo | hi) & _width_mask(width)
    lo = lanes[0] if lanes[0] is not None else jnp.zeros_like(slot)
    hi = lanes[1] if lanes[1] is not None else jnp.zeros_like(slot)
    rid = lanes[2]

    sent = pad_sentinel(side)
    if spec.wide:
        if f:
            key = (lo << jnp.uint32(f)) | pid
            key_hi = (hi << jnp.uint32(f)) | (lo >> jnp.uint32(32 - f))
        else:
            key, key_hi = lo, hi
        key = jnp.where(ok, key, sent)
        key_hi = jnp.where(ok, key_hi, sent)
    else:
        key = (lo << jnp.uint32(f)) | pid if f else lo
        key = jnp.where(ok, key, sent)
        key_hi = None
    rid = jnp.where(ok, rid, PAD_RID)
    return TupleBatch(key=key, rid=rid, key_hi=key_hi), counts
