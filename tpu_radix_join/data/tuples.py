"""Tuple layout: the TPU-native data model.

Replaces the reference's POD types (``data/Tuple.h:19-20`` — ``{uint64 key;
uint64 rid}`` — and ``data/CompressedTuple.h:18`` — one packed ``uint64``).

TPU-first design: int64 arithmetic is slow/limited on TPU, so tuples are
structure-of-arrays batches of uint32 lanes instead of packed scalars:

  * ``TupleBatch``      — full tuples: ``key`` (low 32 key bits), optional
    ``key_hi`` (upper 32 bits when ``key_bits == 64``), ``rid``.
  * ``CompressedBatch`` — the shuffle wire format.  The reference compresses
    16B -> 8B by dropping the partition bits from the key and packing
    ``value = rid | (key >> FANOUT) << (FANOUT + PAYLOAD_BITS)``
    (``NetworkPartitioning.cpp:128-129``).  We keep the same information
    contract — the partition bits are implied by partition membership and
    reconstructed on unpack — as uint32 lanes: 2 lanes (8B/tuple) for 32-bit
    keys, matching the reference's 8B CompressedTuple on the wire.

Padding sentinels: statically-shaped shuffle blocks carry invalid slots.  A
slot is invalid iff its key lane(s) equal the side's sentinel; inner (R) and
outer (S) sentinels differ so padding can never produce a match.  Real keys
must therefore stay below ``0xFFFFFFFE`` in the top lane (enforced by the
generators in ``relation.py``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

# Sentinel key values for padded (invalid) slots, per relation side.
R_PAD_KEY = np.uint32(0xFFFFFFFE)   # inner/build side
S_PAD_KEY = np.uint32(0xFFFFFFFF)   # outer/probe side
PAD_RID = np.uint32(0xFFFFFFFF)


class TupleBatch(NamedTuple):
    """SoA batch of full tuples (analog of ``Tuple[]``, data/Tuple.h)."""

    key: jnp.ndarray                 # uint32 [n] — low 32 key bits
    rid: jnp.ndarray                 # uint32 [n]
    key_hi: Optional[jnp.ndarray] = None   # uint32 [n] when key_bits == 64

    @property
    def size(self) -> int:
        return self.key.shape[-1]


class CompressedBatch(NamedTuple):
    """SoA batch of compressed tuples (analog of ``CompressedTuple[]``).

    ``key_rem`` holds ``key >> network_fanout_bits`` (the surviving key bits,
    BuildProbe.cpp:98-106 compares exactly these); ``key_rem_hi`` the upper
    lane for 64-bit keys.
    """

    key_rem: jnp.ndarray             # uint32 [n]
    rid: jnp.ndarray                 # uint32 [n]
    key_rem_hi: Optional[jnp.ndarray] = None

    @property
    def size(self) -> int:
        return self.key_rem.shape[-1]


def partition_ids(batch: TupleBatch, fanout_bits: int) -> jnp.ndarray:
    """Radix partition id = low ``fanout_bits`` of the key.

    The reference's ``HASH_BIT_MODULO(key, mask, 0)`` (LocalHistogram.cpp:20,
    44-47).  Returns uint32 [n] in [0, 1 << fanout_bits).
    """
    mask = jnp.uint32((1 << fanout_bits) - 1)
    return batch.key & mask


def compress(batch: TupleBatch, fanout_bits: int) -> CompressedBatch:
    """Drop the partition bits from the key (NetworkPartitioning.cpp:128-129).

    The dropped bits are implied by which partition the tuple is routed to and
    are restored by :func:`decompress`.
    """
    f = jnp.uint32(fanout_bits)
    if batch.key_hi is None:
        return CompressedBatch(key_rem=batch.key >> f, rid=batch.rid)
    if fanout_bits == 0:
        return CompressedBatch(batch.key, batch.rid, batch.key_hi)
    lo = (batch.key >> f) | (batch.key_hi << jnp.uint32(32 - fanout_bits))
    hi = batch.key_hi >> f
    return CompressedBatch(key_rem=lo, rid=batch.rid, key_rem_hi=hi)


def decompress(comp: CompressedBatch, pid: jnp.ndarray, fanout_bits: int) -> TupleBatch:
    """Reconstruct full keys from remainder + partition id (inverse of compress)."""
    f = jnp.uint32(fanout_bits)
    if comp.key_rem_hi is None:
        return TupleBatch(key=(comp.key_rem << f) | pid.astype(jnp.uint32), rid=comp.rid)
    if fanout_bits == 0:
        return TupleBatch(comp.key_rem, comp.rid, comp.key_rem_hi)
    lo = (comp.key_rem << f) | pid.astype(jnp.uint32)
    hi = (comp.key_rem_hi << f) | (comp.key_rem >> jnp.uint32(32 - fanout_bits))
    return TupleBatch(key=lo, rid=comp.rid, key_hi=hi)


def probe_key(comp: CompressedBatch) -> jnp.ndarray:
    """The key material compared during probe (``value >> keyShift``,
    BuildProbe.cpp:98-106).  For 64-bit keys returns a [n, 2] (hi, lo) stack
    ordered so lexicographic comparison equals numeric comparison."""
    if comp.key_rem_hi is None:
        return comp.key_rem
    return jnp.stack([comp.key_rem_hi, comp.key_rem], axis=-1)


def pad_sentinel(side: str) -> np.uint32:
    if side == "inner":
        return R_PAD_KEY
    if side == "outer":
        return S_PAD_KEY
    raise ValueError(f"side must be 'inner' or 'outer', got {side!r}")


# TupleBatch and CompressedBatch share a positional layout:
# field 0 = primary key lane, field 1 = rid, field 2 = optional high key lane.
def _sentinel_lane(batch) -> jnp.ndarray:
    return batch[2] if batch[2] is not None else batch[0]


def valid_mask(batch, side: str) -> jnp.ndarray:
    """True for real tuples, False for padding slots (either batch type)."""
    return _sentinel_lane(batch) != pad_sentinel(side)


def make_padding_like(batch, n: int, side: str):
    """A block of n invalid tuples with the same structure as ``batch``."""
    sent = jnp.full((n,), pad_sentinel(side), dtype=jnp.uint32)
    rid = jnp.full((n,), PAD_RID, dtype=jnp.uint32)
    hi = sent if batch[2] is not None else None
    return type(batch)(sent, rid, hi)


def make_padding(n: int, side: str, wide: bool = False) -> CompressedBatch:
    """A block of n invalid compressed tuples."""
    sent = jnp.full((n,), pad_sentinel(side), dtype=jnp.uint32)
    rid = jnp.full((n,), PAD_RID, dtype=jnp.uint32)
    if wide:
        return CompressedBatch(key_rem=sent, rid=rid, key_rem_hi=sent)
    return CompressedBatch(key_rem=sent, rid=rid)
