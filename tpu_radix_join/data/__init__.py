from tpu_radix_join.data.tuples import TupleBatch, CompressedBatch
from tpu_radix_join.data.relation import Relation

__all__ = ["TupleBatch", "CompressedBatch", "Relation"]
