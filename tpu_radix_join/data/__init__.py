from tpu_radix_join.data.tuples import TupleBatch, CompressedBatch
from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.streaming import stream_chunks

__all__ = ["TupleBatch", "CompressedBatch", "Relation", "stream_chunks"]
