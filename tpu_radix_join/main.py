"""Driver CLI: the ``main.cpp`` analog, with runtime flags.

The reference hard-codes its workload (20M tuples/node, seed 1234+rank,
main.cpp:70-71,94) and parses no arguments (main.cpp:28); every knob is a
compile-time constant.  Here the same driver flow — init measurements, size
the pool, generate relations, run the join, aggregate + store results
(main.cpp:28-149) — is a proper CLI over the typed JoinConfig.

Usage:
    python -m tpu_radix_join.main --tuples-per-node 1048576 --nodes 1
    python -m tpu_radix_join.main --nodes 8 --outer-kind zipf --zipf-theta 0.75 \
        --assignment load_aware
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_radix_join",
        description="Distributed radix hash join on a TPU mesh")
    p.add_argument("--tuples-per-node", type=int, default=1 << 20,
                   help="tuples per node per relation (reference: 20M, main.cpp:70)")
    p.add_argument("--nodes", type=int, default=0,
                   help="mesh size (0 = all visible devices)")
    p.add_argument("--hosts", type=int, default=1,
                   help="hosts in the mesh; >1 builds the hierarchical "
                        "(dcn, ici) mesh with the two-stage shuffle")
    p.add_argument("--network-fanout", type=int, default=5,
                   help="network radix bits (Configuration.h:30)")
    p.add_argument("--local-fanout", type=int, default=5)
    p.add_argument("--two-level", action="store_true",
                   help="enable second-level partitioning (Configuration.h:28)")
    p.add_argument("--probe", choices=["sort", "bucket"], default="sort")
    p.add_argument("--assignment", choices=["round_robin", "load_aware"],
                   default="round_robin")
    p.add_argument("--window-sizing", choices=["measured", "static"],
                   default="measured")
    p.add_argument("--chunk-size", type=int, default=None,
                   help="stream the probe in slabs of this many tuples "
                        "(out-of-core LD mode)")
    p.add_argument("--max-retries", type=int, default=0,
                   help="capacity-shortfall retries with doubled shapes")
    p.add_argument("--retry-backoff", type=float, default=0.0,
                   help="seconds to pause before the first capacity retry "
                        "(doubles each attempt, robustness/retry.py); 0 = "
                        "immediate")
    p.add_argument("--fallback", choices=["none", "chunked"], default="none",
                   help="after max-retries capacity doublings still "
                        "overflow: 'chunked' degrades to the out-of-core "
                        "count instead of returning ok=False")
    p.add_argument("--verify", choices=["off", "check", "repair"],
                   default="off",
                   help="end-to-end integrity verification (robustness/"
                        "verify.py): per-partition count/sum/xor checksums "
                        "of the key lanes, computed before the exchange and "
                        "re-derived after it (and after the local radix "
                        "pass on the bucket path).  'check' fails a "
                        "mismatched join with failure_class="
                        "data_corruption; 'repair' recomputes only the "
                        "damaged partitions out-of-core and returns the "
                        "corrected count (VREPAIR counter)")
    p.add_argument("--exchange-codec", choices=["off", "pack", "auto"],
                   default="off",
                   help="shuffle wire codec (data/tuples.make_wire_spec): "
                        "'pack' bit-packs key remainders + rids to the "
                        "bounds measured by the sizing pre-pass and folds "
                        "the count side channel into the packed header "
                        "(one collective per relation per exchange); "
                        "'auto' packs only when the packed block beats the "
                        "raw 8/12 B lanes")
    p.add_argument("--exchange-stages", type=int, default=1, metavar="K",
                   help="staged exchange (parallel/window.py): split each "
                        "[N, C] block buffer into K column groups exchanged "
                        "by K sequenced collectives, bounding live exchange "
                        "memory to ~1/K.  1 = fused single collective, "
                        "0 = auto (stage 4-ways once blocks are >= 4096 "
                        "slots)")
    p.add_argument("--partition-impl",
                   choices=["auto", "sort", "pallas", "pallas_interpret"],
                   default="auto",
                   help="partition/reorder implementation (ops/radix.py): "
                        "'auto' takes the fused Pallas histogram-scan-"
                        "scatter kernel when the backend compiles Mosaic "
                        "and the fanout fits, else the XLA sort path "
                        "(fallback ticks PARTFALLBACK and logs once); "
                        "'sort' forces the sort-based scatter; 'pallas"
                        "_interpret' runs the kernel interpreted (CPU "
                        "parity/bench)")
    p.add_argument("--sort-impl",
                   choices=["auto", "xla", "pallas", "pallas_interpret"],
                   default="auto",
                   help="sort implementation behind every hot reorder "
                        "(ops/sorting.py): 'auto' takes the Pallas LSD "
                        "radix sort (ops/pallas/radix_sort.py) on a TPU "
                        "backend for large 1-D uint32 sorts — fewer digit "
                        "passes when key bounds shrink the effective "
                        "width — else lax.sort (the degrade ticks "
                        "SORTFALLBACK once per process and logs once); "
                        "'xla' forces lax.sort; 'pallas_interpret' runs "
                        "the kernel interpreted (CPU parity/bench)")
    p.add_argument("--cpu-fallback", action="store_true",
                   help="if device/mesh init fails, rebuild the engine over "
                        "host CPU devices (loud [DEGRADE] warning) instead "
                        "of aborting")
    p.add_argument("--grid-chunk-tuples", type=int, default=None,
                   help="run the out-of-core grid join (ops/chunked.py) "
                        "streaming both relations in chunks of this many "
                        "tuples; single-node only")
    p.add_argument("--grid-pipeline", choices=["off", "on", "auto"],
                   default="auto",
                   help="out-of-core grid engine: 'on' overlaps chunk "
                        "prefetch, probe compute, host readbacks, and "
                        "checkpoint flushes (inner chunks sorted once per "
                        "grid row); 'off' keeps the synchronous "
                        "one-pair-at-a-time loop (the A/B lever); 'auto' "
                        "pipelines any grid larger than one chunk pair "
                        "(planner plans may override auto)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="grid mode: directory for the slab-boundary "
                        "checkpoint file (atomic save after every chunk "
                        "pair; see --resume)")
    p.add_argument("--resume", action="store_true",
                   help="grid mode: resume from the checkpoint in "
                        "--checkpoint-dir (default: a fresh run removes any "
                        "stale checkpoint first)")
    p.add_argument("--skew-threshold", type=float, default=None,
                   help="split partitions heavier than this multiple of the "
                        "mean (replicate inner / spread outer); off by default")
    p.add_argument("--debug-checks", action="store_true",
                   help="per-partition conservation invariants "
                        "(JOIN_ASSERT analog; extra passes)")
    p.add_argument("--transfer-guard", choices=["off", "log", "disallow"],
                   default="off",
                   help="arm jax.transfer_guard around the join: 'log' "
                        "prints every implicit device<->host transfer, "
                        "'disallow' raises on one — the runtime twin of "
                        "tools_lint.py's static sync-point rule (explicit "
                        "utils.hostsync.host_readback stays legal under "
                        "both; data generation/placement is outside the "
                        "guard, matching the reference timing bracket)")
    p.add_argument("--measure-phases", action="store_true",
                   help="run shuffle and probe as separate programs so "
                        ".perf carries JMPI and JPROC columns (costs the "
                        "cross-phase fusion)")
    p.add_argument("--generation", choices=["auto", "host", "device"],
                   default="auto",
                   help="relation materialization: on-device sharded "
                        "generation when supported (auto/device) or host "
                        "numpy + transfer (host)")
    p.add_argument("--key-range", choices=["auto", "narrow", "full"],
                   default="auto",
                   help="32-bit count-path key discipline: 'narrow' packs "
                        "key+side into one uint32 (keys < 2^31-2, fastest), "
                        "'full' takes every sub-sentinel uint32 key via the "
                        "2-key lexicographic sort (~1.7x), 'auto' decides "
                        "from the generated relations' static key bounds")
    p.add_argument("--outer-kind", choices=["unique", "modulo", "zipf"],
                   default="unique")
    p.add_argument("--modulo", type=int, default=None)
    p.add_argument("--zipf-theta", type=float, default=0.75)
    p.add_argument("--seed", type=int, default=1234,
                   help="base seed (reference: srand(1234+nodeId), main.cpp:94)")
    p.add_argument("--output-dir", default=None,
                   help="experiment dir for .perf/.info files (default: none)")
    p.add_argument("--timeline-dir", default=None,
                   help="export this rank's phase spans + robustness/planner "
                        "instant events as Chrome trace-event JSON "
                        "(<rank>.spans.json; merge ranks with "
                        "tools_make_report.py --emit-timeline, load in "
                        "Perfetto)")
    p.add_argument("--metrics-interval", type=float, default=0.0,
                   metavar="SEC",
                   help="sample host RSS, device HBM bytes_in_use, and the "
                        "counter registry every SEC seconds into "
                        "<rank>.metrics.jsonl under --timeline-dir (or "
                        "--output-dir); 0 = off")
    p.add_argument("--trace", action="store_true",
                   help="bracket the joins with the profiler (the PAPI "
                        "total-cycles analog, Measurements.cpp:90-107,137): "
                        "CTOTAL lands in .perf and the per-op device table "
                        "in .info; requires --output-dir")
    def positive_int(v):
        iv = int(v)
        if iv < 1:
            raise argparse.ArgumentTypeError("must be >= 1")
        return iv

    p.add_argument("--repeat", type=positive_int, default=1)
    p.add_argument("--plan", default=None, metavar="auto|explain|FILE",
                   help="planner mode (tpu_radix_join.planner): 'auto' "
                        "costs every execution discipline against the "
                        "--profile constants and applies the cheapest "
                        "feasible one; 'explain' prints the per-strategy "
                        "predicted-cost table and exits; a path loads a "
                        "previously saved JoinPlan JSON verbatim")
    p.add_argument("--plan-cache-dir", default=None,
                   help="persist chosen plans AND the engine's converged "
                        "window capacities here (atomic, fingerprinted): a "
                        "warm second run skips planning and the sizing "
                        "pre-pass; invalidated when the profile, shapes, or "
                        "config change")
    p.add_argument("--profile", default="v5e_lite",
                   help="device profile for the planner: a packaged name "
                        "(profiles/*.json), a JSON path (e.g. from "
                        "tools_make_report.py --emit-profile or "
                        "tools_profile_fit.py), or 'auto' — prefer the "
                        "ledger's fitted profile_fitted.json while fresh, "
                        "else the committed snapshot")
    p.add_argument("--ledger-dir", default=None,
                   help="append this run's distilled telemetry (phase "
                        "times, counters, plan-vs-actual, fingerprint) to "
                        "the cross-run ledger here at exit "
                        "(observability/ledger.py; default: "
                        "$TPU_RADIX_LEDGER_DIR, else off).  The ledger "
                        "feeds tools_profile_fit.py and --profile auto")
    p.add_argument("--serve", default=None, metavar="FILE",
                   help="resident service mode (tpu_radix_join.service): "
                        "read one JSON query request per line from FILE "
                        "('-' = stdin), run them all through ONE JoinSession "
                        "(mesh, compiled programs, and converged capacities "
                        "stay warm across queries), and print one outcome "
                        "JSON line per query plus a final summary line with "
                        "the SLO percentiles")
    p.add_argument("--serve-batch", type=int, default=1, metavar="N",
                   help="serve mode: submit N requests before draining "
                        "(default 1 = closed loop; larger batches exercise "
                        "queue depth and tenant quotas)")
    p.add_argument("--serve-queue-depth", type=int, default=64,
                   help="serve mode: admission queue depth bound "
                        "(exceeded -> admission_rejected/queue_full)")
    p.add_argument("--serve-tenant-quota", type=int, default=8,
                   help="serve mode: max in-flight queries per tenant "
                        "(exceeded -> admission_rejected/tenant_quota)")
    p.add_argument("--serve-deadline-s", type=float, default=None,
                   metavar="SEC",
                   help="serve mode: default per-query latency budget "
                        "(requests may override with their own deadline_s; "
                        "expiry -> deadline_exceeded)")
    p.add_argument("--result-cache", type=int, default=0, metavar="N",
                   help="serve/fleet mode: relation-fingerprint result "
                        "cache of N entries (service/resultcache.py) — "
                        "repeated queries over unchanged relation content "
                        "short-circuit before admission, stamped "
                        "served_by=cache_hit (default 0 = off)")
    p.add_argument("--result-cache-ttl-s", type=float, default=None,
                   metavar="SEC",
                   help="serve/fleet mode: expire result-cache entries "
                        "older than SEC (default: no TTL)")
    p.add_argument("--batch-window-ms", type=float, default=0.0,
                   metavar="MS",
                   help="serve/fleet mode: coalesce co-batchable queries "
                        "arriving within MS into ONE fused device program "
                        "(service/microbatch.py + ops/merge_delta.py); the "
                        "fleet router additionally keys on the batch "
                        "signature so co-batchable tenants share a worker "
                        "(default 0 = off)")
    p.add_argument("--batch-max", type=int, default=8, metavar="N",
                   help="serve/fleet mode: max queries fused into one "
                        "micro-batch (default 8)")
    p.add_argument("--place-cache-max", type=int, default=8, metavar="N",
                   help="serve mode: placed-relation LRU entries kept "
                        "device-resident per session (default 8; placed "
                        "bytes surface in heartbeats and --statusz)")
    p.add_argument("--resident-budget-mb", type=float, default=0.0,
                   metavar="MB",
                   help="serve mode: HBM budget for device-resident sorted "
                        "inner lanes (service/resident.py) — incremental "
                        "requests (delta_tuples_per_node > 0) then sort "
                        "only their delta and merge in O(N+Δ), stamped "
                        "served_by=delta_merge (default 0 = off)")
    p.add_argument("--fleet", type=int, default=None, metavar="N",
                   help="crash-only fleet serving (service/fleet.py): "
                        "supervise N --serve worker subprocesses, route "
                        "queries by consistent hash on tenant, health-check "
                        "workers by lease heartbeat (two missed beats = "
                        "lapse, the rank-lapse rule), restart dead workers "
                        "with exponential backoff + a crash-loop breaker, "
                        "and guarantee exactly-once outcomes through the "
                        "durable query journal (intent before dispatch, "
                        "outcome before reply, replay on death); SIGTERM "
                        "drains gracefully.  Requires --serve FILE|-; "
                        "--statusz gains a fleet section and a readiness-"
                        "aware /healthz")
    p.add_argument("--fleet-dir", default=None,
                   help="fleet work dir: the query journal plus per-worker "
                        "lease/timeline artifacts live here (default: "
                        "fleet/ under --output-dir or --timeline-dir, else "
                        "a private tempdir — restart the supervisor over "
                        "the SAME dir to replay unacknowledged intents)")
    p.add_argument("--fleet-kill-at", type=int, default=None, metavar="N",
                   help="arm the fleet.worker_kill chaos site at the N-th "
                        "dispatched query (1-based): the routed worker is "
                        "SIGKILLed right after the request hits its pipe, "
                        "and the supervisor must journal-replay it on a "
                        "healthy worker (seeded from --seed, mirrors "
                        "--rank-death-at)")
    p.add_argument("--statusz", type=int, default=None, metavar="PORT",
                   help="serve mode: expose a read-only live-introspection "
                        "HTTP endpoint on 127.0.0.1:PORT "
                        "(observability/statusz.py): GET /statusz returns a "
                        "JSON snapshot of the current phase + open spans, "
                        "counter registry, SLO/breaker/queue state, lease "
                        "board + membership epoch, straggler/hedge posture, "
                        "and the last few per-query critical paths; "
                        "/statusz/<section> returns one section, /healthz "
                        "liveness; 0 = pick an ephemeral port (printed on "
                        "stderr)")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="serve mode: consecutive backend failures that trip "
                        "the circuit breaker onto the degraded CPU engine")
    p.add_argument("--breaker-cooldown-s", type=float, default=30.0,
                   help="serve mode: seconds the breaker stays open before "
                        "half-opening for a primary health probe")
    p.add_argument("--watchdog-timeout", type=float, default=0.0,
                   metavar="SEC",
                   help="hang watchdog (observability/watchdog.py): when "
                        "the flight recorder sees no progress for SEC "
                        "seconds while a phase timer is open, dump every "
                        "thread's stack + the ring into a forensics bundle "
                        "and kill the join through the engine cancel hook "
                        "(classified backend_unavailable); 0 = off")
    p.add_argument("--forensics-dir", default=None,
                   help="directory for post-mortem forensics bundles "
                        "(observability/postmortem.py): any terminal "
                        "classified failure or watchdog trip writes a "
                        "self-contained bundle_*.json here (default: "
                        "$TPU_RADIX_FORENSICS_DIR, else forensics/ under "
                        "--output-dir or --timeline-dir when one is set)")
    p.add_argument("--elastic", choices=["on", "off"], default="off",
                   help="elastic mesh recovery (robustness/membership.py + "
                        "recovery.py): heartbeat an epoch-stamped lease per "
                        "rank, detect peer loss at phase boundaries, fence "
                        "the membership epoch, and finish the join on the "
                        "survivor mesh by recomputing only the lost "
                        "partitions host-side — a rank death becomes a "
                        "recovered, oracle-exact run instead of a hang")
    p.add_argument("--rank-lease-s", type=float, default=5.0, metavar="SEC",
                   help="membership lease window: a rank whose lease file "
                        "is older than SEC seconds is declared lost and the "
                        "membership epoch fences (default 5.0)")
    p.add_argument("--lease-dir", default=None,
                   help="shared directory for membership lease files "
                        "(default: $TPU_RADIX_LEASE_DIR, else leases/ under "
                        "--output-dir or --timeline-dir, else a private "
                        "tempdir — multi-process runs must share one)")
    p.add_argument("--rank-death-at", type=int, default=None, metavar="N",
                   help="arm the membership.rank_death chaos site at the "
                        "N-th phase boundary (1-based): with "
                        "TPU_RJ_RANK_DEATH_SUICIDE set this process dies "
                        "for real (SIGKILL, the multi-rank recovery test's "
                        "victim); otherwise the highest node rank's death "
                        "is simulated and --elastic on recovers it")
    p.add_argument("--rank-missed-beats", type=int, default=2, metavar="N",
                   help="lapse threshold in missed heartbeats: a lease is "
                        "declared lost only after N full lease windows of "
                        "silence (lapse window = N x --rank-lease-s; "
                        "default 2 — one missed beat never kills a rank)")
    p.add_argument("--elastic-grow", action="store_true",
                   help="admit joining ranks mid-run (rank admission, "
                        "robustness/membership.py): a newcomer's 'joining' "
                        "lease is admitted at the next phase boundary with "
                        "a fenced epoch bump, and the next epoch's recovery "
                        "plan re-expands partition assignment onto it; "
                        "requires --elastic on")
    p.add_argument("--elastic-join", type=int, default=None, metavar="N",
                   help="run as a JOINING process against an N-rank "
                        "incumbent world (the growth half's newcomer): "
                        "write a joining lease under the shared "
                        "--lease-dir, wait for admission (an incumbent "
                        "epoch bump), then recompute this rank's share of "
                        "unfinished partitions through the shared "
                        "--checkpoint-dir manifest; mutually exclusive "
                        "with driving a join")
    p.add_argument("--hedge", choices=["on", "off", "auto"], default="off",
                   help="straggler hedging (robustness/straggler.py): when "
                        "a live rank's manifest progress falls below "
                        "--hedge-threshold x the median for two "
                        "consecutive boundary checks, speculatively "
                        "recompute its unfinished partitions; the manifest "
                        "fence (first writer wins) keeps speculation from "
                        "double-counting; 'auto' backs off while "
                        "SPECWASTE > HEDGEWIN")
    p.add_argument("--hedge-threshold", type=float, default=0.5,
                   metavar="F",
                   help="relative-progress straggler threshold: hedge when "
                        "slowest < F x median partitions done (default "
                        "0.5; must be in (0, 1))")
    p.add_argument("--straggle-factor", type=float, default=0.0,
                   metavar="F",
                   help="arm the compute.straggle chaos site: the highest "
                        "node rank stalls for F x TPU_RJ_STRAGGLE_UNIT_S "
                        "at the first phase boundary — the hedging "
                        "benchmark's slow-rank model (0 = off)")
    p.add_argument("--rank-join-at", type=int, default=None, metavar="N",
                   help="arm the membership.rank_join chaos site at the "
                        "N-th phase boundary (1-based): a synthetic "
                        "joining lease appears beyond the boot world and "
                        "--elastic-grow admits it — the single-process "
                        "growth test's newcomer")
    p.add_argument("--pipeline-repeats", action="store_true",
                   help="dispatch the --repeat joins asynchronously and "
                        "fence once (amortized-throughput methodology, "
                        "bench.py): removes the ~100ms/join host dispatch "
                        "round-trip from the reported rate; no per-join "
                        "retry loop")
    return p


def _forensics_dir(args):
    """Resolve where forensics bundles land: explicit flag, then the
    environment, then a ``forensics/`` subdir of whichever artifact dir
    the run already writes — None (no bundles) only when the run has no
    artifact dir at all."""
    import os

    d = (args.forensics_dir
         or os.environ.get("TPU_RADIX_FORENSICS_DIR")
         or (os.path.join(args.output_dir, "forensics")
             if args.output_dir else None)
         or (os.path.join(args.timeline_dir, "forensics")
             if args.timeline_dir else None))
    return d


def _lease_dir(args):
    """Where membership lease files live: explicit flag, then the
    environment, then ``leases/`` under whichever artifact dir the run
    already writes, else a private tempdir (fine single-process; a
    multi-process world must share one via the flag or env)."""
    import os
    import tempfile

    return (args.lease_dir
            or os.environ.get("TPU_RADIX_LEASE_DIR")
            or (os.path.join(args.output_dir, "leases")
                if args.output_dir else None)
            or (os.path.join(args.timeline_dir, "leases")
                if args.timeline_dir else None)
            or tempfile.mkdtemp(prefix="tpu_rj_leases_"))


def _trace_identity(args, rank):
    """Join-level trace id shared by every rank of one distributed run.

    Rank 0 mints the id and publishes it through the shared lease dir —
    the only cross-rank side channel that exists before the mesh does;
    peers adopt it by polling for the file (with a freshness fence so a
    previous run's stale file is never adopted).  Every rank's span
    export, ledger row, and forensics bundle then carries ONE
    correlation key, which is what lets tools_critical_path.py group a
    directory of span files into a single join.  A peer that never sees
    the file falls back to minting locally with a warning — correlation
    degrades, the run does not."""
    import os
    import tempfile
    import time

    from tpu_radix_join.observability.spans import _new_trace_id

    lease_dir = _lease_dir(args)
    path = os.path.join(lease_dir, "trace_id")
    if rank == 0:
        tid = _new_trace_id()
        os.makedirs(lease_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=lease_dir, prefix=".trace_id.")
        with os.fdopen(fd, "w") as f:
            f.write(tid)
        os.replace(tmp, path)
        return tid
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        try:
            st = os.stat(path)
            # freshness fence: only adopt a file written for THIS run
            # (peers launch within the lease window of rank 0; anything
            # older is a leftover from an earlier run in the same dir)
            if time.time() - st.st_mtime <= 120.0:
                with open(path) as f:
                    tid = f.read().strip()
                if tid:
                    return tid
        except OSError:
            pass
        time.sleep(0.05)
    tid = _new_trace_id()
    print(f"[OBS] rank {rank}: no shared trace_id under {lease_dir} "
          f"after 10s; minted {tid} locally — cross-rank correlation "
          "degraded", file=sys.stderr)
    return tid


def _ledger_dir(args):
    """The cross-run ledger location: explicit flag, then the environment
    — None means this run keeps no ledger (the pre-ledger default)."""
    import os

    return args.ledger_dir or os.environ.get("TPU_RADIX_LEDGER_DIR")


def _ledger_flush(args, meas):
    """Append this run's distilled registry to the cross-run ledger at
    exit.  Runs that measured nothing (--plan explain, argparse errors)
    skip silently; a ledger write failure must never change the run's
    exit code — the ledger is memory, not a dependency."""
    d = _ledger_dir(args)
    if not d or (not meas.times_us and not meas.counters):
        return
    try:
        from tpu_radix_join.observability.ledger import Ledger, run_payload
        led = Ledger(d)
        row = led.append("run", run_payload(meas))
        print(f"[OBS] ledger row {row['run_id']} -> {led.path}",
              file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — telemetry must not fail the run
        print(f"[OBS] ledger append failed: {e!r}", file=sys.stderr)


def _emit_failure_bundle(meas, exc, args, reason="failure"):
    """Write a forensics bundle for a terminal classified failure.

    A watchdog trip already wrote its bundle (the exception carries the
    path); everything else gets one here.  Bundle emission must never
    turn a classified failure into an unclassified crash — errors land
    on stderr and the original failure proceeds."""
    path = getattr(exc, "bundle", None)
    if path:
        return path
    out_dir = _forensics_dir(args)
    if not out_dir:
        print("[FORENSICS] no bundle dir (--forensics-dir / --output-dir / "
              "--timeline-dir all unset); skipping bundle", file=sys.stderr)
        return None
    try:
        from tpu_radix_join.observability.postmortem import write_bundle
        # exceptions may carry structured forensics of their own (e.g.
        # CoordinatorTimeout's attempts + cumulative backoff, RankLost's
        # epoch) — fold them into the bundle next to the repr
        extra = {"error": repr(exc)}
        extra.update(getattr(exc, "bundle_extra", None) or {})
        return write_bundle(
            out_dir, meas, reason=reason,
            failure_class=getattr(exc, "failure_class", None),
            config=vars(args), extra=extra)
    except Exception as e:   # noqa: BLE001 - forensics must not mask
        print(f"[FORENSICS] bundle write failed: {e!r}", file=sys.stderr)
        return None


def _run_grid(args, inner, outer, expected, meas, plan=None) -> int:
    """Out-of-core grid mode: both relations streamed in device-generated
    chunks, every (inner, outer) chunk pair probed exactly once, with an
    atomic checkpoint after each pair (--checkpoint-dir) so a killed run
    resumes from its last completed pair (--resume) instead of restarting
    — the capability the single-shot reference lacks (SURVEY.md §5.4)."""
    import os

    from tpu_radix_join.data.streaming import stream_chunks_device
    from tpu_radix_join.ops.chunked import chunked_join_grid
    from tpu_radix_join.robustness.retry import RetryPolicy

    chunk = args.grid_chunk_tuples
    ckpt_path = None
    if args.checkpoint_dir:
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        ckpt_path = os.path.join(args.checkpoint_dir, "grid.ckpt")
        if not args.resume and os.path.exists(ckpt_path):
            # a fresh run must never silently resume from a stale file
            os.remove(ckpt_path)
    # fingerprint tag: everything that changes the grid's total
    tag = (f"{args.outer_kind}:{inner.global_size}:{args.seed}:{chunk}:"
           f"{args.key_range}")
    policy = (RetryPolicy(max_attempts=args.max_retries + 1,
                          base_delay_s=args.retry_backoff or 0.5,
                          jitter=0.1)
              if args.max_retries else None)
    # --grid-pipeline "auto" defers to a planner plan's decision (the cost
    # model priced both grid rows); an explicit off/on flag wins the A/B
    pipeline = args.grid_pipeline
    if pipeline == "auto" and plan is not None and plan.engine == "chunked":
        pipeline = plan.grid_pipeline
    from tpu_radix_join.planner.audit import audit_plan, phase_snapshot

    meas.set_trace_tags(strategy="chunked_grid", engine="chunked")
    times0 = phase_snapshot(meas)
    meas.start("JTOTAL")
    try:
        total = chunked_join_grid(
            stream_chunks_device(inner, 0, chunk),
            lambda: stream_chunks_device(outer, 0, chunk),
            min(chunk, 1 << 20),
            checkpoint_path=ckpt_path, checkpoint_tag=tag,
            progress=True, key_range=args.key_range, measurements=meas,
            retry_policy=policy, plan=plan, pipeline=pipeline)
    except Exception as e:
        # a classified failure (e.g. DataCorruption from a key lane in the
        # sentinel range — the streamed-lane corruption signature) exits
        # with the machine-readable class instead of a bare traceback
        cls = getattr(e, "failure_class", None)
        if cls is None:
            raise
        meas.stop("JTOTAL")
        meas.meta["failure_class"] = cls
        print(f"[RESULTS] failure/failure_class: {cls}")
        print(f"[RESULTS] failure/error: {e}", file=sys.stderr)
        bundle = _emit_failure_bundle(meas, e, args)
        if bundle:
            print(f"[FORENSICS] bundle {bundle}", file=sys.stderr)
        if args.output_dir:
            path = meas.store(args.output_dir)
            print(f"[PERF] stored {path}")
        return 1
    meas.stop("JTOTAL")
    cp = None
    if meas.tracer is not None:
        from tpu_radix_join.observability.critpath import (
            critical_path_from_tracer, format_summary)
        cp = critical_path_from_tracer(meas.tracer)
        meas.meta["critical_path"] = cp
        print(f"[CRITPATH] {format_summary(cp)}")
    # plan-vs-actual: the grid engine's measured JTOTAL against the cost
    # model's prediction for the chunked strategy (planner/audit.py)
    audit = audit_plan(plan, meas, times0=times0, critical_path=cp)
    if audit is not None:
        print(f"[PLAN] actual_ms={audit['actual_ms']:.1f} "
              f"predicted_ms={audit['predicted_ms']:.1f} "
              f"drift={audit['drift_pct']:.1f}%")
    print(f"[RESULTS] Tuples: {total}")
    if expected is not None:
        status = "OK" if total == expected else "MISMATCH"
        print(f"[RESULTS] Expected: {expected} ({status})")
    for line in meas.lines():
        print(f"[PERF] {line}")
    if args.output_dir:
        path = meas.store(args.output_dir)
        print(f"[PERF] stored {path}")
    return 1 if (expected is not None and total != expected) else 0


def _run_serve(args, cfg, meas, nodes, sampler=None, membership=None) -> int:
    """Resident service mode: every request in the file flows through ONE
    :class:`~tpu_radix_join.service.JoinSession` — warm plan/capacity
    reuse across queries, admission control at the door, per-query
    deadlines, and a circuit breaker that degrades to the CPU engine when
    the backend goes dark.  One outcome JSON line per query on stdout,
    then a summary line carrying the SLO snapshot."""
    import json as _json
    import os
    import time as _time

    import jax

    from tpu_radix_join.core.config import ServiceConfig
    from tpu_radix_join.service import (AdmissionRejected, JoinSession,
                                        MicroBatcher, QueryRequest)

    plan_cache = None
    if args.plan_cache_dir:
        from tpu_radix_join.planner import PlanCache, load_profile
        from tpu_radix_join.planner.cache import ManifestMismatch

        plan_cache = PlanCache(args.plan_cache_dir,
                               load_profile(args.profile),
                               measurements=meas)
        try:
            plan_cache.check_manifest(jax.process_count())
        except ManifestMismatch as e:
            print(f"[PLAN] {e}", file=sys.stderr)
            return 2
        plan_cache.write_manifest(jax.process_count(),
                                  rank=jax.process_index())

    svc = ServiceConfig(
        max_queue_depth=args.serve_queue_depth,
        tenant_quota=args.serve_tenant_quota,
        default_deadline_s=args.serve_deadline_s,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        place_cache_max=args.place_cache_max,
        result_cache_max=args.result_cache,
        result_cache_ttl_s=args.result_cache_ttl_s,
        batch_window_ms=args.batch_window_ms,
        batch_max_queries=args.batch_max,
        resident_budget_bytes=int(args.resident_budget_mb * (1 << 20)))
    ledger = None
    ld = _ledger_dir(args)
    if ld:
        from tpu_radix_join.observability.ledger import Ledger
        ledger = Ledger(ld)
    session = JoinSession(cfg, svc, measurements=meas,
                          plan_cache=plan_cache, profile=args.profile,
                          forensics_dir=_forensics_dir(args),
                          ledger=ledger, membership=membership,
                          elastic=args.elastic == "on",
                          elastic_grow=args.elastic_grow,
                          hedge=args.hedge,
                          hedge_threshold=args.hedge_threshold)
    # the coalescer is owned by the serve loop (no threads of its own):
    # offer() as requests arrive, due() before blocking, flush() at EOF
    batcher = MicroBatcher(svc.batch_window_ms, svc.batch_max_queries)
    # fleet workers are spawned with an incarnation id (w<slot>i<n>,
    # service/fleet.py); stamping it into the flight-recorder context
    # makes every forensics bundle this worker writes group per
    # incarnation under tools_postmortem.py --merge
    incarnation = os.environ.get("TPU_RJ_WORKER_INCARNATION")
    if incarnation:
        meas.flightrec.set_context(worker_incarnation=incarnation)
    if sampler is not None:
        # heartbeat ticks carry the live SLO/breaker snapshot in serve mode;
        # with membership attached the lease write rides the same tick
        if membership is not None:
            lease_extra = membership.board.sampler_extra(
                epoch_of=membership.epoch_of)
            sampler.extra = (lambda hb=session._heartbeat_extra:
                             {**hb(), **lease_extra()})
        else:
            sampler.extra = session._heartbeat_extra

    statusz = None
    if args.statusz is not None:
        # live introspection plane: read-only JSON over loopback, priced
        # per request (no background sampling thread) — polling it costs
        # the poller, not the join
        from tpu_radix_join.observability.statusz import (
            StatuszServer, measurements_sections)
        from tpu_radix_join.performance.measurements import (HEDGED,
                                                             HEDGEWIN,
                                                             SPECWASTE)
        sections = dict(measurements_sections(meas))
        sections["service"] = session._heartbeat_extra
        if membership is not None:
            sections["leases"] = membership.board.sampler_extra(
                epoch_of=membership.epoch_of)
        sections["hedge"] = (lambda: {
            "mode": session.hedge,
            "threshold": session.hedge_threshold,
            "elastic_grow": session.elastic_grow,
            "hedged": int(meas.counters.get(HEDGED, 0)),
            "wins": int(meas.counters.get(HEDGEWIN, 0)),
            "wasted": int(meas.counters.get(SPECWASTE, 0))})
        sections["critical_paths"] = (
            lambda: list(session.recent_critical_paths))
        if svc.result_cache_max or svc.resident_budget_bytes:
            sections["cache"] = (lambda: {
                "result_cache": session.result_cache.stats(),
                "resident": session.resident.stats(),
                "placed_bytes": session.placed_bytes()})
        if svc.batch_window_ms > 0:
            sections["batch"] = (lambda: {
                **batcher.stats(),
                "session_fused_batches": session.batches_fused,
                "session_fused_queries": session.batch_queries_fused})

        def _readiness():
            # /healthz readiness: closed session, open breaker, or a
            # stale own-lease heartbeat all mean "do not route here" —
            # 503 with the reason, so the fleet supervisor or an
            # external LB can act on the status code alone
            from tpu_radix_join.service.breaker import OPEN as _BRK_OPEN
            if session._closed:
                return {"ok": False, "reason": "session_closed"}
            if session.breaker.state == _BRK_OPEN:
                return {"ok": False, "reason": "breaker_open"}
            if membership is not None:
                lease = membership.board.read(membership.board.rank)
                if lease is not None:
                    age = _time.time() - lease.t_epoch_s
                    if age > membership.board.lapse_window_s:
                        return {"ok": False,
                                "reason": f"heartbeat_stale_{age:.1f}s"}
            return {"ok": True}

        statusz = StatuszServer(port=args.statusz, sections=sections,
                                readiness=_readiness)
        statusz.start()
        print(f"[STATUSZ] serving http://127.0.0.1:{statusz.port}"
              "/statusz", file=sys.stderr)

    errors = 0
    fuse = svc.batch_window_ms > 0

    def emit(out):
        print(_json.dumps({"event": "outcome", **out.to_json()}), flush=True)

    def flush_groups(groups):
        # submit every member of every due group back-to-back, then drain:
        # contiguous co-signature queries fuse inside run_next_batch
        submitted = 0
        for group in groups:
            for request in group:
                try:
                    session.submit(request)
                    submitted += 1
                except AdmissionRejected as e:
                    emit(session.rejection_outcome(request, e))
        if submitted:
            session.drain(on_outcome=emit)

    if args.serve == "-":
        # stream, don't slurp: a resident session answers requests as
        # they arrive on the pipe (an operator can hold stdin open and
        # poll --statusz between queries); EOF still ends the session
        if fuse:
            # reader thread + timed queue: a parked micro-batch group
            # must flush when its window expires even if stdin goes
            # quiet — a blocking readline would strand it forever (the
            # fleet supervisor's dispatch_batch awaits those outcomes)
            import queue as _queue
            import threading as _threading

            lineq: "_queue.Queue" = _queue.Queue()

            def _read_lines():
                try:
                    for raw in sys.stdin:
                        lineq.put(raw)
                finally:
                    lineq.put(None)

            _threading.Thread(target=_read_lines, name="serve-stdin",
                              daemon=True).start()

            def _timed_lines():
                while True:
                    nd = batcher.next_deadline_s()
                    wait = 0.2 if nd is None else max(0.001, min(0.2, nd))
                    try:
                        raw = lineq.get(timeout=wait)
                    except _queue.Empty:
                        flush_groups(batcher.due())
                        continue
                    if raw is None:
                        return
                    yield raw

            lines = _timed_lines()
        else:
            lines = iter(sys.stdin)
    else:
        with open(args.serve) as f:
            lines = f.read().splitlines()

    batch = max(1, args.serve_batch)
    try:
        pending = 0
        for lineno, line in enumerate(lines, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            qid = None
            try:
                obj = _json.loads(line)
                if not isinstance(obj, dict):
                    raise ValueError("request must be a JSON object")
                obj.setdefault("query_id", f"line{lineno}")
                qid = obj.get("query_id")
                request = QueryRequest.from_json(obj)
            except (ValueError, TypeError) as e:
                # a malformed line is the CLIENT's bug: report it and keep
                # serving — one bad request must not kill the session
                errors += 1
                print(_json.dumps({"event": "request_error",
                                   "line": lineno, "query_id": qid,
                                   "error": str(e)}),
                      flush=True)
                continue
            # tier 0: a result-cache hit answers before admission — it
            # never occupies a queue slot or a tenant quota
            hit = session.try_cache(request)
            if hit is not None:
                emit(hit)
                continue
            if fuse and request.delta_tuples_per_node == 0:
                # park in the signature window; key bound = the widest
                # key any generated lane can carry for this request
                key_bound = max(request.tuples_per_node * cfg.num_nodes,
                                request.modulo or 0)
                group = batcher.offer(request, key_bound)
                if group is not None:
                    flush_groups([group])
                flush_groups(batcher.due())
                continue
            try:
                session.submit(request)
                pending += 1
            except AdmissionRejected as e:
                emit(session.rejection_outcome(request, e))
            if pending >= batch:
                session.drain(on_outcome=emit)
                pending = 0
        if fuse:
            flush_groups(batcher.flush())
        session.drain(on_outcome=emit)
        summary = session.summary()
        print(_json.dumps({"event": "summary", **summary}), flush=True)
        # admission rejections are backpressure working as designed; only
        # executed-and-failed queries (or unparseable requests) fail the run
        return 1 if (errors or summary.get("queries_failed", 0)) else 0
    finally:
        if statusz is not None:
            statusz.stop()
        session.close()


def _run_fleet(args) -> int:
    """Crash-only fleet supervision (``--fleet N``): own N ``--serve -``
    worker subprocesses behind the journal's exactly-once discipline.

    The supervisor reads the same JSONL request stream serve mode does,
    but each query is intent-journaled, routed by tenant hash to a live
    worker, and outcome-journaled before the client sees the reply; a
    worker SIGKILLed mid-query fails over (replay on a healthy worker),
    and a SIGTERM to the supervisor drains gracefully — admission stops,
    in-flight queries finish, workers exit cleanly (withdrawing their own
    leases), and the journal ends with zero unacknowledged intents.
    Exit 0 = every accepted query got exactly one outcome."""
    import contextlib
    import json as _json
    import os
    import queue as _queue
    import signal as _signal
    import tempfile
    import threading

    from tpu_radix_join.performance.measurements import Measurements
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.service.fleet import FleetSupervisor

    work_dir = (args.fleet_dir
                or (os.path.join(args.output_dir, "fleet")
                    if args.output_dir else None)
                or (os.path.join(args.timeline_dir, "fleet")
                    if args.timeline_dir else None)
                or tempfile.mkdtemp(prefix="tpu_rj_fleet_"))

    # the workers inherit the supervisor's join/serve shape; requests
    # carry the per-query knobs (tuples_per_node, seed, deadline_s, ...)
    worker_args = []
    if args.nodes:
        worker_args += ["--nodes", str(args.nodes)]
    if args.verify != "off":
        worker_args += ["--verify", args.verify]
    worker_args += ["--profile", args.profile,
                    "--max-retries", str(args.max_retries),
                    "--fallback", args.fallback,
                    "--breaker-threshold", str(args.breaker_threshold),
                    "--breaker-cooldown-s", str(args.breaker_cooldown_s),
                    "--serve-queue-depth", str(args.serve_queue_depth),
                    "--serve-tenant-quota", str(args.serve_tenant_quota),
                    "--place-cache-max", str(args.place_cache_max)]
    if args.serve_deadline_s is not None:
        worker_args += ["--serve-deadline-s", str(args.serve_deadline_s)]
    if args.result_cache:
        worker_args += ["--result-cache", str(args.result_cache)]
        if args.result_cache_ttl_s is not None:
            worker_args += ["--result-cache-ttl-s",
                            str(args.result_cache_ttl_s)]
    if args.batch_window_ms > 0:
        # the workers MUST share the batch window: dispatch_batch writes a
        # fused group's request lines back-to-back, and it is the worker's
        # own coalescer that turns them into one device program
        worker_args += ["--batch-window-ms", str(args.batch_window_ms),
                        "--batch-max", str(args.batch_max)]
    if args.resident_budget_mb:
        worker_args += ["--resident-budget-mb", str(args.resident_budget_mb)]

    meas = Measurements()
    sup = FleetSupervisor(args.fleet, worker_args, work_dir,
                          measurements=meas,
                          lease_s=args.rank_lease_s,
                          missed_beats=args.rank_missed_beats,
                          result_cache_max=args.result_cache,
                          result_cache_ttl_s=args.result_cache_ttl_s,
                          batch_window_ms=args.batch_window_ms)

    statusz = None
    if args.statusz is not None:
        from tpu_radix_join.observability.statusz import (
            StatuszServer, measurements_sections)
        sections = dict(measurements_sections(meas))
        sections["fleet"] = sup.statusz_section
        statusz = StatuszServer(port=args.statusz, sections=sections,
                                readiness=sup.readiness)
        statusz.start()
        print(f"[STATUSZ] serving http://127.0.0.1:{statusz.port}"
              "/statusz", file=sys.stderr)

    # SIGTERM = graceful drain: the handler only flips a flag — the
    # in-flight dispatch (the supervisor is single-threaded by design)
    # finishes its query, then the loop sees the flag and drains
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    prev_term = _signal.signal(_signal.SIGTERM, _on_term)

    # requests arrive through a reader thread + queue so the serve loop
    # can poll the stop flag: a blocking readline would ride out SIGTERM
    # (PEP 475 retries it) and strand the drain until the next line
    lineq: "_queue.Queue" = _queue.Queue()

    def _read_lines(src):
        try:
            for line in src:
                lineq.put(line)
        finally:
            lineq.put(None)

    if args.serve == "-":
        src = sys.stdin
    else:
        src = open(args.serve)
    reader = threading.Thread(target=_read_lines, args=(src,),
                              name="fleet-stdin", daemon=True)

    def emit(out):
        print(_json.dumps({"event": "outcome", **out}, default=str),
              flush=True)

    errors = 0
    rc = 0
    try:
        with contextlib.ExitStack() as stack:
            if args.fleet_kill_at is not None:
                inj = faults.FaultInjector(seed=args.seed,
                                           measurements=meas)
                inj.arm(faults.FLEET_WORKER_KILL, at=args.fleet_kill_at)
                stack.enter_context(inj)
            sup.start()
            # a previous incarnation's accepted-but-unanswered queries
            # replay before any new admission — the restart half of
            # exactly-once (each replayed outcome is emitted too, marked
            # replayed, so the client is made whole)
            replayed = sup.replay_unacknowledged(emit)
            if replayed:
                print(f"[FLEET] replayed {len(replayed)} unacknowledged "
                      f"intent(s) from {sup.journal.path}",
                      file=sys.stderr)
            reader.start()
            # supervisor-side micro-batch windows: co-signature requests
            # arriving within --batch-window-ms dispatch together via
            # dispatch_batch (one signature-routed worker, back-to-back
            # lines, the worker fuses them into one device program)
            import time as _time
            window_s = args.batch_window_ms / 1000.0
            parked: dict = {}          # sig -> (opened_monotonic, [obj])

            def _flush_sig(sig):
                _, group = parked.pop(sig)
                for out in sup.dispatch_batch(group):
                    emit(out)

            def _flush_due():
                now = _time.monotonic()
                for sig in sorted(parked, key=lambda s: parked[s][0]):
                    if now - parked[sig][0] >= window_s:
                        _flush_sig(sig)

            poll_s = min(0.2, window_s) if window_s > 0 else 0.2
            lineno = 0
            while not stop.is_set():
                try:
                    line = lineq.get(timeout=poll_s if parked else 0.2)
                except _queue.Empty:
                    _flush_due()
                    continue
                if line is None:
                    break
                lineno += 1
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    obj = _json.loads(line)
                    if not isinstance(obj, dict):
                        raise ValueError("request must be a JSON object")
                    obj.setdefault("query_id", f"line{lineno}")
                except (ValueError, TypeError) as e:
                    errors += 1
                    print(_json.dumps({"event": "request_error",
                                       "line": lineno, "error": str(e)}),
                          flush=True)
                    continue
                sig = sup._batch_signature(obj)
                if sig is None or obj.get("delta_tuples_per_node"):
                    emit(sup.dispatch(obj))
                else:
                    opened, group = parked.get(sig,
                                               (_time.monotonic(), []))
                    group.append(obj)
                    parked[sig] = (opened, group)
                    if len(group) >= args.batch_max:
                        _flush_sig(sig)
                _flush_due()
            # EOF / SIGTERM: no parked query is ever lost to the drain
            for sig in list(parked):
                _flush_sig(sig)
        report = sup.drain()
        summary = {**sup.summary(), "drain": report}
        print(_json.dumps({"event": "summary", **summary}, default=str),
              flush=True)
        if report["unacked"] or report["double_exec"]:
            # a stranded or doubled query is the one failure this mode
            # exists to rule out — fail loud
            print(f"[FLEET] exactly-once violated at drain: "
                  f"unacked={report['unacked']} "
                  f"double_exec={report['double_exec']}", file=sys.stderr)
            rc = 1
        if errors:
            rc = 1
        return rc
    finally:
        sup.close()
        if statusz is not None:
            statusz.stop()
        if src is not sys.stdin:
            src.close()
        _signal.signal(_signal.SIGTERM, prev_term)
        _ledger_flush(args, meas)


def _run_joiner(args, cfg, meas, nodes, *, membership) -> int:
    """The newcomer's half of elastic growth (``--elastic-join N``).

    Mirror image of the incumbents' admission path
    (membership.MembershipView._admit): this process wrote a ``joining``
    lease before any work; here it (1) waits for an incumbent epoch bump
    — the fenced admission signal, readable from the shared lease dir
    with no coordinator — then (2) regenerates the deterministic
    relations host-side and recomputes ITS share of unfinished
    partitions through the shared manifest, exactly the
    ``execute_recovery(only_rank=...)`` multi-survivor discipline the
    incumbents' regrow uses.  Divergent plan timing across processes is
    safe: the manifest fence (first writer wins within an epoch) makes
    double-computation waste, never double-counting.
    """
    import os
    import time as _time

    from tpu_radix_join import Relation
    from tpu_radix_join.robustness.checkpoint import PartitionManifest
    from tpu_radix_join.robustness.recovery import (execute_recovery,
                                                    host_keys,
                                                    partition_weights,
                                                    plan_recovery)

    board = membership.board
    num_ranks = board.num_ranks            # incumbent world size (= N)
    if nodes % num_ranks:
        print(f"[RESULTS] failure/joiner: {nodes} nodes do not divide "
              f"over {num_ranks} incumbent ranks", file=sys.stderr)
        return 1
    npp = nodes // num_ranks
    my_nodes = list(range(board.rank * npp, (board.rank + 1) * npp))
    print(f"[ELASTIC] joiner rank={board.rank} nodes={my_nodes} "
          f"waiting for admission under {board.run_dir}", file=sys.stderr)

    # -- wait for the fenced admission: any incumbent member lease at
    # epoch >= 1 means the board admitted someone (us — we are the only
    # joining lease we wrote) and the next plan prices us in
    deadline = _time.monotonic() + max(120.0, 6.0 * board.lapse_window_s)
    admitted_epoch = 0
    while _time.monotonic() < deadline:
        for r in board.discover():
            if r == board.rank:
                continue
            lease = board.read(r)
            if (lease is not None and lease.status == "member"
                    and lease.epoch > admitted_epoch):
                admitted_epoch = lease.epoch
        if admitted_epoch >= 1:
            break
        board.heartbeat(membership.epoch, status="joining")
        _time.sleep(min(0.2, board.lease_s / 4.0))
    if admitted_epoch < 1:
        print("[RESULTS] failure/joiner: no admission epoch bump before "
              "deadline — incumbents never saw the joining lease "
              "(dead world, or --elastic-grow not set there)",
              file=sys.stderr)
        return 1
    membership.epoch = admitted_epoch
    membership.joined.add(board.rank)
    board.heartbeat(admitted_epoch, status="member")
    print(f"[ELASTIC] joiner admitted epoch={admitted_epoch}",
          file=sys.stderr)

    # -- regenerate the deterministic inputs host-side (the property
    # that makes coordinator-free growth possible: a newcomer computes
    # the same host_keys every incumbent does)
    global_size = args.tuples_per_node * nodes
    inner = Relation(global_size, nodes, "unique", seed=args.seed)
    outer_kw = {}
    if args.outer_kind == "modulo":
        outer_kw["modulo"] = args.modulo or max(1, global_size // 4)
    elif args.outer_kind == "zipf":
        outer_kw["zipf_theta"] = args.zipf_theta
        outer_kw["key_domain"] = global_size
    outer = Relation(global_size, nodes, args.outer_kind,
                     seed=args.seed + 1, **outer_kw)
    rk, rhi = host_keys(inner)
    sk, shi = host_keys(outer)
    num_p = cfg.network_partition_count
    fp = (f"elastic:{args.outer_kind}:{global_size}:"
          f"{args.seed}:{num_p}")
    os.makedirs(args.checkpoint_dir, exist_ok=True)
    manifest = PartitionManifest(
        os.path.join(args.checkpoint_dir, "partitions.manifest"),
        fingerprint=fp, measurements=meas)

    plan = plan_recovery(
        num_nodes=nodes, num_partitions=num_p, lost_ranks=[],
        epoch=admitted_epoch, manifest=manifest,
        weights=partition_weights(rk, sk, num_p),
        joined_ranks=my_nodes)
    board.heartbeat(admitted_epoch, status="member")
    matches, counts = execute_recovery(
        plan, rk, sk, rhi, shi, only_rank=set(my_nodes),
        manifest=manifest, measurements=meas)

    # -- report once the shared manifest is complete: our share is done
    # (post-realization lines above), the rest arrives as incumbents
    # finish theirs — completeness, not a barrier, is the exit signal
    deadline = _time.monotonic() + max(120.0, 6.0 * board.lapse_window_s)
    while _time.monotonic() < deadline:
        if len(manifest.completed()) >= num_p:
            break
        board.heartbeat(admitted_epoch, status="member")
        _time.sleep(0.1)
    done = manifest.completed()
    matches = int(sum(rec["count"] for rec in done.values()))
    mine = sum(1 for rec in done.values()
               if rec.get("owner") in set(my_nodes))
    expected = inner.expected_matches(outer)
    print(f"[RESULTS] joiner: rank={board.rank} epoch={admitted_epoch} "
          f"owned_partitions={mine} "
          f"manifest_partitions={len(done)}/{num_p}")
    print(f"[RESULTS] Tuples: {matches}")
    if expected is not None:
        status = "OK" if matches == expected else "MISMATCH"
        print(f"[RESULTS] Expected: {expected} ({status})")
        if matches != expected:
            return 1
    if len(done) < num_p:
        print("[RESULTS] failure/joiner: manifest incomplete at "
              "deadline", file=sys.stderr)
        return 1
    aud = manifest.audit()
    print(f"[ELASTIC] joiner manifest audit total={aud['total']} "
          f"fenced_duplicates={aud['fenced_duplicates']}",
          file=sys.stderr)
    if args.output_dir:
        path = meas.store(args.output_dir)
        print(f"[PERF] stored {path}")
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.trace and not args.output_dir:
        parser.error("--trace writes its artifacts under --output-dir")
    if args.pipeline_repeats and args.measure_phases:
        parser.error("--pipeline-repeats dispatches without intermediate "
                     "fences; the --measure-phases split timers need a "
                     "fence per program — drop one of the two")
    if args.serve is not None and args.grid_chunk_tuples is not None:
        parser.error("--serve runs the in-core resident engine; the "
                     "out-of-core grid is a one-shot mode")
    if args.elastic_grow and args.elastic != "on":
        parser.error("--elastic-grow admits ranks into the elastic "
                     "recovery protocol — it needs --elastic on")
    if args.hedge != "off" and args.elastic != "on":
        parser.error("--hedge speculates through the elastic recovery "
                     "machinery — it needs --elastic on")
    if not 0.0 < args.hedge_threshold < 1.0:
        parser.error("--hedge-threshold must be in (0, 1): it is the "
                     "slowest/median progress ratio below which hedging "
                     "arms")
    if args.rank_missed_beats < 1:
        parser.error("--rank-missed-beats must be >= 1")
    if args.fleet is not None:
        if args.fleet < 1:
            parser.error("--fleet needs at least one worker")
        if args.serve is None:
            parser.error("--fleet supervises --serve workers — pass "
                         "--serve FILE (or '-' for stdin)")
        if args.elastic_join is not None:
            parser.error("--fleet is a serving supervisor, not a mesh "
                         "rank; it cannot run as --elastic-join")
    if args.elastic_join is not None:
        if not args.checkpoint_dir:
            parser.error("--elastic-join recomputes through the shared "
                         "partition manifest — pass the incumbents' "
                         "--checkpoint-dir")
        if args.elastic != "on":
            parser.error("--elastic-join is the growth half of elastic "
                         "recovery — it needs --elastic on")
        if not args.nodes:
            parser.error("--elastic-join cannot infer the incumbent "
                         "world's node count from its own devices — "
                         "pass the incumbents' --nodes")

    import contextlib
    import os

    if args.profile == "auto":
        # resolve BEFORE jax init: the decision reads only the ledger dir
        from tpu_radix_join.planner.profile import resolve_profile
        args.profile = resolve_profile("auto", ledger_dir=_ledger_dir(args))
        print(f"[PROFILE] auto -> {args.profile}", file=sys.stderr)

    if args.fleet is not None:
        # the supervisor never initializes devices — the workers own the
        # mesh; dispatch before the driver's jax/device bring-up
        return _run_fleet(args)

    import jax

    from tpu_radix_join.utils.platform import apply_platform_override

    apply_platform_override()

    from tpu_radix_join import HashJoin, JoinConfig, Relation
    from tpu_radix_join.parallel.multihost import initialize as init_multihost
    from tpu_radix_join.performance import Measurements

    distributed = init_multihost()   # no-op unless a world is configured
    nodes = args.nodes or jax.device_count()
    if args.grid_chunk_tuples is not None and nodes != 1:
        parser.error("--grid-chunk-tuples runs the single-node out-of-core "
                     "grid; use --nodes 1")
    if args.resume and not args.checkpoint_dir:
        parser.error("--resume reads the checkpoint under --checkpoint-dir")
    cfg = JoinConfig(
        num_nodes=nodes,
        num_hosts=args.hosts,
        network_fanout_bits=args.network_fanout,
        local_fanout_bits=args.local_fanout,
        two_level=args.two_level,
        probe_algorithm=args.probe,
        assignment_policy=args.assignment,
        window_sizing=args.window_sizing,
        chunk_size=args.chunk_size,
        max_retries=args.max_retries,
        retry_backoff_s=args.retry_backoff,
        fallback=args.fallback,
        skew_threshold=args.skew_threshold,
        key_range=args.key_range,
        generation=args.generation,
        debug_checks=args.debug_checks,
        measure_phases=args.measure_phases,
        verify=args.verify,
        exchange_codec=args.exchange_codec,
        exchange_stages=args.exchange_stages,
        partition_impl=args.partition_impl,
        sort_impl=args.sort_impl,
    )

    meas = Measurements(node_id=jax.process_index(), num_nodes=nodes)

    # compile telemetry: every backend compile lands in NCOMPILE/COMPILEMS
    # via jax.monitoring (observability/compilemon.py) — heartbeat ticks,
    # the ledger row, and the regress gate all see compile churn
    from tpu_radix_join.observability.compilemon import (
        install_compile_monitor, uninstall_compile_monitor)
    install_compile_monitor(meas)

    # ---------------------------------------------------- observability
    # (tpu_radix_join.observability): opt-in span timeline + live metrics
    # heartbeat; without the flags the driver behaves exactly as before.
    tracer = None
    if args.timeline_dir:
        os.makedirs(args.timeline_dir, exist_ok=True)
        # distributed runs share ONE join-level trace id (rank 0 mints,
        # peers adopt through the lease dir) so the exported span files
        # correlate as a single join; solo runs mint locally
        trace_id = (_trace_identity(args, jax.process_index())
                    if jax.process_count() > 1 else None)
        tracer = meas.attach_tracer(trace_id=trace_id, nodes=nodes)
    sampler = None
    if args.metrics_interval:
        mdir = args.timeline_dir or args.output_dir
        if not mdir:
            parser.error("--metrics-interval writes <rank>.metrics.jsonl "
                         "under --timeline-dir or --output-dir — pass one")
        from tpu_radix_join.observability import MetricsSampler
        sampler = MetricsSampler(
            os.path.join(mdir, f"{meas.node_id}.metrics.jsonl"),
            args.metrics_interval, measurements=meas)
        sampler.start()

    # ------------------------------------------------- elastic membership
    # (tpu_radix_join.robustness.membership): epoch-stamped leases in a
    # shared dir.  Always on for multi-process worlds (loss DETECTION and
    # classification are free safety); recovery itself is --elastic on.
    membership = None
    board = None
    if args.elastic == "on" or distributed:
        from tpu_radix_join.robustness.membership import (LeaseBoard,
                                                          MembershipView)
        if args.elastic_join is not None:
            # joiner mode: rank comes from the shared lease dir (first
            # free id at or above the incumbent world size), and the
            # first lease is a JOINING lease — admission is the
            # incumbents' move, not ours
            lease_dir = _lease_dir(args)
            rank = LeaseBoard.next_rank(lease_dir,
                                        floor=args.elastic_join)
            board = LeaseBoard(lease_dir, rank=rank,
                               num_ranks=args.elastic_join,
                               lease_s=args.rank_lease_s,
                               missed_beats=args.rank_missed_beats,
                               measurements=meas)
            membership = MembershipView(board, measurements=meas)
            board.heartbeat(0, status="joining")
        else:
            board = LeaseBoard(_lease_dir(args), rank=jax.process_index(),
                               num_ranks=jax.process_count(),
                               lease_s=args.rank_lease_s,
                               missed_beats=args.rank_missed_beats,
                               measurements=meas)
            membership = MembershipView(board, measurements=meas)
            board.heartbeat(0)       # first lease before any join work
        if sampler is not None:
            # liveness rides the telemetry cadence: every sampler tick
            # heartbeats the lease and reports the membership epoch +
            # lease status (a joiner's tick says "joining" until its
            # own view admits it)
            sampler.extra = board.sampler_extra(
                epoch_of=membership.epoch_of,
                status_of=membership.my_status)
    try:
        if args.elastic_join is not None:
            rc = _run_joiner(args, cfg, meas, nodes,
                             membership=membership)
        elif args.serve is not None:
            rc = _run_serve(args, cfg, meas, nodes, sampler=sampler,
                            membership=membership)
        else:
            rc = _run_driver(args, cfg, meas, distributed, nodes,
                             membership=membership)
    finally:
        if board is not None:
            # a clean exit withdraws the lease: peers see an absent (not
            # stale) lease and a deliberate departure, not a silent death
            board.withdraw(board.rank)
        uninstall_compile_monitor(meas)
        if sampler is not None:
            sampler.stop()
        _ledger_flush(args, meas)
        if tracer is not None:
            # save in the finally: a failed/degraded run's timeline is the
            # one a post-mortem needs most
            path = tracer.save(args.timeline_dir,
                               device_summary=meas.meta.get("trace"))
            print(f"[OBS] timeline spans stored {path}", file=sys.stderr)
    if distributed and membership is not None and membership.lost:
        # a survivor of a rank loss must NOT walk jax.distributed's atexit
        # shutdown: the coordination service's shutdown barrier can never
        # complete with a dead peer and LOG(FATAL)s the process (observed
        # rc -6 after a fully recovered run).  Every artifact is already
        # flushed above — exit hard with the honest code.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    return rc


def _plan_static_payload(profile, workload, plan, meas):
    """graftcheck cross-validation for ``--plan explain``: trace the
    fused pipeline at this workload's geometry and diff its all_to_all
    bytes against the cost model (STATIC-DRIFT column), recording the
    STATICMEM / JXAUDIT gauges.  Best-effort: tracing needs
    ``num_nodes`` host devices — on any failure explain simply omits
    the column rather than failing the driver."""
    if plan is None or plan.engine != "incore":
        return None
    try:
        from tpu_radix_join.analysis.jaxpr import run_audit
        from tpu_radix_join.analysis.jaxpr.crossval import static_for_explain
        from tpu_radix_join.analysis.jaxpr.trace import build_entries
        from tpu_radix_join.performance.measurements import (JXAUDIT,
                                                             STATICMEM)
        from tpu_radix_join.planner.cost_model import plan_exchange

        n = max(1, workload.num_nodes)
        per_node = max(8, -(-max(workload.r_tuples, workload.s_tuples)
                            // n))
        cap = max(8, 1 << (-(-per_node // n) - 1).bit_length())
        views = build_entries(num_nodes=n, per_node=per_node, cap=cap,
                              entries=("pipeline",))
        res = run_audit(views)
        xplan = plan_exchange(profile, workload,
                              fanout_bits=plan.network_fanout_bits)
        payload = static_for_explain(views[0], xplan)
        meas.counters[JXAUDIT] = len(res.findings)
        peak = res.stats.get("pipeline", {}).get("peak_live_bytes")
        if peak is not None:
            meas.counters[STATICMEM] = int(peak)
        return payload
    except Exception as e:       # noqa: BLE001 — advisory column only
        print(f"[PLAN] static cross-validation unavailable: {e}",
              file=sys.stderr)
        return None


def _run_driver(args, cfg, meas, distributed, nodes, membership=None) -> int:
    """Driver body after flag/observability setup (main() wraps this in the
    tracer/sampler lifecycle so every exit path exports its timeline)."""
    import contextlib
    import os

    import jax

    from tpu_radix_join import HashJoin, Relation

    # ---------------------------------------------------------- planner
    # (tpu_radix_join.planner): optional — without --plan/--plan-cache-dir
    # the driver behaves exactly as before.
    plan = None
    plan_cache = None
    plan_costs = None
    explain_tbl = None
    plan_static = None
    if args.plan is not None or args.plan_cache_dir:
        import dataclasses as _dc

        from tpu_radix_join.planner import (JoinPlan, PlanCache, Workload,
                                            explain_table, load_profile,
                                            plan_join)
        from tpu_radix_join.planner.cache import ManifestMismatch

        profile = load_profile(args.profile)
        global_size = args.tuples_per_node * nodes
        if args.plan_cache_dir:
            plan_cache = PlanCache(args.plan_cache_dir, profile,
                                   measurements=meas)
            try:
                # multi-host guard: a cache dir written by a different
                # topology or profile must fail fast, not desynchronize
                plan_cache.check_manifest(jax.process_count())
            except ManifestMismatch as e:
                print(f"[PLAN] {e}", file=sys.stderr)
                return 2
            plan_cache.write_manifest(jax.process_count(),
                                      rank=jax.process_index())
        if args.plan in ("auto", "explain"):
            workload = Workload(
                r_tuples=global_size, s_tuples=global_size,
                key_bound=global_size,   # generated keys live in [0, N)
                num_nodes=nodes, repeats=args.repeat)
            wl_fp = {"workload": _dc.asdict(workload)}
            if plan_cache is not None and args.plan == "auto":
                plan, _ = plan_cache.lookup(global_size, global_size, wl_fp)
            if plan is None:
                plan, costs = plan_join(profile, workload)
                plan_costs, explain_tbl = costs, explain_table
                plan_static = _plan_static_payload(profile, workload,
                                                   plan, meas)
                if args.plan == "explain":
                    cp_col = None
                    if args.timeline_dir:
                        # measured critical path from the span exports a
                        # prior run left under --timeline-dir: the table
                        # prices the winning strategy against the rank
                        # that actually bounded the wall clock, not the
                        # local mean
                        from tpu_radix_join.observability.critpath import \
                            critical_path_for_dir
                        cp = critical_path_for_dir(args.timeline_dir)
                        if not cp.get("error"):
                            # compile wall comes off the measured bound
                            # (audit_plan's exclude-from-running twin):
                            # the table prices steady-state joins
                            jc = float((cp.get("phase_ms") or {})
                                       .get("JCOMPILE", 0.0))
                            cp_col = {
                                "strategy": plan.strategy,
                                "bound_ms": max(
                                    0.0, cp.get("path_ms", 0.0) - jc),
                                "bound_rank": cp.get("bounding_rank"),
                                "wait_fraction": cp.get("wait_fraction")}
                    print(explain_table(costs, plan, static=plan_static,
                                        critpath=cp_col))
                    # constants half of explain: where each profile
                    # constant came from (fit provenance vs committed
                    # citation) and which ones the ledger's accumulated
                    # PLANDRIFT says have gone stale
                    from tpu_radix_join.observability.ledger import (
                        default_ledger_dir, load_rows)
                    from tpu_radix_join.planner.calibrate import detect_stale
                    from tpu_radix_join.planner.profile import \
                        format_provenance
                    ld = _ledger_dir(args) or default_ledger_dir()
                    print(format_provenance(
                        profile, stale=detect_stale(load_rows(ld))))
                    return 0
                if plan_cache is not None:
                    plan_cache.store(global_size, global_size, wl_fp,
                                     plan=plan)
        elif args.plan is not None:
            plan = JoinPlan.load(args.plan)
        if plan is not None:
            print(f"[PLAN] strategy={plan.strategy} engine={plan.engine} "
                  f"predicted_ms={plan.predicted_ms:.1f} "
                  f"profile={plan.profile_name or profile.name}")
            meas.meta["plan"] = plan.to_dict()
            # the planner's decision is a timeline instant event + span tag:
            # a merged multi-rank trace shows WHICH discipline each rank ran
            # next to the phases it produced (ISSUE 3 tentpole)
            meas.event("plan_decision", strategy=plan.strategy,
                       engine=plan.engine,
                       predicted_ms=round(plan.predicted_ms, 3))
            meas.set_trace_tags(strategy=plan.strategy, engine=plan.engine)
            if plan.engine == "chunked" and nodes == 1:
                if args.grid_chunk_tuples is None:
                    args.grid_chunk_tuples = plan.chunk_tuples or (1 << 20)
            elif plan.engine == "chunked":
                print("[PLAN] chunked engine is single-node; keeping the "
                      "in-core engine at this mesh size", file=sys.stderr)
            if plan.engine == "incore" and args.grid_chunk_tuples is None:
                cfg = cfg.replace(**plan.config_kwargs())
                if (plan.pipeline_repeats and args.repeat > 1
                        and not cfg.measure_phases):
                    args.pipeline_repeats = True

    engine = None
    if args.grid_chunk_tuples is None:
        if args.cpu_fallback:
            from tpu_radix_join.robustness.degrade import \
                engine_with_cpu_fallback
            engine, dinfo = engine_with_cpu_fallback(cfg, measurements=meas)
            if dinfo["degraded"]:
                # structured, parseable: key=value pairs after the marker
                print(f"[DEGRADE] failure_class={dinfo['failure_class']} "
                      f"backend=cpu nodes={dinfo['num_nodes']} "
                      f"error={dinfo['error']}", file=sys.stderr)
                cfg = engine.config
                nodes = cfg.num_nodes
        else:
            engine = HashJoin(cfg, measurements=meas, plan_cache=plan_cache)

    # elastic wiring: membership view (loss detection + epoch fencing) and,
    # with a checkpoint dir, the partition manifest (partition-level resume)
    elastic = args.elastic == "on"
    if engine is not None and (elastic or membership is not None):
        manifest = None
        if elastic and args.checkpoint_dir:
            from tpu_radix_join.robustness.checkpoint import PartitionManifest
            os.makedirs(args.checkpoint_dir, exist_ok=True)
            fp = (f"elastic:{args.outer_kind}:{args.tuples_per_node * nodes}:"
                  f"{args.seed}:{cfg.network_partition_count}")
            manifest = PartitionManifest(
                os.path.join(args.checkpoint_dir, "partitions.manifest"),
                fingerprint=fp, measurements=meas)
        engine.membership = membership
        engine.elastic = elastic
        engine.partition_manifest = manifest
        engine.elastic_grow = args.elastic_grow
        engine.hedge = args.hedge
        engine.hedge_threshold = args.hedge_threshold
        engine.straggle_factor = args.straggle_factor

    global_size = args.tuples_per_node * nodes
    meas.meta.update(tuples_per_node=args.tuples_per_node,
                     global_size=global_size, config=vars(args))
    inner = Relation(global_size, nodes, "unique", seed=args.seed)
    outer_kw = {}
    if args.outer_kind == "modulo":
        outer_kw["modulo"] = args.modulo or max(1, global_size // 4)
    elif args.outer_kind == "zipf":
        outer_kw["zipf_theta"] = args.zipf_theta
        outer_kw["key_domain"] = global_size
    outer = Relation(global_size, nodes, args.outer_kind,
                     seed=args.seed + 1, **outer_kw)

    expected = inner.expected_matches(outer)

    if args.grid_chunk_tuples is not None:
        return _run_grid(args, inner, outer, expected, meas, plan=plan)
    # Generate + place once, join --repeat times: the reference generates
    # before its join timers start (main.cpp:94-116), so repeats must not
    # re-pay generation/transfer — with host generation the device_put
    # completes lazily inside the first join's fence, silently inflating
    # JPROC by the transfer time on remote-attached devices.
    r_batch, s_batch = engine.place(inner), engine.place(outer)
    result = None
    # --trace: the reference brackets exactly the join with PAPI and writes
    # CTOTAL into every rank's perf file (Measurements.cpp:90-107,137); here
    # the profiler bracket wraps the same span and the xplane decoder turns
    # it into CTOTAL + the per-op table on exit (Measurements.trace).
    trace_ctx = (meas.trace(os.path.join(args.output_dir, "trace"))
                 if args.trace else contextlib.nullcontext())
    # hang watchdog (--watchdog-timeout): evidence first (stacks + bundle),
    # then the kill through the engine cancel hook — a hung collective
    # becomes a classified backend_unavailable exit, not a silent stall
    from tpu_radix_join.observability.watchdog import Watchdog, engine_killer
    from tpu_radix_join.planner.audit import (actuals_for_explain,
                                              audit_plan,
                                              critpath_for_explain,
                                              phase_snapshot)

    wd_ctx = (Watchdog(meas, timeout_s=args.watchdog_timeout,
                       kill=engine_killer(engine),
                       bundle_dir=_forensics_dir(args), config=vars(args),
                       membership=membership)
              if args.watchdog_timeout > 0 else contextlib.nullcontext())
    if elastic and engine is not None:
        # host-side regeneration source for recovery: the deterministic
        # Relation specs, never the distributed arrays (hash_join.join()
        # records the same pair on the Relations API path)
        engine._elastic_rel = (inner, outer)
    # Membership chaos sites arm on ONE injector: only the innermost
    # installed injector is consulted (faults.py stacking), so a driver
    # mixing --rank-death-at / --rank-join-at / --straggle-factor must
    # register every site on the same instance.  The victim of the
    # multi-rank recovery test additionally sets the suicide env var.
    from tpu_radix_join.robustness import faults as _faults
    death_ctx = contextlib.nullcontext()
    if args.rank_death_at or args.rank_join_at or args.straggle_factor > 0:
        inj = _faults.FaultInjector(seed=args.seed, measurements=meas)
        if args.rank_death_at:
            inj.arm(_faults.RANK_DEATH, at=args.rank_death_at)
        if args.rank_join_at:
            inj.arm(_faults.RANK_JOIN, at=args.rank_join_at)
        if args.straggle_factor > 0:
            inj.arm(_faults.COMPUTE_STRAGGLE, at=1)
        death_ctx = inj
    # --transfer-guard: the runtime half of the sync-point discipline —
    # the static rule (tools_lint.py) forbids implicit readback spellings;
    # this guard proves at run time that none slipped through a dynamic
    # path.  Armed around the join only: generation + placement transfer
    # by design (the reference pays them outside its timers too).
    tg_ctx = (jax.transfer_guard(args.transfer_guard)
              if args.transfer_guard != "off" else contextlib.nullcontext())
    times0 = phase_snapshot(meas)
    try:
        with trace_ctx, wd_ctx, death_ctx, tg_ctx:
            if args.pipeline_repeats and args.repeat > 1:
                result = engine.join_arrays_pipelined(r_batch, s_batch,
                                                      args.repeat)
            else:
                for i in range(args.repeat):
                    result = engine.join_arrays(r_batch, s_batch)
    except Exception as e:
        # terminal classified failure (watchdog trip, injected fault,
        # corruption): exit with the machine-readable class + a forensics
        # bundle; an unclassified exception stays a loud traceback
        cls = getattr(e, "failure_class", None)
        if cls is None:
            raise
        if "JTOTAL" in meas._starts:
            meas.stop("JTOTAL")
        meas.meta["failure_class"] = cls
        print(f"[RESULTS] failure/failure_class: {cls}")
        print(f"[RESULTS] failure/error: {e}", file=sys.stderr)
        bundle = _emit_failure_bundle(meas, e, args)
        if bundle:
            print(f"[FORENSICS] bundle {bundle}", file=sys.stderr)
        if args.output_dir:
            path = meas.store(args.output_dir)
            print(f"[PERF] stored {path}")
        return 1
    # plan-vs-actual audit (planner/audit.py): every planned join closes
    # the loop on the PR 2 cost model — measured JTOTAL vs predicted_ms,
    # PLANDRIFT gauge for the regress gate, and the explain table grows
    # its actuals column for the strategy that actually ran
    # critical-path attribution (observability/critpath.py): reconstruct
    # the path over this rank's live tracer stream (the cross-rank file
    # merge is tools_critical_path.py's post-run job), stamp it into the
    # registry meta so bundles and the ledger carry it, print the
    # [CRITPATH] line, and re-price the plan audit against the measured
    # bounding rank instead of the local mean
    cp = None
    if meas.tracer is not None:
        from tpu_radix_join.observability.critpath import (
            critical_path_from_tracer, format_summary)
        cp = critical_path_from_tracer(meas.tracer)
        meas.meta["critical_path"] = cp
        if jax.process_index() == 0:
            print(f"[CRITPATH] {format_summary(cp)}")
    audit = audit_plan(plan, meas, repeats=args.repeat, times0=times0,
                       critical_path=cp)
    if audit is not None and jax.process_index() == 0:
        print(f"[PLAN] actual_ms={audit['actual_ms']:.1f} "
              f"predicted_ms={audit['predicted_ms']:.1f} "
              f"drift={audit['drift_pct']:.1f}%")
        if plan_costs is not None and explain_tbl is not None:
            print(explain_tbl(plan_costs, plan,
                              actuals=actuals_for_explain(audit),
                              static=plan_static,
                              critpath=critpath_for_explain(audit)))
    # per-rank failure class rides the registry meta into the rank-0
    # aggregate report (performance.print_results): a multi-rank run where
    # one rank degraded must say so in the summary, not only in that
    # rank's own .info file
    meas.meta["failure_class"] = (result.diagnostics or {}).get(
        "failure_class", "ok" if result.ok else "unknown")
    # per-site fault-injection accounting (hits/fired, faults.site_stats):
    # rides into the rank-0 FaultSites aggregate next to FailureClasses
    if (result.diagnostics or {}).get("fault_sites"):
        meas.meta["fault_sites"] = result.diagnostics["fault_sites"]
    if args.repeat > 1:
        # RESULTS accumulates per join; the report's "Tuples" line means THE
        # join's result count.  Times/tuple counters stay cumulative (JRATE
        # divides cumulative tuples by cumulative time — consistent).
        meas.counters["RESULTS"] = result.matches
    if args.measure_phases or args.output_dir:
        # dispatch-floor tag: lets readers subtract the per-program host
        # round trip from the split phase columns (VERDICT r3 weak #6)
        meas.measure_dispatch_floor()

    if (result.diagnostics or {}).get("recovered"):
        d = result.diagnostics
        print(f"[RESULTS] recovered: epoch={d.get('membership_epoch')} "
              f"lost_ranks={d.get('lost_ranks')} "
              f"resumed={len(d.get('resumed_partitions') or [])} "
              f"recomputed={len(d.get('recovered_partitions') or [])}")
        if d.get("regrown"):
            print(f"[RESULTS] regrown: "
                  f"joined_ranks={d.get('joined_ranks_admitted')} "
                  f"survivors={d.get('survivors')}")
        if d.get("hedged"):
            print(f"[RESULTS] hedged: straggler={d.get('straggler')} "
                  f"partitions={d.get('hedged_partitions')} "
                  f"hedgewin={d.get('hedgewin')} "
                  f"specwaste={d.get('specwaste')}")
    # The reference's rank-0 aggregate report (Measurements.cpp:592-702):
    # multi-process worlds gather every rank's registry over the network
    # first (Measurements.gather_all); rank 0 alone prints.  After a rank
    # loss the gather itself is a collective on the dead mesh — skip it
    # and let the lowest SURVIVOR report from its own registry.
    lost = sorted(membership.lost) if membership is not None else []
    all_meas = (meas.gather_all() if distributed and not lost else [meas])
    if lost and membership.board.num_ranks > 1:
        reporter = membership.board.rank == min(membership.survivors)
    else:
        reporter = jax.process_index() == 0
    if reporter:
        if len(all_meas) == 1:
            # multi-rank runs get this line from print_results below
            print(f"[RESULTS] Tuples: {result.matches}")
        if expected is not None:
            status = "OK" if result.matches == expected else "MISMATCH"
            print(f"[RESULTS] Expected: {expected} ({status})")
        print(f"[RESULTS] Conservation: {'OK' if result.ok else 'VIOLATED'}")
        if not result.ok and result.diagnostics:
            for k, v in result.diagnostics.items():
                print(f"[RESULTS] failure/{k}: {v}")
        total_us = meas.times_us.get("JTOTAL", 0.0)
        if total_us:
            rate = (2 * global_size * args.repeat) / (total_us / 1e6)
            print(f"[RESULTS] Throughput: {rate / 1e6:.1f} M tuples/sec")
        if len(all_meas) > 1:
            from tpu_radix_join.performance import print_results
            print_results(all_meas)
        else:
            for line in meas.lines():
                print(f"[PERF] {line}")
    if args.output_dir:
        # the post-join memory checkpoint (JOIN_MEM_DEBUG analog,
        # main.cpp:32,68,92): lands in <rank>.info under "memory"
        meas.memory_utilization()
        path = meas.store(args.output_dir)
        if jax.process_index() == 0:
            print(f"[PERF] stored {path}")

    bad = (expected is not None and result.matches != expected) or not result.ok
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
