"""Per-shard partition histogram.

Replaces ``histograms/LocalHistogram.{h,cpp}``: one pass over the shard
counting tuples per network partition, radix = low
``NETWORK_PARTITIONING_FANOUT`` key bits (LocalHistogram.cpp:20,44-47).
"""

from __future__ import annotations

import jax.numpy as jnp

from tpu_radix_join.data.tuples import TupleBatch, partition_ids
from tpu_radix_join.ops.radix import local_histogram


def compute_local_histogram(batch: TupleBatch, fanout_bits: int,
                            valid: jnp.ndarray | None = None):
    """Returns (pid uint32 [n], histogram uint32 [1 << fanout_bits])."""
    pid = partition_ids(batch, fanout_bits)
    return pid, local_histogram(pid, 1 << fanout_bits, valid)
