"""Write-offset computation for the shuffle.

Replaces ``histograms/OffsetMap.{h,cpp}``, whose three arrays let every rank
write into disjoint slices of every other rank's RMA window with zero
coordination (OffsetMap.cpp:59-93):

  * base offsets    — running sum of the global histogram in assignment order
    per target node (OffsetMap.cpp:59-73);
  * relative offsets — ``MPI_Exscan(SUM)`` of local histograms
    (OffsetMap.cpp:75-85);
  * absolute = base + relative (OffsetMap.cpp:87-93).

On the TPU mesh the *data plane* is a dense ``all_to_all`` of fixed-capacity
blocks (parallel/window.py), so absolute write offsets are not needed to avoid
races — but the same quantities drive the receive-side compaction (where each
sender's run lands inside the owner's contiguous partition storage) and the
conservation checks.  ``MPI_Exscan`` becomes an ``all_gather`` of local
histograms plus a masked sum over ranks below self — one ICI collective.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from tpu_radix_join.parallel.mesh import AxisName


class Offsets(NamedTuple):
    base: jnp.ndarray        # uint32 [P]   start of each partition in owner-order storage
    relative: jnp.ndarray    # uint32 [P]   this rank's exclusive prefix among ranks
    absolute: jnp.ndarray    # uint32 [P]   base + relative
    all_local_hists: jnp.ndarray  # uint32 [N, P] gathered local histograms


def compute_offsets(
    local_hist: jnp.ndarray,
    global_hist: jnp.ndarray,
    assignment: jnp.ndarray,
    axis_name: AxisName,
) -> Offsets:
    """Runs inside shard_map; all shapes static.

    base[p]: for each owner node, its assigned partitions are laid out in
    partition-id order; base[p] is the running sum of global counts of the
    owner's earlier partitions (OffsetMap.cpp:59-73 does the same walk).
    """
    num_partitions = global_hist.shape[0]
    p_idx = jnp.arange(num_partitions, dtype=jnp.uint32)
    same_owner = assignment[None, :] == assignment[:, None]        # [P, P]
    earlier = p_idx[None, :] < p_idx[:, None]                      # [P, P]
    base = jnp.sum(
        jnp.where(same_owner & earlier, global_hist[None, :], 0), axis=1
    ).astype(jnp.uint32)

    all_hists = jax.lax.all_gather(local_hist, axis_name)          # [N, P]
    all_hists = all_hists.reshape((-1,) + local_hist.shape)        # flatten mesh axes
    my = jax.lax.axis_index(axis_name)
    ranks = jnp.arange(all_hists.shape[0], dtype=jnp.int32)
    relative = jnp.sum(
        jnp.where((ranks < my)[:, None], all_hists, 0), axis=0
    ).astype(jnp.uint32)

    return Offsets(base=base, relative=relative,
                   absolute=base + relative, all_local_hists=all_hists)
