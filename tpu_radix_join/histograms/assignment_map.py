"""Partition -> owner-node assignment.

Replaces ``histograms/AssignmentMap.{h,cpp}``.  The reference's policy is
round-robin ``p % numberOfNodes`` (AssignmentMap.cpp:41-43), but its
constructor takes both global histograms (AssignmentMap.cpp:17-23) — an API
shaped for load-aware assignment it never implements.  We implement both:

  * ``round_robin`` — exact parity with the reference.
  * ``load_aware``  — greedy longest-processing-time: partitions are taken in
    decreasing combined (R+S) size and each is assigned to the currently
    least-loaded node.  This is the capability the skew (Zipf) benchmark
    config targets (SURVEY.md §2.1 AssignmentMap note) and the distributed
    counterpart of the dormant GPU skew machinery
    (kernels_optimized.cu:301-344).

Both run identically on every node (deterministic on replicated global
histograms), so no broadcast is needed — same as the reference where every
rank recomputes the map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_robin_assignment(num_partitions: int, num_nodes: int) -> jnp.ndarray:
    """assignment[p] = p % numberOfNodes (AssignmentMap.cpp:41-43)."""
    return (jnp.arange(num_partitions, dtype=jnp.uint32) % jnp.uint32(num_nodes))


def load_aware_assignment(
    inner_global_hist: jnp.ndarray, outer_global_hist: jnp.ndarray, num_nodes: int
) -> jnp.ndarray:
    """Greedy LPT assignment on combined partition weights.

    Static shapes throughout: a ``lax.scan`` over the (static) partition count,
    carrying per-node load accumulators.  The weight model is R+S tuple count —
    the shuffle bytes and probe work are both linear in it.
    """
    weight = inner_global_hist.astype(jnp.float32) + outer_global_hist.astype(jnp.float32)
    num_partitions = weight.shape[0]
    order = jnp.argsort(-weight)  # heaviest first

    def step(loads, p):
        node = jnp.argmin(loads).astype(jnp.uint32)
        loads = loads.at[node].add(weight[p])
        return loads, (p, node)

    # On legacy jax/XLA a rolled scan here aborts the process: the old
    # sharding-propagation pass CHECK-fails on a while-loop whose outputs
    # feed sharded consumers (utils/compat.is_legacy).  Full unroll emits
    # straight-line HLO — same math, no loop for the pass to choke on.
    from tpu_radix_join.utils import compat
    unroll = num_partitions if compat.is_legacy() else 1
    _, (ps, nodes) = jax.lax.scan(step, jnp.zeros((num_nodes,), jnp.float32),
                                  order, unroll=unroll)
    assignment = jnp.zeros((num_partitions,), jnp.uint32).at[ps].set(nodes)
    return assignment


def compute_partition_assignment(
    inner_global_hist: jnp.ndarray,
    outer_global_hist: jnp.ndarray,
    num_nodes: int,
    policy: str = "round_robin",
) -> jnp.ndarray:
    """uint32 [P] with values in [0, num_nodes)."""
    num_partitions = inner_global_hist.shape[0]
    if policy == "round_robin":
        return round_robin_assignment(num_partitions, num_nodes)
    if policy == "load_aware":
        return load_aware_assignment(inner_global_hist, outer_global_hist, num_nodes)
    raise ValueError(f"unknown assignment policy {policy!r}")
