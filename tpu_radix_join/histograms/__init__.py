from tpu_radix_join.histograms.local_histogram import compute_local_histogram
from tpu_radix_join.histograms.global_histogram import compute_global_histogram
from tpu_radix_join.histograms.assignment_map import compute_partition_assignment
from tpu_radix_join.histograms.offset_map import compute_offsets

__all__ = [
    "compute_local_histogram",
    "compute_global_histogram",
    "compute_partition_assignment",
    "compute_offsets",
]
