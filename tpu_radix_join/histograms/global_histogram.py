"""Global partition histogram.

Replaces ``histograms/GlobalHistogram.{h,cpp}``: the reference sums local
histograms with ``MPI_Allreduce(UINT64, SUM)`` (GlobalHistogram.cpp:37-42);
on a TPU mesh this is ``jax.lax.psum`` over the nodes axis — one ICI
all-reduce, called from inside the shard_map'd pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def compute_global_histogram(local_hist: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """uint32 [P] -> uint32 [P], summed across the mesh axis."""
    return jax.lax.psum(local_hist, axis_name)
