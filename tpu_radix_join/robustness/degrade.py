"""Graceful degradation: build the engine somewhere, even when the
accelerator is gone.

``engine_with_cpu_fallback`` is the resilient twin of constructing
``HashJoin`` directly: when device/mesh initialization fails (a real dead
TPU, a mis-sized mesh, or the injectable ``engine.device_init`` fault
site), it rebuilds over the host CPU devices instead of propagating the
error — a correctness-preserving, slower fallback, reported loudly via a
structured warning, a ``degrade`` trace event, and the returned info dict
(``failure_class="device_unavailable"``).

Kept out of ``robustness/__init__`` on purpose: importing it pulls the
whole engine stack, which the leaf modules (faults/retry/checkpoint) must
not depend on.
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import jax
import numpy as np

from tpu_radix_join.robustness.retry import DEVICE_UNAVAILABLE


def build_cpu_engine(config, measurements=None, plan_cache=None
                     ) -> Tuple[object, dict]:
    """Construct a ``HashJoin`` over the host CPU devices, shrinking
    ``num_nodes`` to the available CPU count and collapsing ``num_hosts``
    to 1 (a degraded run is local by definition).

    This is the shared fallback recipe: ``engine_with_cpu_fallback`` uses
    it at construction time, and the service's circuit breaker
    (service/session.py) uses it at query time to keep serving while the
    chip backend is open-circuited.  Returns (engine, info) where info
    carries ``num_nodes`` and ``backend="cpu"``.  CPU-construction
    failures propagate: with no device anywhere there is nothing to
    degrade to.
    """
    from jax.sharding import Mesh

    from tpu_radix_join.operators.hash_join import HashJoin

    cpu = jax.devices("cpu")
    n = min(config.num_nodes, len(cpu))
    cfg = config.replace(num_nodes=n, num_hosts=1)
    cpu_mesh = Mesh(np.asarray(cpu[:n]), (cfg.mesh_axis,))
    engine = HashJoin(cfg, mesh=cpu_mesh, measurements=measurements,
                      plan_cache=plan_cache)
    return engine, {"backend": "cpu", "num_nodes": n}


def engine_with_cpu_fallback(config, measurements=None, mesh=None
                             ) -> Tuple[object, dict]:
    """(engine, info): a constructed ``HashJoin`` plus how it was obtained.

    ``info["degraded"]`` is False when the primary construction succeeded;
    on fallback it is True and ``info`` carries ``failure_class``,
    ``error`` (repr of the primary failure), and ``backend="cpu"``.  The
    fallback shrinks ``num_nodes`` to the available CPU device count when
    needed (and collapses ``num_hosts`` to 1 — a degraded run is local by
    definition), so a pod-sized config still produces a working engine.
    CPU-construction failures propagate: with no device anywhere there is
    nothing to degrade to.
    """
    from tpu_radix_join.operators.hash_join import HashJoin

    try:
        engine = HashJoin(config, mesh=mesh, measurements=measurements)
        return engine, {"degraded": False,
                        "backend": jax.devices()[0].platform}
    except Exception as e:   # noqa: BLE001 — any init failure degrades
        primary_error = e

    engine, cpu_info = build_cpu_engine(config, measurements=measurements)
    n = cpu_info["num_nodes"]
    info = {"degraded": True, "backend": "cpu",
            "failure_class": DEVICE_UNAVAILABLE,
            "num_nodes": n, "error": repr(primary_error)}
    warnings.warn(
        f"[DEGRADE] device init failed ({primary_error!r}); running on "
        f"{n} CPU device(s) — expect reduced throughput", RuntimeWarning,
        stacklevel=2)
    if measurements is not None:
        measurements.event("degrade", to="cpu", num_nodes=n,
                           error=repr(primary_error))
    return engine, info
