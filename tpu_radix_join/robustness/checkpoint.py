"""Atomic checkpoint/resume for out-of-core joins.

Generalizes the checkpoint discipline that grew inside
``ops/chunked.chunked_join_grid`` into a reusable manager, so a killed
1B-row grid run resumes from its last completed chunk pair instead of
restarting (the single-shot reference has no such capability, SURVEY.md
§5.4).  File format (JSON, one object):

    {"<cursor/count fields...>", "done": bool, "fingerprint": {...}}

Rules:

  * **Atomicity** — writes go to ``<path>.tmp.<pid>`` then ``fsync`` +
    ``os.replace`` (the utils/locks.py rename discipline): a reader never
    observes a torn file, a crash mid-write leaves the previous checkpoint
    intact.
  * **Fingerprint** — a JSON-serializable dict identifying the run
    (slab size, input tag, grid shape, ...).  ``load`` raises
    :class:`CheckpointMismatch` when the file's fingerprint differs:
    resuming a *different* join from a stale file would silently return a
    wrong total.  Callers choose the fields; equality is exact.
  * **Corruption** — unreadable/truncated files restart from scratch
    (``load`` returns None) rather than wedging every rerun.
  * **Durability beats availability for writes** — a failed *save* must not
    kill a healthy multi-hour join: I/O errors are swallowed into a
    ``checkpoint_save_failed`` trace event (the run just loses one resume
    point).

Counters: ``CKPTSAVE`` per checkpoint written, ``CKPTLOAD`` per successful
resume (missing files count neither).
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Optional

from tpu_radix_join.performance.measurements import CKPTLOAD, CKPTSAVE
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness.retry import CHECKPOINT_MISMATCH


class CheckpointMismatch(ValueError):
    """Checkpoint fingerprint does not match the current run config."""

    failure_class = CHECKPOINT_MISMATCH


class CheckpointManager:
    """One checkpoint file + fingerprint guard (see module docstring)."""

    def __init__(self, path: str, fingerprint: dict, measurements=None):
        self.path = path
        self.fingerprint = fingerprint
        self.measurements = measurements

    def _span(self, name: str):
        m = self.measurements
        return m.span(name) if m is not None else contextlib.nullcontext()

    def load(self) -> Optional[dict]:
        """The saved state dict (including ``done``), or None when there is
        nothing valid to resume from.  Raises :class:`CheckpointMismatch` on
        a fingerprint conflict — never silently resumes the wrong join."""
        m = self.measurements
        if not os.path.exists(self.path):
            return None
        try:
            with self._span("ckpt_load"):
                _faults.check(_faults.CKPT_LOAD, m)
                with open(self.path) as f:
                    state = json.load(f)
                saved_fp = state.pop("fingerprint")
        except (json.JSONDecodeError, KeyError, OSError) as e:
            # truncated/corrupt checkpoint: restart from zero rather than
            # wedging every rerun on an unreadable file
            if m is not None:
                m.event("checkpoint_corrupt", path=self.path, error=repr(e))
            return None
        if saved_fp != self.fingerprint:
            raise CheckpointMismatch(
                f"checkpoint {self.path} belongs to a different join "
                f"({saved_fp} != {self.fingerprint}); remove it or use a "
                f"distinct fingerprint/tag")
        if m is not None:
            m.incr(CKPTLOAD)
            m.event("checkpoint_load", path=self.path,
                    done=bool(state.get("done")))
        return state

    def save(self, state: dict, done: bool = False,
             span: str = "ckpt_save") -> bool:
        """Atomically persist ``state`` (+ ``done`` + fingerprint); returns
        False (after recording a trace event) on I/O failure instead of
        raising — losing one resume point must not kill the join.

        ``span`` names the timeline span the write is recorded under:
        "ckpt_save" for synchronous critical-path saves, "ckpt_flush" when
        the write happens on the :class:`AsyncCheckpointWriter`'s flush
        thread (off the critical path — the distinction is what the
        overlap timeline shows)."""
        m = self.measurements
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with self._span(span):
                _faults.check(_faults.CKPT_SAVE, m)
                with open(tmp, "w") as f:
                    json.dump({**state, "done": done,
                               "fingerprint": self.fingerprint}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
        except OSError as e:
            if m is not None:
                m.event("checkpoint_save_failed", path=self.path,
                        error=repr(e))
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        if m is not None:
            m.incr(CKPTSAVE)
        return True


class AsyncCheckpointWriter:
    """Write-behind mode for a :class:`CheckpointManager`: ``save()``
    enqueues and returns immediately; a single daemon thread performs the
    fsync + rename while the caller computes the next chunk pair
    (ops/chunked.py pipelined grid).

    Semantics that preserve the "every saved pair is realized" resume
    invariant:

      * **Latest-wins coalescing** — the queue holds at most ONE pending
        state; enqueueing replaces it.  A newer state always covers a
        strict superset of realized pairs, so dropping the older write
        loses at most one resume point, never correctness (the same
        trade the manager's swallowed-save rule already makes).
      * **Callers enqueue only realized states** — the grid resolves a
        pair's device counts to a host total *before* enqueueing, so no
        state on disk ever claims an unrealized pair.
      * **flush() is a barrier** — returns only once every enqueued state
        has hit the disk (or failed into the manager's
        ``checkpoint_save_failed`` event); the grid flushes before its
        final synchronous ``done=True`` save and on every exit path.

    Writes are recorded under the "ckpt_flush" span (the timeline shows
    them overlapping the next pair's "grid_pair" span instead of
    serializing after it).
    """

    def __init__(self, manager: CheckpointManager):
        import threading
        self._mgr = manager
        self._cond = threading.Condition()
        self._pending = None          # (state, done) | None
        self._busy = False
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="ckpt-write-behind", daemon=True)
        self._thread.start()

    def save(self, state: dict, done: bool = False) -> None:
        with self._cond:
            self._pending = (dict(state), done)
            self._cond.notify_all()

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return            # stopped with nothing left to write
                state, done = self._pending
                self._pending = None
                self._busy = True
            try:
                self._mgr.save(state, done=done, span="ckpt_flush")
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def flush(self) -> None:
        """Barrier: every state enqueued before this call is on disk (or
        recorded as a failed save) when it returns."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()

    def close(self) -> None:
        """Flush outstanding writes and stop the thread (idempotent)."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
