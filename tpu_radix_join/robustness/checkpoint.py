"""Atomic checkpoint/resume for out-of-core joins.

Generalizes the checkpoint discipline that grew inside
``ops/chunked.chunked_join_grid`` into a reusable manager, so a killed
1B-row grid run resumes from its last completed chunk pair instead of
restarting (the single-shot reference has no such capability, SURVEY.md
§5.4).  File format (JSON, one object):

    {"<cursor/count fields...>", "done": bool, "fingerprint": {...}}

Rules:

  * **Atomicity** — writes go to ``<path>.tmp.<pid>`` then ``fsync`` +
    ``os.replace`` (the utils/locks.py rename discipline): a reader never
    observes a torn file, a crash mid-write leaves the previous checkpoint
    intact.
  * **Fingerprint** — a JSON-serializable dict identifying the run
    (slab size, input tag, grid shape, ...).  ``load`` raises
    :class:`CheckpointMismatch` when the file's fingerprint differs:
    resuming a *different* join from a stale file would silently return a
    wrong total.  Callers choose the fields; equality is exact.
  * **Corruption** — unreadable/truncated files restart from scratch
    (``load`` returns None) rather than wedging every rerun.
  * **Durability beats availability for writes** — a failed *save* must not
    kill a healthy multi-hour join: I/O errors are swallowed into a
    ``checkpoint_save_failed`` trace event (the run just loses one resume
    point).

Counters: ``CKPTSAVE`` per checkpoint written, ``CKPTLOAD`` per successful
resume (missing files count neither).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
from typing import Dict, Optional

from tpu_radix_join.performance.measurements import CKPTLOAD, CKPTSAVE
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness.retry import CHECKPOINT_MISMATCH


class CheckpointMismatch(ValueError):
    """Checkpoint fingerprint does not match the current run config."""

    failure_class = CHECKPOINT_MISMATCH


class CheckpointManager:
    """One checkpoint file + fingerprint guard (see module docstring)."""

    def __init__(self, path: str, fingerprint: dict, measurements=None):
        self.path = path
        self.fingerprint = fingerprint
        self.measurements = measurements

    def _span(self, name: str):
        m = self.measurements
        return m.span(name) if m is not None else contextlib.nullcontext()

    def load(self) -> Optional[dict]:
        """The saved state dict (including ``done``), or None when there is
        nothing valid to resume from.  Raises :class:`CheckpointMismatch` on
        a fingerprint conflict — never silently resumes the wrong join."""
        m = self.measurements
        if not os.path.exists(self.path):
            return None
        try:
            with self._span("ckpt_load"):
                _faults.check(_faults.CKPT_LOAD, m)
                with open(self.path) as f:
                    state = json.load(f)
                saved_fp = state.pop("fingerprint")
        except (json.JSONDecodeError, KeyError, OSError) as e:
            # truncated/corrupt checkpoint: restart from zero rather than
            # wedging every rerun on an unreadable file
            if m is not None:
                m.event("checkpoint_corrupt", path=self.path, error=repr(e))
            return None
        if saved_fp != self.fingerprint:
            raise CheckpointMismatch(
                f"checkpoint {self.path} belongs to a different join "
                f"({saved_fp} != {self.fingerprint}); remove it or use a "
                f"distinct fingerprint/tag")
        if m is not None:
            m.incr(CKPTLOAD)
            m.event("checkpoint_load", path=self.path,
                    done=bool(state.get("done")))
        return state

    def save(self, state: dict, done: bool = False,
             span: str = "ckpt_save") -> bool:
        """Atomically persist ``state`` (+ ``done`` + fingerprint); returns
        False (after recording a trace event) on I/O failure instead of
        raising — losing one resume point must not kill the join.

        ``span`` names the timeline span the write is recorded under:
        "ckpt_save" for synchronous critical-path saves, "ckpt_flush" when
        the write happens on the :class:`AsyncCheckpointWriter`'s flush
        thread (off the critical path — the distinction is what the
        overlap timeline shows)."""
        m = self.measurements
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with self._span(span):
                _faults.check(_faults.CKPT_SAVE, m)
                with open(tmp, "w") as f:
                    json.dump({**state, "done": done,
                               "fingerprint": self.fingerprint}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
        except OSError as e:
            if m is not None:
                m.event("checkpoint_save_failed", path=self.path,
                        error=repr(e))
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        if m is not None:
            m.incr(CKPTSAVE)
        return True


class AsyncCheckpointWriter:
    """Write-behind mode for a :class:`CheckpointManager`: ``save()``
    enqueues and returns immediately; a single daemon thread performs the
    fsync + rename while the caller computes the next chunk pair
    (ops/chunked.py pipelined grid).

    Semantics that preserve the "every saved pair is realized" resume
    invariant:

      * **Latest-wins coalescing** — the queue holds at most ONE pending
        state; enqueueing replaces it.  A newer state always covers a
        strict superset of realized pairs, so dropping the older write
        loses at most one resume point, never correctness (the same
        trade the manager's swallowed-save rule already makes).
      * **Callers enqueue only realized states** — the grid resolves a
        pair's device counts to a host total *before* enqueueing, so no
        state on disk ever claims an unrealized pair.
      * **flush() is a barrier** — returns only once every enqueued state
        has hit the disk (or failed into the manager's
        ``checkpoint_save_failed`` event); the grid flushes before its
        final synchronous ``done=True`` save and on every exit path.

    Writes are recorded under the "ckpt_flush" span (the timeline shows
    them overlapping the next pair's "grid_pair" span instead of
    serializing after it).
    """

    def __init__(self, manager: CheckpointManager):
        import threading
        self._mgr = manager
        self._cond = threading.Condition()
        self._pending = None          # (state, done) | None
        self._busy = False
        self._stop = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="ckpt-write-behind", daemon=True)
        self._thread.start()
        # The flush thread is a daemon: a clean sys.exit between save()
        # and flush() would kill it mid-queue and silently drop the final
        # checkpoint.  Registering close() guarantees the interpreter
        # drains the queue on any non-SIGKILL exit; explicit close()
        # unregisters so a long-lived process doesn't accumulate dead
        # callbacks.
        atexit.register(self.close)

    def save(self, state: dict, done: bool = False) -> None:
        with self._cond:
            self._pending = (dict(state), done)
            self._cond.notify_all()

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return            # stopped with nothing left to write
                state, done = self._pending
                self._pending = None
                self._busy = True
            try:
                self._mgr.save(state, done=done, span="ckpt_flush")
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def flush(self) -> None:
        """Barrier: every state enqueued before this call is on disk (or
        recorded as a failed save) when it returns."""
        with self._cond:
            while self._pending is not None or self._busy:
                self._cond.wait()

    def close(self) -> None:
        """Flush outstanding writes and stop the thread (idempotent —
        safe to call explicitly, from ``with``-exit, and again from the
        atexit hook)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        try:
            atexit.unregister(self.close)
        except Exception:       # pragma: no cover - interpreter teardown
            pass

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PartitionManifest:
    """Append-only per-partition completion manifest (elastic recovery).

    Extends the checkpoint discipline from "one cursor per grid run" to
    *partition granularity*: one JSONL line per realized network
    partition —

        {"fingerprint": {...}, "schema": 1}          # header line
        {"partition": 3, "count": 4096, "owner": 1, "epoch": 0}
        ...

    Rules carried over from :class:`CheckpointManager`:

      * **Kill-never-overclaims** — callers append a line only AFTER the
        partition's count is realized on host; the last line of a
        killed writer may be torn and is skipped on read, so the
        manifest never claims unrealized work.
      * **Fingerprint guard** — the header binds the manifest to one
        (inputs, geometry) identity; a conflicting header raises
        :class:`CheckpointMismatch` (resuming counts from a different
        join would splice wrong totals), a corrupt header restarts from
        zero.
      * **Durability beats availability** — a failed append is swallowed
        into a ``manifest_append_failed`` event (the run loses one
        resume point, not its life).

    Recovery (robustness/recovery.py) reads :meth:`completed` to skip
    every realized partition and recompute exactly the lost rank's
    unfinished ones; the ``owner``/``epoch`` stamps make the recovery
    timeline reconstructible in post-mortem bundles.

    **Fencing (hedge-never-double-counts)** — per partition, a line at a
    strictly newer epoch supersedes (a partition re-realized after a
    membership change owns its new count), but within one epoch the
    FIRST writer wins: when a straggler hedge (robustness/straggler.py)
    realizes a partition before its slow original owner does, the
    original's late line is dead on arrival — read-side arbitration, so
    two uncoordinated appenders can never sum the same partition twice.
    :meth:`claim` records hedge intent (forensics + the HEDGEWIN /
    SPECWASTE split); the *done* line remains the only count arbiter.
    """

    def __init__(self, path: str, fingerprint: dict, measurements=None):
        self.path = path
        self.fingerprint = fingerprint
        self.measurements = measurements
        self._ensure_header()

    def _ensure_header(self) -> None:
        m = self.measurements
        header = None
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    header = json.loads(f.readline())
            except (OSError, json.JSONDecodeError) as e:
                if m is not None:
                    m.event("manifest_corrupt", path=self.path,
                            error=repr(e))
                header = None
        if header is not None:
            if header.get("fingerprint") != self.fingerprint:
                raise CheckpointMismatch(
                    f"partition manifest {self.path} belongs to a different "
                    f"join ({header.get('fingerprint')} != "
                    f"{self.fingerprint}); remove it or use a distinct "
                    f"fingerprint/tag")
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({"fingerprint": self.fingerprint, "schema": 1}, f)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError as e:
            if m is not None:
                m.event("manifest_init_failed", path=self.path,
                        error=repr(e))
            try:
                os.remove(tmp)
            except OSError:
                pass

    def mark_done(self, partition: int, count: int, owner: int,
                  epoch: int = 0) -> bool:
        """Append one realized-partition line; False (after an event) on
        I/O failure instead of raising."""
        m = self.measurements
        rec = {"partition": int(partition), "count": int(count),
               "owner": int(owner), "epoch": int(epoch)}
        try:
            with open(self.path, "a") as f:
                json.dump(rec, f)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            if m is not None:
                m.event("manifest_append_failed", path=self.path,
                        error=repr(e))
            return False
        if m is not None:
            m.incr(CKPTSAVE)
        return True

    def mark_many(self, counts: Dict[int, int], owner_of, epoch: int = 0
                  ) -> int:
        """Bulk append (join epilogue: every partition realized at once).
        ``owner_of(p)`` maps a partition to its owner rank.  Returns the
        number of lines written."""
        n = 0
        for p, c in counts.items():
            if self.mark_done(p, c, owner_of(p), epoch):
                n += 1
        return n

    def completed(self) -> Dict[int, dict]:
        """``{partition: {"count", "owner", "epoch"}}`` of every realized
        partition; torn/corrupt lines are skipped — the
        kill-never-overclaims read side.  Arbitration per partition: a
        strictly newer epoch supersedes, and within one epoch the first
        writer wins (the hedge fence — a late-finishing original can
        never displace the speculative count that already landed)."""
        out: Dict[int, dict] = {}
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines[1:]:
            try:
                rec = json.loads(line)
                if "count" not in rec:
                    continue        # claim line, not a done line
                p = int(rec["partition"])
                ep = int(rec.get("epoch", 0))
                if p in out and ep <= out[p]["epoch"]:
                    continue        # first writer already won this epoch
                out[p] = {"count": int(rec["count"]),
                          "owner": int(rec["owner"]), "epoch": ep}
            except (ValueError, KeyError, json.JSONDecodeError):
                continue
        return out

    # ------------------------------------------------------------- claims
    def claim(self, partition: int, owner: int, epoch: int = 0) -> bool:
        """Record hedge intent on a partition; returns True when this
        ``(owner, epoch)`` holds the claim (first claimant at the highest
        epoch), False when a rival claimed it first or the partition is
        already done at ``epoch`` or newer.  Claims are advisory — they
        split HEDGEWIN from SPECWASTE and render in the post-mortem
        timeline — while the *done*-line fence in :meth:`completed`
        remains the count arbiter, so a lost claim race can waste work
        but never double-count."""
        m = self.measurements
        done = self.completed().get(int(partition))
        if done is not None and done["epoch"] >= int(epoch):
            return False
        holder = self.claims().get(int(partition))
        if holder is not None and holder["epoch"] >= int(epoch):
            return (holder["owner"] == int(owner)
                    and holder["epoch"] == int(epoch))
        rec = {"partition": int(partition), "claim": True,
               "owner": int(owner), "epoch": int(epoch)}
        try:
            with open(self.path, "a") as f:
                json.dump(rec, f)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            if m is not None:
                m.event("manifest_append_failed", path=self.path,
                        error=repr(e))
            return False
        if m is not None:
            m.event("hedge_claim", partition=int(partition),
                    owner=int(owner), epoch=int(epoch))
        return True

    def claims(self) -> Dict[int, dict]:
        """``{partition: {"owner", "epoch"}}`` of every claimed partition,
        arbitrated like :meth:`completed` (newer epoch supersedes, first
        claimant wins within an epoch)."""
        out: Dict[int, dict] = {}
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for line in lines[1:]:
            try:
                rec = json.loads(line)
                if not rec.get("claim"):
                    continue
                p = int(rec["partition"])
                ep = int(rec.get("epoch", 0))
                if p in out and ep <= out[p]["epoch"]:
                    continue
                out[p] = {"owner": int(rec["owner"]), "epoch": ep}
            except (ValueError, KeyError, json.JSONDecodeError):
                continue
        return out

    def audit(self) -> dict:
        """The double-count audit the chaos soak asserts on: the fenced
        total (sum of winning counts), plus every partition where a
        second writer's same-epoch line was fenced out — absorbed
        double-count attempts, each one a would-have-been wrong total."""
        winners = self.completed()
        fenced: Dict[int, int] = {}
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            lines = []
        for line in lines[1:]:
            try:
                rec = json.loads(line)
                if "count" not in rec:
                    continue
                p = int(rec["partition"])
                win = winners.get(p)
                if (win is not None and int(rec.get("epoch", 0)) == win["epoch"]
                        and int(rec["owner"]) != win["owner"]):
                    fenced[p] = fenced.get(p, 0) + 1
            except (ValueError, KeyError, json.JSONDecodeError):
                continue
        return {"total": sum(rec["count"] for rec in winners.values()),
                "partitions": len(winners),
                "fenced_duplicates": fenced}
