"""Lease-based rank membership with epoch fencing.

The reference pipeline assumes every MPI rank survives the whole job; so
did this port until now — a rank dying mid-run left its peers blocked in
a collective until the watchdog converted the stall into a
``backend_unavailable`` suicide.  This module turns rank loss into a
*detectable, classified* condition:

  * **Leases** — every rank heartbeats a small epoch-stamped JSON lease
    into a shared run directory (:class:`LeaseBoard`).  Heartbeats ride
    an existing cadence (the MetricsSampler's daemon tick via
    :meth:`LeaseBoard.sampler_extra`, or any caller loop); a write is
    atomic (tmp + ``os.replace``, the checkpoint.py discipline) and
    *never raises* — a full disk must not kill a healthy rank.
  * **Lapse detection** — a rank whose lease is older than ``lease_s``
    (or which never wrote one within the grace window) is *lapsed*.
    Wall-clock (``time.time``) timestamps are used deliberately: they
    are the only clock comparable across processes, and the lease
    window is seconds-coarse, far above credible host skew on one
    machine or a TPU pod's NTP-disciplined hosts.
  * **Epoch fencing** — :class:`MembershipView` turns lapses into a
    declaration: the rank joins the ``lost`` set, ``RANKLOST`` ticks,
    and the **membership epoch** bumps (``MEPOCH``).  Work stamped with
    an older epoch (compiled plans, exchange plans, warm capacity
    entries) is rejected via :meth:`MembershipView.fence` raising
    :class:`StaleEpoch` — stale collectives from the old mesh shape die
    loudly instead of deadlocking against a peer that no longer exists.

Every survivor computes the same view independently from the shared
lease directory — no coordinator, no broadcast (the assignment-map
discipline: deterministic recomputation beats agreement protocols at
this scale).

The watchdog integration is duck-typed (observability stays
dependency-free of robustness): :meth:`MembershipView.suspect` returns a
ready-to-deliver :class:`RankLost` when a lapsed lease explains a stall,
else None — the watchdog's trip path consults it before classifying the
stall as ``backend_unavailable``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tpu_radix_join.performance.measurements import MEPOCH, RANKLOST
from tpu_radix_join.robustness.retry import RANK_LOST


class RankLost(ConnectionError):
    """A peer rank's lease lapsed (or its death was injected) mid-run.

    Deliberately NOT blind-retryable (see retry.py's class catalog): the
    remedy is the elastic-recovery path — fence the epoch, re-plan on
    the survivor mesh, resume at partition granularity
    (robustness/recovery.py) — never a same-shape rerun, which would
    block on the same dead collective."""

    failure_class = RANK_LOST

    def __init__(self, rank: int, epoch: int, detail: str = ""):
        super().__init__(
            f"rank {rank} lost at membership epoch {epoch}"
            + (f": {detail}" if detail else ""))
        self.rank = rank
        self.epoch = epoch
        # forensics bundles fold this in next to the error repr
        # (main._emit_failure_bundle), same contract as CoordinatorTimeout
        self.bundle_extra = {"lost_rank": rank, "membership_epoch": epoch}


class StaleEpoch(RuntimeError):
    """Epoch-fenced rejection: work stamped with an old membership epoch
    reached a collective/dispatch boundary after the mesh shrank.  Shares
    the ``rank_lost`` failure class — the *cause* is the lost rank; the
    fence merely converts what would have been a deadlock into a
    classified exit the recovery path owns."""

    failure_class = RANK_LOST

    def __init__(self, stamped: int, current: int):
        super().__init__(
            f"stale membership epoch: work stamped epoch {stamped} but the "
            f"mesh is at epoch {current} — re-plan on the survivor mesh")
        self.stamped = stamped
        self.current = current


@dataclass(frozen=True)
class Lease:
    """One rank's most recent heartbeat."""

    rank: int
    epoch: int
    t_epoch_s: float
    pid: int
    host: str
    seq: int


class LeaseBoard:
    """Per-rank lease files in a shared run directory.

    File ``lease_r<rank>.json`` holds one :class:`Lease` as JSON; writes
    are atomic (tmp + ``os.replace``) so a reader never observes a torn
    lease, and :meth:`heartbeat` never raises — losing one heartbeat to
    a transient I/O error must not kill a healthy rank (the same
    durability-beats-availability rule as checkpoint saves).
    """

    def __init__(self, run_dir: str, rank: int, num_ranks: int,
                 lease_s: float = 5.0,
                 clock: Callable[[], float] = time.time,
                 measurements=None):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be positive, got {lease_s}")
        self.run_dir = run_dir
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self.lease_s = float(lease_s)
        self.clock = clock
        self.measurements = measurements
        self._seq = 0
        # heartbeat() runs on the metrics-sampler daemon tick (via
        # sampler_extra) AND on the main thread's join loop — unguarded,
        # both racers share one ``<path>.tmp.<pid>`` scratch name, so a
        # torn interleaving can replace a half-written lease
        self._lock = threading.Lock()
        self._t0 = clock()      # grace anchor for never-heartbeated ranks
        os.makedirs(run_dir, exist_ok=True)

    def lease_path(self, rank: int) -> str:
        return os.path.join(self.run_dir, f"lease_r{rank}.json")

    # ------------------------------------------------------------ heartbeat
    def heartbeat(self, epoch: int = 0) -> dict:
        """Write this rank's lease; returns the lease dict (merged into
        sampler ticks by :meth:`sampler_extra`).  Never raises."""
        with self._lock:
            self._seq += 1
            rec = {"rank": self.rank, "epoch": int(epoch),
                   "t_epoch_s": self.clock(), "pid": os.getpid(),
                   "host": socket.gethostname(), "seq": self._seq}
            path = self.lease_path(self.rank)
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                    f.flush()
                os.replace(tmp, path)
            except OSError as e:
                rec = dict(rec, error=repr(e))
                m = self.measurements
                if m is not None:
                    m.event("lease_write_failed", rank=self.rank,
                            error=repr(e))
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            return rec

    def sampler_extra(self, epoch_of: Optional[Callable[[], int]] = None
                      ) -> Callable[[], dict]:
        """A zero-arg hook for ``MetricsSampler(extra=...)``: every sampler
        tick heartbeats the lease and folds it into the metrics record —
        liveness rides the telemetry cadence instead of a second thread.
        ``epoch_of`` supplies the current membership epoch per tick (e.g.
        ``view.epoch_of``)."""
        def _extra() -> dict:
            ep = epoch_of() if epoch_of is not None else 0
            return {"lease": self.heartbeat(ep)}
        return _extra

    # -------------------------------------------------------------- reading
    def read(self, rank: int) -> Optional[Lease]:
        """The rank's current lease, or None (missing/torn files read as
        absent — a torn lease is indistinguishable from a dead writer
        and ages out the same way)."""
        try:
            with open(self.lease_path(rank)) as f:
                d = json.load(f)
            return Lease(rank=int(d["rank"]), epoch=int(d["epoch"]),
                         t_epoch_s=float(d["t_epoch_s"]), pid=int(d["pid"]),
                         host=str(d.get("host", "")), seq=int(d.get("seq", 0)))
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None

    def snapshot(self) -> Dict[int, Lease]:
        return {r: lease for r in range(self.num_ranks)
                if (lease := self.read(r)) is not None}

    def lapsed(self, now: Optional[float] = None) -> List[int]:
        """Ranks whose lease age exceeds ``lease_s``.  A rank that never
        wrote a lease lapses once the same window has elapsed since this
        board was created (startup grace: a slow-booting peer is not
        declared dead before it had one full window to appear)."""
        now = self.clock() if now is None else now
        out = []
        for r in range(self.num_ranks):
            if r == self.rank:
                continue          # self-liveness is tautological
            lease = self.read(r)
            anchor = self._t0 if lease is None else lease.t_epoch_s
            if now - anchor > self.lease_s:
                out.append(r)
        return out

    def withdraw(self, rank: int) -> None:
        """Delete a rank's lease — the chaos/test hook for simulating an
        instant death without waiting out the lapse window."""
        try:
            os.remove(self.lease_path(rank))
        except OSError:
            pass


class MembershipView:
    """Fenced membership state derived from a :class:`LeaseBoard`.

    ``epoch`` starts at 0 (the boot mesh) and bumps once per
    :meth:`check` batch that declares new losses — ``MEPOCH`` counts the
    bumps, so the counter *is* the epoch.  ``lost`` only grows: a rank
    that re-appears after being declared lost must rejoin at a future
    epoch (join-side elasticity, ROADMAP item 2's other half), never
    silently re-enter the current one — its in-flight state is gone.
    """

    def __init__(self, board: LeaseBoard, measurements=None):
        self.board = board
        self.measurements = measurements
        self.epoch = 0
        self.lost: set = set()

    # epoch accessor shaped for LeaseBoard.sampler_extra(epoch_of=...)
    def epoch_of(self) -> int:
        return self.epoch

    @property
    def survivors(self) -> List[int]:
        return [r for r in range(self.board.num_ranks) if r not in self.lost]

    def _declare(self, ranks: List[int], cause: str) -> List[int]:
        fresh = [r for r in ranks if r not in self.lost]
        if not fresh:
            return []
        self.lost.update(fresh)
        self.epoch += 1
        m = self.measurements
        if m is not None:
            m.incr(MEPOCH)
            m.incr(RANKLOST, len(fresh))
            m.event("rank_lost", ranks=fresh, epoch=self.epoch, cause=cause,
                    survivors=len(self.survivors))
        return fresh

    def check(self, now: Optional[float] = None) -> List[int]:
        """Scan leases; declare newly lapsed ranks lost (one epoch bump
        per batch regardless of how many lapsed together — a host loss
        takes its ranks in one fence, not N).  Returns the newly lost
        ranks.  Cheap enough for phase-boundary polling: one small-file
        read per peer."""
        return self._declare(self.board.lapsed(now), cause="lease_lapse")

    def declare_lost(self, rank: int, cause: str = "declared") -> int:
        """Explicit declaration (watchdog suspicion confirmed, chaos
        injection).  Withdraws the lease too so every survivor's next
        scan converges on the same verdict.  Returns the new epoch."""
        self.board.withdraw(rank)
        self._declare([rank], cause=cause)
        return self.epoch

    # --------------------------------------------------------------- fencing
    def fence(self, stamped_epoch: int) -> None:
        """Reject work stamped with an old epoch (see :class:`StaleEpoch`)."""
        if stamped_epoch != self.epoch:
            raise StaleEpoch(stamped_epoch, self.epoch)

    def require_live(self, rank: int) -> None:
        if rank in self.lost:
            raise RankLost(rank, self.epoch, "rank already declared lost")

    # ------------------------------------------------------- watchdog bridge
    def suspect(self) -> Optional[RankLost]:
        """The watchdog's stall triage: a stalled collective *plus* a
        lapsed lease is a dead peer, not a downed backend.  Runs a lease
        scan; if any rank is (or just became) lost, returns a
        :class:`RankLost` for the watchdog to deliver — recovery owns it
        from there.  Returns None when every peer is live (the stall is
        the backend's fault; the watchdog keeps its
        ``backend_unavailable`` verdict)."""
        self.check()
        if not self.lost:
            return None
        rank = min(self.lost)
        return RankLost(rank, self.epoch, "lease lapsed during stall")
