"""Lease-based rank membership with epoch fencing.

The reference pipeline assumes every MPI rank survives the whole job; so
did this port until now — a rank dying mid-run left its peers blocked in
a collective until the watchdog converted the stall into a
``backend_unavailable`` suicide.  This module turns rank loss into a
*detectable, classified* condition:

  * **Leases** — every rank heartbeats a small epoch-stamped JSON lease
    into a shared run directory (:class:`LeaseBoard`).  Heartbeats ride
    an existing cadence (the MetricsSampler's daemon tick via
    :meth:`LeaseBoard.sampler_extra`, or any caller loop); a write is
    atomic (tmp + ``os.replace``, the checkpoint.py discipline) and
    *never raises* — a full disk must not kill a healthy rank.
  * **Lapse detection** — a rank whose lease is older than ``lease_s``
    (or which never wrote one within the grace window) is *lapsed*.
    Wall-clock (``time.time``) timestamps are used deliberately: they
    are the only clock comparable across processes, and the lease
    window is seconds-coarse, far above credible host skew on one
    machine or a TPU pod's NTP-disciplined hosts.
  * **Epoch fencing** — :class:`MembershipView` turns lapses into a
    declaration: the rank joins the ``lost`` set, ``RANKLOST`` ticks,
    and the **membership epoch** bumps (``MEPOCH``).  Work stamped with
    an older epoch (compiled plans, exchange plans, warm capacity
    entries) is rejected via :meth:`MembershipView.fence` raising
    :class:`StaleEpoch` — stale collectives from the old mesh shape die
    loudly instead of deadlocking against a peer that no longer exists.

  * **Admission** — the growth mirror of loss: a new process writes a
    ``joining`` lease (:meth:`LeaseBoard.heartbeat` with
    ``status="joining"``) and every member's next :meth:`check` batch
    admits it exactly once — one fenced epoch bump per batch
    (``RANKJOIN`` ticks, same discipline as ``rank_lost``), so the next
    epoch's plan re-prices and re-assigns partitions onto the newcomer
    (robustness/recovery.py ``joined_ranks``).  A rank previously
    declared lost re-enters ONLY through this path — at a future epoch,
    never silently into the current one.

Every survivor computes the same view independently from the shared
lease directory — no coordinator, no broadcast (the assignment-map
discipline: deterministic recomputation beats agreement protocols at
this scale).

Lapse policy: heartbeats ride phase boundaries and the MetricsSampler
daemon tick, but a single long device pass (a Pallas sort over a big
shard) can legitimately exceed one lease window on a healthy rank.  A
rank is therefore declared lost only after ``missed_beats`` (default 2)
consecutive windows pass without a beat — one slow kernel is a missed
beat, not a death certificate.

The watchdog integration is duck-typed (observability stays
dependency-free of robustness): :meth:`MembershipView.suspect` returns a
ready-to-deliver :class:`RankLost` when a lapsed lease explains a stall,
else None — the watchdog's trip path consults it before classifying the
stall as ``backend_unavailable``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tpu_radix_join.performance.measurements import (MEPOCH, RANKJOIN,
                                                     RANKLOST)
from tpu_radix_join.robustness.retry import RANK_JOIN, RANK_LOST


class RankLost(ConnectionError):
    """A peer rank's lease lapsed (or its death was injected) mid-run.

    Deliberately NOT blind-retryable (see retry.py's class catalog): the
    remedy is the elastic-recovery path — fence the epoch, re-plan on
    the survivor mesh, resume at partition granularity
    (robustness/recovery.py) — never a same-shape rerun, which would
    block on the same dead collective."""

    failure_class = RANK_LOST

    def __init__(self, rank: int, epoch: int, detail: str = ""):
        super().__init__(
            f"rank {rank} lost at membership epoch {epoch}"
            + (f": {detail}" if detail else ""))
        self.rank = rank
        self.epoch = epoch
        # forensics bundles fold this in next to the error repr
        # (main._emit_failure_bundle), same contract as CoordinatorTimeout
        self.bundle_extra = {"lost_rank": rank, "membership_epoch": epoch}


class RankJoined(RuntimeError):
    """A joining rank was admitted mid-join (the epoch bumped under us).

    NOT a failure — control flow for the elastic wrapper: in-flight work
    is stamped with the pre-admission epoch, so the engine finishes the
    join on the *grown* membership (recovery's re-expansion path with
    ``joined_ranks``) instead of dispatching stale-epoch collectives.
    Raised only when growth handling is enabled (``--elastic-grow``)."""

    failure_class = RANK_JOIN

    def __init__(self, ranks, epoch: int):
        ranks = tuple(int(r) for r in ranks)
        super().__init__(
            f"rank(s) {list(ranks)} admitted at membership epoch {epoch} — "
            f"re-plan on the grown mesh")
        self.ranks = ranks
        self.epoch = epoch
        self.bundle_extra = {"joined_ranks": list(ranks),
                             "membership_epoch": epoch}


class StaleEpoch(RuntimeError):
    """Epoch-fenced rejection: work stamped with an old membership epoch
    reached a collective/dispatch boundary after the mesh shrank.  Shares
    the ``rank_lost`` failure class — the *cause* is the lost rank; the
    fence merely converts what would have been a deadlock into a
    classified exit the recovery path owns."""

    failure_class = RANK_LOST

    def __init__(self, stamped: int, current: int):
        super().__init__(
            f"stale membership epoch: work stamped epoch {stamped} but the "
            f"mesh is at epoch {current} — re-plan on the survivor mesh")
        self.stamped = stamped
        self.current = current


@dataclass(frozen=True)
class Lease:
    """One rank's most recent heartbeat.

    ``status`` is ``"member"`` for a participating rank or ``"joining"``
    for a newcomer awaiting admission; ``partitions_done`` mirrors the
    rank's :class:`~tpu_radix_join.robustness.checkpoint.PartitionManifest`
    progress at beat time (-1 = unknown/no manifest) — the per-rank
    progress clock the straggler detector reads."""

    rank: int
    epoch: int
    t_epoch_s: float
    pid: int
    host: str
    seq: int
    status: str = "member"
    partitions_done: int = -1


class LeaseBoard:
    """Per-rank lease files in a shared run directory.

    File ``lease_r<rank>.json`` holds one :class:`Lease` as JSON; writes
    are atomic (tmp + ``os.replace``) so a reader never observes a torn
    lease, and :meth:`heartbeat` never raises — losing one heartbeat to
    a transient I/O error must not kill a healthy rank (the same
    durability-beats-availability rule as checkpoint saves).
    """

    def __init__(self, run_dir: str, rank: int, num_ranks: int,
                 lease_s: float = 5.0,
                 clock: Callable[[], float] = time.time,
                 measurements=None, missed_beats: int = 2):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be positive, got {lease_s}")
        if missed_beats < 1:
            raise ValueError(f"missed_beats must be >= 1, got {missed_beats}")
        self.run_dir = run_dir
        self.rank = int(rank)
        self.num_ranks = int(num_ranks)
        self.lease_s = float(lease_s)
        self.missed_beats = int(missed_beats)
        self.clock = clock
        self.measurements = measurements
        #: optional zero-arg progress hook (set by the engine once a
        #: PartitionManifest exists): every heartbeat folds its value in
        #: as ``partitions_done`` — liveness and progress ride one beat
        self.progress_of: Optional[Callable[[], int]] = None
        self._seq = 0
        # heartbeat() runs on the metrics-sampler daemon tick (via
        # sampler_extra) AND on the main thread's join loop — unguarded,
        # both racers share one ``<path>.tmp.<pid>`` scratch name, so a
        # torn interleaving can replace a half-written lease
        self._lock = threading.Lock()
        self._t0 = clock()      # grace anchor for never-heartbeated ranks
        os.makedirs(run_dir, exist_ok=True)

    def lease_path(self, rank: int) -> str:
        return os.path.join(self.run_dir, f"lease_r{rank}.json")

    @property
    def lapse_window_s(self) -> float:
        """Seconds of silence before a rank is lapsed: ``missed_beats``
        consecutive lease windows (one slow device pass on a healthy
        rank costs one beat, not a death certificate)."""
        return self.lease_s * self.missed_beats

    # ------------------------------------------------------------ heartbeat
    def heartbeat(self, epoch: int = 0, status: str = "member") -> dict:
        """Write this rank's lease; returns the lease dict (merged into
        sampler ticks by :meth:`sampler_extra`).  Never raises.

        ``status="joining"`` is the admission request: a newcomer beats
        with it until every member's view has admitted the rank."""
        with self._lock:
            self._seq += 1
            done = -1
            if self.progress_of is not None:
                try:
                    done = int(self.progress_of())
                except Exception:
                    done = -1       # progress is advisory, never lethal
            rec = {"rank": self.rank, "epoch": int(epoch),
                   "t_epoch_s": self.clock(), "pid": os.getpid(),
                   "host": socket.gethostname(), "seq": self._seq,
                   "status": str(status), "partitions_done": done}
            path = self.lease_path(self.rank)
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                    f.flush()
                os.replace(tmp, path)
            except OSError as e:
                rec = dict(rec, error=repr(e))
                m = self.measurements
                if m is not None:
                    m.event("lease_write_failed", rank=self.rank,
                            error=repr(e))
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            return rec

    def sampler_extra(self, epoch_of: Optional[Callable[[], int]] = None,
                      status_of: Optional[Callable[[], str]] = None
                      ) -> Callable[[], dict]:
        """A zero-arg hook for ``MetricsSampler(extra=...)``: every sampler
        tick heartbeats the lease and folds it into the metrics record —
        liveness rides the telemetry cadence instead of a second thread
        (and doubles as the secondary beat that keeps a healthy rank
        under the ``missed_beats`` lapse threshold during long device
        passes).  ``epoch_of`` supplies the current membership epoch per
        tick (e.g. ``view.epoch_of``); ``status_of`` the lease status
        (e.g. ``view.my_status`` on a joining process)."""
        def _extra() -> dict:
            ep = epoch_of() if epoch_of is not None else 0
            st = status_of() if status_of is not None else "member"
            return {"lease": self.heartbeat(ep, status=st)}
        return _extra

    # -------------------------------------------------------------- reading
    def read(self, rank: int) -> Optional[Lease]:
        """The rank's current lease, or None (missing/torn files read as
        absent — a torn lease is indistinguishable from a dead writer
        and ages out the same way)."""
        try:
            with open(self.lease_path(rank)) as f:
                d = json.load(f)
            return Lease(rank=int(d["rank"]), epoch=int(d["epoch"]),
                         t_epoch_s=float(d["t_epoch_s"]), pid=int(d["pid"]),
                         host=str(d.get("host", "")), seq=int(d.get("seq", 0)),
                         status=str(d.get("status", "member")),
                         partitions_done=int(d.get("partitions_done", -1)))
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None

    def discover(self) -> List[int]:
        """Every rank with a lease file in the run directory, including
        ranks beyond the boot ``num_ranks`` — how members notice a
        newcomer's ``joining`` lease without being told its rank."""
        ranks = set(range(self.num_ranks))
        try:
            names = os.listdir(self.run_dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith("lease_r") and name.endswith(".json"):
                try:
                    ranks.add(int(name[len("lease_r"):-len(".json")]))
                except ValueError:
                    continue
        return sorted(ranks)

    @staticmethod
    def next_rank(run_dir: str, floor: int = 0) -> int:
        """The first unused rank id in ``run_dir`` at or above ``floor``
        — how a joining process picks its rank without a coordinator
        (deterministic from shared state, like everything else here)."""
        taken = set()
        try:
            names = os.listdir(run_dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith("lease_r") and name.endswith(".json"):
                try:
                    taken.add(int(name[len("lease_r"):-len(".json")]))
                except ValueError:
                    continue
        r = int(floor)
        while r in taken:
            r += 1
        return r

    def snapshot(self, ranks=None) -> Dict[int, Lease]:
        """Current leases for ``ranks`` (default: every discovered rank,
        so joiners show up)."""
        ranks = self.discover() if ranks is None else ranks
        return {r: lease for r in ranks
                if (lease := self.read(r)) is not None}

    def lapsed(self, now: Optional[float] = None, ranks=None) -> List[int]:
        """Ranks whose lease age exceeds :attr:`lapse_window_s` —
        ``missed_beats`` consecutive windows without a beat, so one long
        device pass is a missed beat, not a lapse.  A rank that never
        wrote a lease lapses once the same window has elapsed since this
        board was created (startup grace: a slow-booting peer is not
        declared dead before it had a full window to appear).  ``ranks``
        overrides the scanned domain (the membership view passes its
        possibly-grown member set)."""
        now = self.clock() if now is None else now
        out = []
        for r in (range(self.num_ranks) if ranks is None else sorted(ranks)):
            if r == self.rank:
                continue          # self-liveness is tautological
            lease = self.read(r)
            anchor = self._t0 if lease is None else lease.t_epoch_s
            if now - anchor > self.lapse_window_s:
                out.append(r)
        return out

    def withdraw(self, rank: int) -> None:
        """Delete a rank's lease — the chaos/test hook for simulating an
        instant death without waiting out the lapse window."""
        try:
            os.remove(self.lease_path(rank))
        except OSError:
            pass


class MembershipView:
    """Fenced membership state derived from a :class:`LeaseBoard`.

    ``epoch`` starts at 0 (the boot mesh) and bumps once per
    :meth:`check` batch that declares new losses OR admits new joiners —
    ``MEPOCH`` counts the bumps, so the counter *is* the epoch.
    Membership changes only through fenced batches: a rank that
    re-appears after being declared lost must rejoin through the
    ``joining``-lease admission path at a future epoch, never silently
    re-enter the current one — its in-flight state is gone.
    """

    def __init__(self, board: LeaseBoard, measurements=None):
        self.board = board
        self.measurements = measurements
        self.epoch = 0
        self.lost: set = set()
        #: ranks admitted beyond (or back into) the boot mesh, in
        #: admission order — recovery's ``joined_ranks`` input
        self.joined: set = set()

    # epoch accessor shaped for LeaseBoard.sampler_extra(epoch_of=...)
    def epoch_of(self) -> int:
        return self.epoch

    @property
    def members(self) -> set:
        """The membership domain: boot ranks plus every admitted joiner
        (``lost`` ranks stay in the domain — they are members that died,
        which is what the lapse scan must keep asserting)."""
        return set(range(self.board.num_ranks)) | self.joined

    @property
    def survivors(self) -> List[int]:
        return sorted(r for r in self.members if r not in self.lost)

    def is_live(self, rank: int) -> bool:
        return rank in self.members and rank not in self.lost

    def my_status(self) -> str:
        """This process's lease status: ``"joining"`` until its own view
        admits it (shaped for ``sampler_extra(status_of=...)``)."""
        return "member" if self.is_live(self.board.rank) else "joining"

    def _declare(self, ranks: List[int], cause: str) -> List[int]:
        fresh = [r for r in ranks if r not in self.lost]
        if not fresh:
            return []
        self.lost.update(fresh)
        self.epoch += 1
        m = self.measurements
        if m is not None:
            # context first: the MEPOCH/RANKLOST records below — and every
            # later HEDGED/HEDGEWIN/RANKJOIN tick — must carry the epoch
            # they happened under, not leave forensics to infer it from
            # neighboring records
            m.flightrec.set_context(membership_epoch=self.epoch)
            m.incr(MEPOCH)
            m.incr(RANKLOST, len(fresh))
            m.event("rank_lost", ranks=fresh, epoch=self.epoch, cause=cause,
                    survivors=len(self.survivors))
        return fresh

    def _admit(self, ranks: List[int], cause: str) -> List[int]:
        """The growth mirror of :meth:`_declare`: admit a batch of
        joining ranks with ONE epoch bump (a host bringing up several
        processes joins in one fence, not N).  A previously-lost rank
        re-enters here — at the new epoch, as promised."""
        fresh = [r for r in ranks if not self.is_live(r)]
        if not fresh:
            return []
        for r in fresh:
            self.lost.discard(r)
            self.joined.add(r)
        self.epoch += 1
        m = self.measurements
        if m is not None:
            m.flightrec.set_context(membership_epoch=self.epoch)
            m.incr(MEPOCH)
            m.incr(RANKJOIN, len(fresh))
            m.event("rank_join", ranks=fresh, epoch=self.epoch, cause=cause,
                    members=len(self.survivors))
        return fresh

    def _scan_joiners(self, now: Optional[float] = None) -> List[int]:
        """Discovered ranks with a *fresh* ``joining`` lease that are not
        live members.  Staleness matters: a joiner that died before
        admission must age out of its request, not be admitted into a
        mesh it can no longer serve."""
        now = self.board.clock() if now is None else now
        out = []
        for r in self.board.discover():
            if self.is_live(r):
                continue
            lease = self.board.read(r)
            if (lease is not None and lease.status == "joining"
                    and now - lease.t_epoch_s <= self.board.lapse_window_s):
                out.append(r)
        return out

    def check(self, now: Optional[float] = None) -> List[int]:
        """Scan leases; admit fresh joiners, then declare newly lapsed
        ranks lost (one epoch bump per admission batch and one per loss
        batch regardless of how many ranks moved together — a host loss
        takes its ranks in one fence, not N).  Returns the newly lost
        ranks (admissions are visible via :attr:`joined` and the epoch).
        Cheap enough for phase-boundary polling: one small-file read per
        peer."""
        self._admit(self._scan_joiners(now), cause="joining_lease")
        return self._declare(self.board.lapsed(now, ranks=self.members),
                             cause="lease_lapse")

    def sync_epoch(self) -> int:
        """Adopt the highest epoch any live lease carries — how a joiner
        (booted at epoch 0) catches up with a mesh whose incumbents
        already fenced through losses/admissions it never observed.
        Never rewinds."""
        for lease in self.board.snapshot().values():
            if lease.epoch > self.epoch:
                self.epoch = lease.epoch
        return self.epoch

    def declare_lost(self, rank: int, cause: str = "declared") -> int:
        """Explicit declaration (watchdog suspicion confirmed, chaos
        injection).  Withdraws the lease too so every survivor's next
        scan converges on the same verdict.  Returns the new epoch."""
        self.board.withdraw(rank)
        self._declare([rank], cause=cause)
        return self.epoch

    # --------------------------------------------------------------- fencing
    def fence(self, stamped_epoch: int) -> None:
        """Reject work stamped with an old epoch (see :class:`StaleEpoch`)."""
        if stamped_epoch != self.epoch:
            raise StaleEpoch(stamped_epoch, self.epoch)

    def require_live(self, rank: int) -> None:
        if rank in self.lost:
            raise RankLost(rank, self.epoch, "rank already declared lost")

    # ------------------------------------------------------- watchdog bridge
    def suspect(self) -> Optional[RankLost]:
        """The watchdog's stall triage: a stalled collective *plus* a
        lapsed lease is a dead peer, not a downed backend.  Runs a lease
        scan; if any rank is (or just became) lost, returns a
        :class:`RankLost` for the watchdog to deliver — recovery owns it
        from there.  Returns None when every peer is live (the stall is
        the backend's fault; the watchdog keeps its
        ``backend_unavailable`` verdict)."""
        self.check()
        if not self.lost:
            return None
        rank = min(self.lost)
        return RankLost(rank, self.epoch, "lease lapsed during stall")
