"""Fault injection, retry policies, and checkpoint/resume.

The resilience layer the reference never had: its only failure contract is a
hard ``JOIN_ASSERT`` after the RMA window exchange (Window.cpp:180-191,
SURVEY.md §4.3).  This package gives every failure path a name, a policy,
and a test:

  * :mod:`~tpu_radix_join.robustness.faults` — seeded, deterministic
    fault-injection registry consulted by the engine at named sites, so
    every failure path is exercisable on CPU under tier-1.
  * :mod:`~tpu_radix_join.robustness.retry` — ``RetryPolicy`` (max attempts,
    exponential backoff, deterministic jitter) + the retryable-vs-fatal
    failure-class taxonomy derived from ``JoinResult.diagnostics``.
  * :mod:`~tpu_radix_join.robustness.checkpoint` — atomic slab-boundary
    checkpoint/resume for out-of-core grid joins.
  * :mod:`~tpu_radix_join.robustness.degrade` — graceful degradation
    (accelerator-init failure -> CPU engine).  Imported lazily by callers,
    not here: it pulls in the full engine stack.
"""

from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.checkpoint import (CheckpointManager,
                                                  CheckpointMismatch)
from tpu_radix_join.robustness.retry import (RetriesExhausted, RetryPolicy,
                                             classify_diagnostics, execute)

__all__ = [
    "faults",
    "CheckpointManager",
    "CheckpointMismatch",
    "RetryPolicy",
    "RetriesExhausted",
    "classify_diagnostics",
    "execute",
]
