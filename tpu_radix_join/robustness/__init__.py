"""Fault injection, retry policies, and checkpoint/resume.

The resilience layer the reference never had: its only failure contract is a
hard ``JOIN_ASSERT`` after the RMA window exchange (Window.cpp:180-191,
SURVEY.md §4.3).  This package gives every failure path a name, a policy,
and a test:

  * :mod:`~tpu_radix_join.robustness.faults` — seeded, deterministic
    fault-injection registry consulted by the engine at named sites, so
    every failure path is exercisable on CPU under tier-1.
  * :mod:`~tpu_radix_join.robustness.retry` — ``RetryPolicy`` (max attempts,
    exponential backoff, deterministic jitter) + the retryable-vs-fatal
    failure-class taxonomy derived from ``JoinResult.diagnostics``.
  * :mod:`~tpu_radix_join.robustness.checkpoint` — atomic slab-boundary
    checkpoint/resume for out-of-core grid joins.
  * :mod:`~tpu_radix_join.robustness.verify` — end-to-end data-integrity
    verification: order-independent per-partition checksums (count / key
    sum / key xor-fold) compared across pipeline stages, the
    ``data_corruption`` failure class, and the fingerprint primitives the
    engine's ``--verify`` modes build on.
  * :mod:`~tpu_radix_join.robustness.chaos` — seeded chaos/soak harness:
    randomized fault schedules over the :data:`faults.SITES` vocabulary,
    a pass-or-classified invariant over N runs, and delta-debugging
    shrink of violating schedules to minimal replayable repros.  Imported
    lazily by callers, not here: it pulls in the full engine stack.
  * :mod:`~tpu_radix_join.robustness.degrade` — graceful degradation
    (accelerator-init failure -> CPU engine).  Imported lazily by callers,
    not here: it pulls in the full engine stack.
"""

from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.checkpoint import (CheckpointManager,
                                                  CheckpointMismatch)
from tpu_radix_join.robustness.retry import (DATA_CORRUPTION,
                                             RetriesExhausted, RetryPolicy,
                                             classify_diagnostics, execute)
from tpu_radix_join.robustness.verify import DataCorruption

__all__ = [
    "faults",
    "CheckpointManager",
    "CheckpointMismatch",
    "DataCorruption",
    "DATA_CORRUPTION",
    "RetryPolicy",
    "RetriesExhausted",
    "classify_diagnostics",
    "execute",
]
