"""End-to-end data-integrity verification: per-partition checksums.

The pipeline moves every tuple through a redistribution step (histogram ->
window allocation -> all_to_all exchange -> local partition/sort) whose
correctness was previously observable only through the final match count; a
bit-flip in flight — the TPU analogue of a corrupted RMA Put — would either
surface as an inscrutable wrong answer or vanish entirely.  This module
gives every network partition an order-independent fingerprint:

  * **count**  — tuples per partition (the conservation invariant the
    engine already tracks in aggregate, here per partition);
  * **sum**    — wraparound uint32 sum of the key lane (order-independent
    mod 2**32; catches value changes);
  * **xor**    — xor-fold of the key lane (ops/sorting.segmented_xor_fold;
    catches paired/bit-level changes that cancel in addition).

Wide (64-bit) keys add sum/xor rows for the hi lane.  The fingerprints are
computed over the pristine inputs *before* the exchange and re-derived from
the pipeline *after* the exchange (and after the local radix pass on the
bucket path); any partition whose rows disagree is **damaged**.  A
mismatch raises the ``data_corruption`` failure class (robustness/retry.py)
— or, under ``verify="repair"``, triggers partition-granular recompute in
the engine (operators/hash_join.py).

Everything here is traced-code-safe (pure jnp/lax) so the post-exchange
checksums ride inside the engine's shard_map programs as extra outputs;
the cross-device combine uses psum for count/sum and per-bit parity psum
for xor (global xor == per-bit popcount parity — no scatter-xor or
all_gather+reduce needed, and it composes with hierarchical meshes).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.ops.sorting import segmented_xor_fold
from tpu_radix_join.robustness.retry import DATA_CORRUPTION


class DataCorruption(ValueError):
    """A per-partition integrity checksum disagreed across pipeline stages
    (or a key lane reached the reserved sentinel range — the streamed-lane
    corruption signature, ops/chunked.py).  Carries the machine-readable
    failure class, like CheckpointMismatch does."""

    failure_class = DATA_CORRUPTION

    def __init__(self, message: str, partitions=()):
        super().__init__(message)
        self.partitions = tuple(int(p) for p in partitions)


def checksum_rows(wide: bool) -> int:
    """Rows per relation fingerprint: count + (sum, xor) per key lane."""
    return 5 if wide else 3


def device_partition_checksums(
    key: jnp.ndarray,
    pid: jnp.ndarray,
    num_partitions: int,
    valid: Optional[jnp.ndarray] = None,
    key_hi: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """This device's per-partition fingerprint halves.

    Returns ``(adds, xors)``: ``adds`` is ``[1 + lanes, P]`` uint32 (count
    row then per-lane wraparound sums — psum-combinable), ``xors`` is
    ``[lanes, P]`` uint32 (per-lane xor-folds — parity-combinable).
    Invalid lanes are routed to a discard bucket, so capacity-padded
    receive buffers fingerprint only their real tuples.
    """
    p = pid.astype(jnp.uint32)
    if valid is not None:
        p = jnp.where(valid, p, jnp.uint32(num_partitions))
    ones = jnp.ones_like(p)
    lanes = [key] if key_hi is None else [key, key_hi]

    def scatter_add(contrib):
        return jnp.zeros((num_partitions + 1,), jnp.uint32).at[p].add(
            contrib, mode="drop")[:num_partitions]

    adds = jnp.stack([scatter_add(ones)]
                     + [scatter_add(lane.astype(jnp.uint32))
                        for lane in lanes])
    xors = jnp.stack([segmented_xor_fold(p, lane, num_partitions)
                      for lane in lanes])
    return adds, xors


def global_partition_checksums(
    key: jnp.ndarray,
    pid: jnp.ndarray,
    num_partitions: int,
    axis,
    valid: Optional[jnp.ndarray] = None,
    key_hi: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mesh-global ``[rows, P]`` fingerprint (traced inside shard_map).

    count/sum rows combine by psum; xor rows by per-bit parity psum
    (``XOR over devices == popcount mod 2`` per bit — psum keeps this
    compatible with tuple axis names on hierarchical meshes, where
    all_gather+reduce would not compose as directly).
    """
    adds, xors = device_partition_checksums(key, pid, num_partitions,
                                            valid=valid, key_hi=key_hi)
    g_adds = jax.lax.psum(adds, axis)
    bits = jnp.arange(32, dtype=jnp.uint32)
    parity = jax.lax.psum((xors[..., None] >> bits) & jnp.uint32(1),
                          axis) & jnp.uint32(1)
    g_xors = jnp.sum(parity << bits, axis=-1).astype(jnp.uint32)
    return jnp.concatenate([g_adds, g_xors], axis=0)


def damaged_partitions(pre: np.ndarray, post: np.ndarray) -> np.ndarray:
    """Host-side compare of two ``[rows, P]`` fingerprints: the sorted
    partition ids whose rows disagree (empty == intact)."""
    pre = np.asarray(pre)
    post = np.asarray(post)
    if pre.shape != post.shape:
        raise ValueError(
            f"checksum shape mismatch: {pre.shape} vs {post.shape}")
    return np.nonzero((pre != post).any(axis=0))[0]


def cross_check_counts(partition_counts: np.ndarray, matches: int,
                       r_counts: np.ndarray,
                       s_counts: np.ndarray) -> Optional[str]:
    """Join-level invariants over the reported per-partition counts:
    their uint64 sum must equal the reported total, and no partition may
    report more matches than ``|R_p| * |S_p|`` (its cross-product bound).

    ``partition_counts`` is the host counts array reshaped ``[devices, P]``
    (per-device per-partition); ``r_counts``/``s_counts`` are the count
    rows of the global pre-exchange fingerprints.  Returns a human-readable
    violation description, or None when the invariants hold.
    """
    counts = np.asarray(partition_counts, dtype=np.uint64)
    total = int(counts.sum())
    if total != int(matches):
        return (f"sum of per-partition matches {total} != reported total "
                f"{int(matches)}")
    per_part = counts.sum(axis=0)
    bound = (np.asarray(r_counts, dtype=np.uint64)
             * np.asarray(s_counts, dtype=np.uint64))
    over = np.nonzero(per_part > bound)[0]
    if over.size:
        p = int(over[0])
        return (f"partition {p} reports {int(per_part[p])} matches, above "
                f"its |R_p|*|S_p| bound {int(bound[p])}")
    return None
