"""Seeded, deterministic fault injection.

The engine consults this registry at *named sites* (the constants below);
an armed :class:`FaultInjector` decides — deterministically, from its seed —
whether the site fires on each hit.  A fired site either raises (simulated
kill, connect timeout, I/O error) or tells the caller to corrupt its own
state (flag mutation, sentinel-damaged lane), so every failure path in
SURVEY.md §4.3's taxonomy is exercisable on CPU under tier-1 without
touching real hardware.

Usage::

    with FaultInjector(seed=7).arm(faults.GRID_KILL, at=3):
        chunked_join_grid(...)        # third pair probe raises InjectedKill

Injectors nest via a stack; only the innermost (top) injector is consulted,
so a test's injector shadows any ambient one.  Sites are plain strings —
``arm`` still accepts unknown names (forward compatibility for downstream
experiments) but warns with a difflib near-miss suggestion, so a typo'd
chaos schedule doesn't silently no-op; the canonical vocabulary is
:data:`SITES`.

Determinism contract: per-site decisions come from
``random.Random(f"{seed}:{site}")``, so the same seed + same hit sequence
replays the same failures (tested in tests/test_robustness.py).
"""

from __future__ import annotations

import difflib
import random
import warnings
from typing import Dict, List, Optional, Tuple

from tpu_radix_join.performance.measurements import FINJECT
from tpu_radix_join.robustness.retry import BACKEND_UNAVAILABLE

# ---------------------------------------------------------------- site names
SHUFFLE_OVERFLOW = "engine.shuffle_overflow"   # shuffle-block capacity loss
DEVICE_INIT = "engine.device_init"             # accelerator unavailable
COORD_CONNECT = "multihost.coordinator_connect"  # distributed-init timeout
GRID_KILL = "grid.mid_chunk_kill"              # hard kill between slabs
GRID_TRANSIENT = "grid.transient"              # retryable per-pair hiccup
STREAM_CORRUPT = "stream.corrupt_lane"         # sentinel-damaged key lane
EXCHANGE_CORRUPT = "exchange.corrupt_lane"     # bit-flipped key post-exchange
CKPT_SAVE = "checkpoint.save"                  # checkpoint write I/O error
CKPT_LOAD = "checkpoint.load"                  # checkpoint read I/O error
BACKEND_DISPATCH = "backend.dispatch"          # per-query tunnel outage
                                               # (service/session.py probe)
BACKEND_STALL = "backend.stall"                # simulated hung collective:
                                               # the engine spins (checking
                                               # its cancel hook) instead of
                                               # raising — the watchdog's
                                               # downed-tunnel failure mode
                                               # (operators/hash_join.py)
RANK_DEATH = "membership.rank_death"           # peer rank dies mid-run: its
                                               # lease lapses and the local
                                               # membership view must fence
                                               # the epoch + recover instead
                                               # of hanging (robustness/
                                               # membership.py + recovery.py)
RANK_JOIN = "membership.rank_join"             # a new peer writes a `joining`
                                               # lease mid-run: the view admits
                                               # it with a fenced epoch bump
                                               # and the next plan re-expands
                                               # onto the grown membership
                                               # (membership.py + recovery.py)
COMPUTE_STRAGGLE = "compute.straggle"          # a live rank slows down by a
                                               # seeded factor: alive-but-slow
                                               # is NOT rank_death — the
                                               # straggler detector must hedge
                                               # its unfinished partitions,
                                               # never declare it dead
                                               # (robustness/straggler.py)
FLEET_WORKER_KILL = "fleet.worker_kill"        # SIGKILL a fleet worker right
                                               # after its query hit the pipe:
                                               # the supervisor must journal-
                                               # replay the query on a healthy
                                               # worker, exactly one outcome
                                               # (service/fleet.py dispatch)
CACHE_POISON = "serve.cache_poison"            # corrupt a stored result-cache
                                               # entry in place: the digest/
                                               # epoch re-check on read must
                                               # drop it (count a miss, re-
                                               # execute) — a stale or damaged
                                               # entry is NEVER served
                                               # (service/resultcache.py)

SITES = (SHUFFLE_OVERFLOW, DEVICE_INIT, COORD_CONNECT, GRID_KILL,
         GRID_TRANSIENT, STREAM_CORRUPT, EXCHANGE_CORRUPT, CKPT_SAVE,
         CKPT_LOAD, BACKEND_DISPATCH, BACKEND_STALL, RANK_DEATH,
         RANK_JOIN, COMPUTE_STRAGGLE, FLEET_WORKER_KILL, CACHE_POISON)


class InjectedFault(RuntimeError):
    """Raised by :meth:`FaultInjector.check` when a site fires."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


class InjectedKill(InjectedFault):
    """Simulated hard kill (mid-chunk death): never retried in-process."""


class TransientFault(InjectedFault):
    """Simulated transient error (tunnel hiccup): safe to retry.  Carries
    the transient infrastructure class so the shared retryability
    predicate (retry.is_retryable_class) and the service's circuit
    breaker classify it without type-sniffing."""

    failure_class = BACKEND_UNAVAILABLE


class _Arm:
    def __init__(self, site: str, seed: int, at, p, times, exc):
        self.site = site
        if at is not None and not isinstance(at, (tuple, list, set, frozenset)):
            at = (at,)
        self.at = frozenset(int(a) for a in at) if at is not None else None
        self.p = p
        self.times = times if times is not None else (
            len(self.at) if self.at is not None else None)
        self.exc = exc
        self.hits = 0
        self.fired = 0
        self._rng = random.Random(f"{seed}:{site}")

    def decide(self) -> bool:
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.at is not None:
            fire = self.hits in self.at
        elif self.p is not None:
            fire = self._rng.random() < self.p
        else:
            fire = True
        if fire:
            self.fired += 1
        return fire


class FaultInjector:
    """Context-manager fault registry (see module docstring).

    ``measurements`` (optional) receives an ``FINJECT`` increment and a
    ``fault`` trace event for every fire; sites consulted through the
    module-level helpers may also pass their own registry.
    """

    def __init__(self, seed: int = 0, measurements=None):
        self.seed = seed
        self.measurements = measurements
        self._arms: Dict[str, _Arm] = {}
        #: every (site, hit_index) that fired, in order — the replay record
        self.history: List[Tuple[str, int]] = []

    def arm(self, site: str, *, at=None, p: Optional[float] = None,
            times: Optional[int] = None, exc=None) -> "FaultInjector":
        """Arm ``site``; returns self for chaining.

        ``at``: 1-based hit index (or iterable of them) at which to fire.
        ``p``: per-hit fire probability (seeded per site).  Neither ->
        fire on every hit.  ``times``: cap on total fires (defaults to
        ``len(at)`` when ``at`` is given, else unlimited).  ``exc``:
        exception class/factory ``check`` raises (default
        :class:`InjectedFault`; must accept ``(site, hit)`` or no args).
        """
        if at is None and p is None and times is None:
            times = None   # fire every hit
        if site not in SITES:
            near = difflib.get_close_matches(site, SITES, n=1, cutoff=0.6)
            hint = f"; did you mean {near[0]!r}?" if near else ""
            warnings.warn(
                f"arming unknown fault site {site!r} — no engine code "
                f"consults it, so this arm will never fire{hint}",
                RuntimeWarning, stacklevel=2)
        self._arms[site] = _Arm(site, self.seed, at, p, times, exc)
        return self

    # -------------------------------------------------------------- queries
    def fires(self, site: str, measurements=None) -> bool:
        arm = self._arms.get(site)
        if arm is None:
            return False
        if not arm.decide():
            return False
        self.history.append((site, arm.hits))
        for m in (self.measurements, measurements):
            if m is not None:
                m.incr(FINJECT)
                m.event("fault", site=site, hit=arm.hits)
        return True

    def check(self, site: str, measurements=None) -> None:
        """Raise the armed exception if ``site`` fires on this hit."""
        if not self.fires(site, measurements):
            return
        arm = self._arms[site]
        exc = arm.exc or InjectedFault
        if isinstance(exc, type) and issubclass(exc, InjectedFault):
            raise exc(site, arm.hits)
        raise exc(f"injected fault at {site!r} (hit {arm.hits})")

    def hits(self, site: str) -> int:
        arm = self._arms.get(site)
        return arm.hits if arm else 0

    def fired(self, site: str) -> int:
        arm = self._arms.get(site)
        return arm.fired if arm else 0

    def site_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-armed-site ``{"hits": n, "fired": n}`` — the accounting that
        lands in ``JoinResult.diagnostics["fault_sites"]`` and the
        ``print_results`` FaultSites aggregate."""
        return {site: {"hits": arm.hits, "fired": arm.fired}
                for site, arm in self._arms.items()}

    # ------------------------------------------------------------- stacking
    def __enter__(self) -> "FaultInjector":
        _STACK.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _STACK.remove(self)


_STACK: List[FaultInjector] = []


def active() -> Optional[FaultInjector]:
    """The innermost active injector, or None (production: always None)."""
    return _STACK[-1] if _STACK else None


def fires(site: str, measurements=None) -> bool:
    """Module-level probe: False when no injector is active (zero-cost in
    production beyond a list check)."""
    inj = active()
    return inj.fires(site, measurements) if inj is not None else False


def check(site: str, measurements=None) -> None:
    """Module-level raise-if-armed probe (no-op without an injector)."""
    inj = active()
    if inj is not None:
        inj.check(site, measurements)
