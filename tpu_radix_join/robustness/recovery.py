"""Elastic recovery: re-plan on shrink + partition-level resume.

The companion to robustness/membership.py — once a rank is declared lost
(:class:`~tpu_radix_join.robustness.membership.RankLost`), this module
turns the aborted join into a bounded recompute instead of a restart:

  1. **Resume** — read the per-partition completion manifest
     (checkpoint.PartitionManifest): every partition some rank realized
     before the death is *done*, its count is trusted (every manifest
     line is written post-realization, so trusting it can never
     overclaim).
  2. **Re-plan on shrink OR growth** — the not-done partitions are
     re-assigned across the survivor set with the same deterministic
     machinery the boot mesh used (``histograms/assignment_map``):
     load-aware LPT over measured per-partition weights when histograms
     are available, round-robin otherwise.  ``joined_ranks`` (admitted
     via the membership view's ``joining``-lease protocol) enlarge the
     survivor set, so an admission re-expands the map onto the newcomer
     exactly as a loss shrinks it.  Every survivor computes the
     identical map from the shared lease/manifest state — no
     coordinator.  The planner re-prices strategies for the changed mesh
     (`plan_join` on a ``num_nodes=len(survivors)`` workload) so the
     post-recovery steady state doesn't run the old mesh's plan.
  3. **Recompute out-of-band** — each unfinished partition re-joins as
     its own masked ``chunked_join_grid`` (``(key & (P-1)) == p``), the
     exact machinery ``verify="repair"`` already trusts, over inputs
     regenerated host-side from the deterministic seeded Relation specs.
     Nothing touches the (possibly wedged) distributed arrays: a
     survivor must never issue a collective against a mesh containing a
     dead rank.

Counters: ``RECOVERN`` per partition recomputed (strictly below the
partition count whenever the manifest resumed anything — the
acceptance-bar signal that resume was partition-granular, not a veiled
restart), ``RECOVERMS`` for the detect→re-plan→recompute→splice wall.
Every recovered result's diagnostics carry the full recovery record
(lost ranks, epoch, resumed/recomputed partitions, reassignment,
re-priced plan), which the post-mortem bundle and
``tools_postmortem.py --merge`` render as the recovery timeline.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.histograms.assignment_map import (load_aware_assignment,
                                                      round_robin_assignment)
from tpu_radix_join.performance.measurements import RECOVERMS, RECOVERN
from tpu_radix_join.robustness.membership import RankLost


@dataclasses.dataclass(frozen=True)
class RecoveryPlan:
    """The survivor-side decision record (identical on every survivor)."""

    epoch: int                      # membership epoch the recovery fences to
    lost_ranks: Tuple[int, ...]
    survivors: Tuple[int, ...]
    num_partitions: int
    #: partitions whose counts resume from the manifest (trusted, done)
    resumed: Dict[int, int]
    #: partitions to recompute, in ascending order
    recompute: Tuple[int, ...]
    #: recompute partition -> survivor rank that owns the recompute
    reassignment: Dict[int, int]
    #: re-priced strategy for the shrunken mesh (advisory; "" = no profile)
    replan_strategy: str = ""
    replan_predicted_ms: float = 0.0

    def to_diag(self) -> dict:
        return {
            "recovered": True,
            "membership_epoch": self.epoch,
            "lost_ranks": list(self.lost_ranks),
            "survivors": list(self.survivors),
            "resumed_partitions": sorted(self.resumed),
            "recovered_partitions": list(self.recompute),
            "recovery_assignment": {str(p): r
                                    for p, r in self.reassignment.items()},
            "replan_strategy": self.replan_strategy,
            "replan_predicted_ms": round(self.replan_predicted_ms, 3),
        }


def plan_recovery(*, num_nodes: int, num_partitions: int,
                  lost_ranks, epoch: int, manifest=None,
                  weights: Optional[np.ndarray] = None,
                  profile=None, workload=None,
                  joined_ranks=()) -> RecoveryPlan:
    """Build the survivor-side :class:`RecoveryPlan`.

    ``manifest`` (checkpoint.PartitionManifest) supplies resumable
    counts; ``weights`` (per-partition R+S tuple counts, host array of
    length ``num_partitions``) switches the reassignment from
    round-robin to load-aware LPT; ``profile``/``workload``
    (planner.profile.DeviceProfile, planner.cost_model.Workload) trigger
    the re-pricing for the changed mesh.

    ``joined_ranks`` is the growth half: ranks the membership view
    admitted beyond (or back into) the boot mesh.  The survivor set —
    and with it the deterministic reassignment and the planner's
    re-priced workload — expands over the enlarged membership, so the
    next epoch's plan prices and assigns partitions onto the newcomer
    (safe because recovery inputs are regenerated from the deterministic
    seeded Relation specs: a newcomer computes the same
    :func:`host_keys` every incumbent does, no foreign-mesh arrays are
    touched)."""
    lost = tuple(sorted(set(int(r) for r in lost_ranks)))
    members = set(range(num_nodes)) | {int(r) for r in joined_ranks}
    survivors = tuple(sorted(members - set(lost)))
    if not survivors:
        raise RankLost(lost[0] if lost else 0, epoch,
                       "no survivors to recover onto")
    resumed: Dict[int, int] = {}
    if manifest is not None:
        for p, rec in manifest.completed().items():
            if 0 <= p < num_partitions:
                resumed[p] = rec["count"]
    recompute = tuple(p for p in range(num_partitions) if p not in resumed)
    # deterministic reassignment over the SURVIVOR count, then mapped back
    # to survivor rank ids — each survivor recomputes the same map, the
    # assignment_map no-broadcast discipline
    if weights is not None and len(recompute) > 0:
        w = np.zeros(num_partitions, np.float32)
        w[list(recompute)] = np.asarray(weights, np.float32)[list(recompute)]
        amap = np.asarray(load_aware_assignment(
            jnp.asarray(w), jnp.zeros_like(jnp.asarray(w)), len(survivors)))
    else:
        amap = np.asarray(round_robin_assignment(num_partitions,
                                                 max(1, len(survivors))))
    reassignment = {int(p): int(survivors[int(amap[p])]) for p in recompute}
    strategy, predicted_ms = "", 0.0
    if profile is not None and workload is not None:
        try:
            from tpu_radix_join.planner.plan import plan_join
            shrunk = dataclasses.replace(workload,
                                         num_nodes=len(survivors))
            plan, _ = plan_join(profile, shrunk)
            strategy, predicted_ms = plan.strategy, plan.predicted_ms
        except Exception:
            pass    # re-pricing is advisory; recovery must not die on it
    return RecoveryPlan(epoch=epoch, lost_ranks=lost, survivors=survivors,
                        num_partitions=num_partitions, resumed=resumed,
                        recompute=recompute, reassignment=reassignment,
                        replan_strategy=strategy,
                        replan_predicted_ms=predicted_ms)


def host_keys(rel) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Regenerate a Relation's global key lanes host-side.

    Recovery's input path: the seeded generators are deterministic, so a
    survivor reconstructs the *global* relation (including the dead
    rank's shards) without touching a single distributed array — the one
    property that makes host-side recovery possible at all."""
    shards = [rel.shard_np(i) for i in range(rel.num_nodes)]
    keys = np.concatenate([sh[0] for sh in shards])
    hi = (np.concatenate([sh[1] for sh in shards])
          if rel.key_bits == 64 else None)
    return keys, hi


def partition_weights(r_keys: np.ndarray, s_keys: np.ndarray,
                      num_partitions: int) -> np.ndarray:
    """Per-partition R+S tuple counts (the LPT weight model) from host
    key lanes — one bincount pass each."""
    mask = np.uint64(num_partitions - 1)
    rw = np.bincount((r_keys.astype(np.uint64) & mask).astype(np.int64),
                     minlength=num_partitions)
    sw = np.bincount((s_keys.astype(np.uint64) & mask).astype(np.int64),
                     minlength=num_partitions)
    return (rw + sw).astype(np.float32)


def execute_recovery(plan: RecoveryPlan,
                     r_keys: np.ndarray, s_keys: np.ndarray,
                     r_hi: Optional[np.ndarray] = None,
                     s_hi: Optional[np.ndarray] = None,
                     *, only_rank=None,
                     slab: int = 1 << 20, pipeline: str = "off",
                     measurements=None, manifest=None,
                     clock=time.monotonic) -> Tuple[int, Dict[int, int]]:
    """Recompute the plan's unfinished partitions; returns
    ``(matches, counts)`` where ``counts`` maps every partition this call
    accounted for (resumed + recomputed) to its realized count.

    ``only_rank`` (an int or an iterable of ints — a multi-node process
    owns several node ranks) restricts the recompute to partitions the
    reassignment gave those survivors (the multi-survivor path: each
    appends its realized partitions to the shared ``manifest`` and the
    totals merge through it); None recomputes everything (single
    survivor, or the in-process simulation).  Each partition is one masked
    ``chunked_join_grid`` — the ``verify="repair"`` machinery — under a
    ``recover_partition`` span, and is marked done in the manifest only
    AFTER its count is realized (kill-never-overclaims carries over).
    """
    from tpu_radix_join.ops.chunked import chunked_join_grid
    m = measurements
    t0 = clock()
    counts: Dict[int, int] = dict(plan.resumed)
    mask = np.uint64(plan.num_partitions - 1)
    mine = (None if only_rank is None
            else {int(only_rank)} if isinstance(only_rank, int)
            else {int(r) for r in only_rank})
    todo = [p for p in plan.recompute
            if mine is None or plan.reassignment[p] in mine]
    recovered = 0
    for p in todo:
        rsel = (r_keys.astype(np.uint64) & mask) == p
        ssel = (s_keys.astype(np.uint64) & mask) == p
        cnt = 0
        if rsel.any() and ssel.any():
            span = (m.span("recover_partition", partition=int(p),
                           owner=plan.reassignment[p])
                    if m is not None else _null())
            with span:
                cnt = chunked_join_grid(
                    [TupleBatch(
                        key=jnp.asarray(r_keys[rsel]),
                        rid=jnp.zeros(int(rsel.sum()), jnp.uint32),
                        key_hi=None if r_hi is None
                        else jnp.asarray(r_hi[rsel]))],
                    [TupleBatch(
                        key=jnp.asarray(s_keys[ssel]),
                        rid=jnp.zeros(int(ssel.sum()), jnp.uint32),
                        key_hi=None if s_hi is None
                        else jnp.asarray(s_hi[ssel]))],
                    max(1, min(slab, int(ssel.sum()))), measurements=m,
                    pipeline=pipeline)
        counts[p] = int(cnt)
        recovered += 1
        if manifest is not None:
            manifest.mark_done(p, int(cnt), plan.reassignment[p],
                               epoch=plan.epoch)
    if manifest is not None and only_rank is not None:
        # multi-survivor merge: fold in partitions other survivors
        # realized (their manifest lines are post-realization, so this
        # can only under- never over-count relative to the oracle)
        for p, rec in manifest.completed().items():
            counts.setdefault(int(p), rec["count"])
    matches = int(sum(counts.values()))
    if m is not None:
        m.incr(RECOVERN, recovered)
        m.incr(RECOVERMS, int((clock() - t0) * 1000))
        m.event("recovery", epoch=plan.epoch,
                lost_ranks=list(plan.lost_ranks),
                resumed=len(plan.resumed), recomputed=recovered,
                matches=matches)
    return matches, counts


def _null():
    import contextlib
    return contextlib.nullcontext()
