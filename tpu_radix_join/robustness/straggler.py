"""Straggler detection + speculative hedge accounting.

Rank loss (robustness/membership.py) has a dual failure mode the lease
machinery must NOT catch: a rank that is alive — heartbeating on time —
but slow.  Declaring it dead would be wrong (its collectives still
complete, eventually) and waiting for it stretches the whole join's
tail until the watchdog mislabels the stall.  The remedy is a *hedge*:
speculatively recompute the straggler's unfinished partitions
out-of-band (the masked ``chunked_join_grid`` path recovery already
uses) while the original keeps running, and let the
:class:`~tpu_radix_join.robustness.checkpoint.PartitionManifest`'s
first-writer-wins fence arbitrate — whichever writer realizes a
partition first owns its count, so kill-never-overclaims extends to
hedge-never-double-counts.

Detection is *relative progress*, not absolute time: every lease beat
carries ``partitions_done`` (the rank's manifest progress — the flight
recorder's progress clock exported to peers), and a rank is a straggler
when its progress falls below ``threshold`` × the live median while it
still has at least ``min_outstanding`` partitions to go.  The verdict
must hold for ``dwell_checks`` consecutive observations before it
stands — the detection mirror of the lease board's two-missed-beats
rule, so one slow poll never launches a hedge.

Counters: ``HEDGED`` per hedge launched, ``HEDGEWIN`` per hedged
partition whose speculative count won the manifest fence, ``SPECWASTE``
per hedged partition whose original landed first (wasted speculation —
the cost gauge that keeps ``--hedge auto`` honest).
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Dict, List, Mapping, Optional

from tpu_radix_join.performance.measurements import HEDGEWIN, SPECWASTE


class StragglerDetected(RuntimeError):
    """A live rank fell below the relative-progress threshold.

    Control flow, not a failure: the elastic wrapper absorbs it into the
    hedge path (the straggler stays a member — nothing is declared lost,
    no epoch bumps).  Raised only when hedging is enabled."""

    def __init__(self, rank: int, epoch: int, progress: int,
                 median: float, outstanding: int):
        super().__init__(
            f"rank {rank} straggling at epoch {epoch}: progress {progress} "
            f"< threshold x median {median:.1f} with {outstanding} "
            f"partition(s) outstanding — hedging its unfinished work")
        self.rank = int(rank)
        self.epoch = int(epoch)
        self.progress = int(progress)
        self.median = float(median)
        self.outstanding = int(outstanding)


@dataclasses.dataclass(frozen=True)
class StragglerVerdict:
    """One confirmed detection (post-dwell)."""

    rank: int
    progress: int
    median: float
    outstanding: int

    def to_exc(self, epoch: int) -> StragglerDetected:
        return StragglerDetected(self.rank, epoch, self.progress,
                                 self.median, self.outstanding)


class StragglerDetector:
    """Relative-progress straggler detector (see module docstring).

    ``observe`` is pure w.r.t. its inputs plus a small dwell state:
    callers feed ``{rank: partitions_done}`` (only ranks with known
    progress, i.e. ``partitions_done >= 0`` leases) and
    ``{rank: partitions_outstanding}``; the same suspect must survive
    ``dwell_checks`` consecutive calls before a verdict is returned.
    """

    def __init__(self, threshold: float = 0.5, min_outstanding: int = 2,
                 dwell_checks: int = 2):
        if not 0.0 < threshold < 1.0:
            raise ValueError(f"threshold must be in (0, 1), got {threshold}")
        if dwell_checks < 1:
            raise ValueError(f"dwell_checks must be >= 1, got {dwell_checks}")
        self.threshold = float(threshold)
        self.min_outstanding = int(min_outstanding)
        self.dwell_checks = int(dwell_checks)
        self._suspect: Optional[int] = None
        self._streak = 0

    def reset(self) -> None:
        self._suspect = None
        self._streak = 0

    def observe(self, progress: Mapping[int, int],
                outstanding: Mapping[int, int]
                ) -> Optional[StragglerVerdict]:
        """One detection poll; returns a verdict only once the same rank
        has been the suspect for ``dwell_checks`` consecutive calls."""
        if len(progress) < 2:
            self.reset()            # no peers to be relative to
            return None
        med = statistics.median(progress.values())
        if med <= 0:
            self.reset()            # nobody has progressed: too early
            return None
        # ties break to the smallest rank so every process's detector
        # converges on the same suspect (no-coordinator discipline)
        slowest = min(sorted(progress), key=lambda r: progress[r])
        behind = progress[slowest] < self.threshold * med
        todo = int(outstanding.get(slowest, 0))
        if not behind or todo < self.min_outstanding:
            self.reset()
            return None
        if slowest != self._suspect:
            self._suspect, self._streak = slowest, 0
        self._streak += 1
        if self._streak < self.dwell_checks:
            return None
        return StragglerVerdict(rank=slowest, progress=int(progress[slowest]),
                                median=float(med), outstanding=todo)


def board_progress(board, ranks) -> Dict[int, int]:
    """Per-rank ``partitions_done`` from live leases (the heartbeat
    metadata side of the progress clock); ranks whose lease carries no
    progress (-1) are omitted — the detector only compares ranks that
    export a clock."""
    out: Dict[int, int] = {}
    for r, lease in board.snapshot(ranks).items():
        if lease.partitions_done >= 0:
            out[int(r)] = int(lease.partitions_done)
    return out


def unfinished_partitions(num_partitions: int, owner_of, rank: int,
                          manifest=None) -> List[int]:
    """The partitions ``owner_of(p) == rank`` still owes — the hedge's
    work list (everything the straggler owns minus what the manifest
    already shows realized by anyone)."""
    done = set(manifest.completed()) if manifest is not None else set()
    return [p for p in range(num_partitions)
            if owner_of(p) == rank and p not in done]


def score_hedge(manifest, hedged_partitions, straggler: int,
                measurements=None) -> Dict[str, int]:
    """Post-hedge accounting against the manifest fence: for every hedged
    partition, the winning ``owner`` decides whether the speculation won
    (``HEDGEWIN``: someone other than the straggler holds the count) or
    was wasted (``SPECWASTE``: the original landed first).  Partitions
    with no winner yet count as wins-in-waiting for neither."""
    winners = manifest.completed()
    win = waste = 0
    for p in hedged_partitions:
        rec = winners.get(int(p))
        if rec is None:
            continue
        if int(rec["owner"]) == int(straggler):
            waste += 1
        else:
            win += 1
    m = measurements
    if m is not None:
        if win:
            m.incr(HEDGEWIN, win)
        if waste:
            m.incr(SPECWASTE, waste)
    return {"hedgewin": win, "specwaste": waste}
