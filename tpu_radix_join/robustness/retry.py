"""Retry policies and the machine-readable failure-class taxonomy.

Generalizes the engine's ad-hoc detect-and-retry window-grow loop
(operators/hash_join.py) into a reusable :class:`RetryPolicy` — max
attempts, exponential backoff, deterministic jitter — and gives every
terminal failure a *failure class* string derived from the existing
``JoinResult.diagnostics`` flag taxonomy, so callers branch on data
instead of parsing asserts (the reference's only contract was
``JOIN_ASSERT``, Window.cpp:180-191).

Classes (stable strings, stamped into ``diagnostics["failure_class"]``
and surfaced by main.py / bench reports):

  * ``ok``                   — no failure flags raised.
  * ``capacity_overflow``    — a measured buffer was too small (shuffle
    window, local partition slack, skew hot cap, rate cap).  RETRYABLE:
    regrow and rerun.
  * ``key_contract``         — input keys violate the declared key-range
    contract.  FATAL: growth cannot fix data.
  * ``conservation``         — tuples lost/duplicated across the shuffle.
    FATAL: indicates a bug, not a sizing problem.
  * ``count_overflow_risk``  — match count near the uint32 accumulator
    edge.  FATAL for the current dtype config.
  * ``data_corruption``      — a per-partition integrity checksum
    (verify.py: count / sum / xor-fold of key lanes) disagreed across
    pipeline stages, or the join-level cross-check failed.  FATAL for
    the attempt — but partition-granular (``--verify repair``
    recomputes only the damaged partitions, hash_join.py).
  * ``device_unavailable``   — accelerator/mesh init failed (degrade.py).
  * ``coordinator_timeout``  — distributed init could not reach the
    coordinator within policy (multihost.initialize).
  * ``interrupted``          — run killed mid-flight (resume via
    checkpoint.py).
  * ``checkpoint_mismatch``  — checkpoint fingerprint does not match the
    run configuration.
  * ``retries_exhausted``    — a retryable class persisted through every
    attempt (possibly after a failed fallback).
  * ``backend_unavailable``  — the requested JAX backend never became
    reachable within the wait budget (bench.py's pre-flight, the
    service's per-query dispatch probe); distinct from
    ``device_unavailable`` (init *failed*) because the remedy is
    "retry later / check the tunnel", not "fall back to CPU".
  * ``admission_rejected``   — the resident service refused the query at
    the door (queue depth or per-tenant quota, service/admission.py).
    The query never ran; resubmitting later is safe by construction.
  * ``request_error``        — the request line itself was malformed or
    unservable, so a serve worker refused it (service/fleet.py).  FATAL
    and worker-independent: the same line fails on every worker, so the
    fleet classifies instead of failing over — the fix is the client's.
  * ``deadline_exceeded``    — the query's latency budget expired between
    pipeline phases (service/deadline.py cooperative cancellation).
  * ``rank_lost``            — a peer rank's membership lease lapsed
    mid-run (robustness/membership.py).  NOT blind-retryable: the remedy
    is the explicit elastic-recovery path (robustness/recovery.py) —
    fence the membership epoch, re-plan on the survivor mesh, and resume
    at partition granularity — not a same-shape rerun, which would hang
    on the same dead collective.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from tpu_radix_join.performance.measurements import BACKOFFMS, RETRYN

# ------------------------------------------------------------ failure classes
OK = "ok"
CAPACITY_OVERFLOW = "capacity_overflow"
KEY_CONTRACT = "key_contract"
CONSERVATION = "conservation"
COUNT_OVERFLOW_RISK = "count_overflow_risk"
DATA_CORRUPTION = "data_corruption"
DEVICE_UNAVAILABLE = "device_unavailable"
COORDINATOR_TIMEOUT = "coordinator_timeout"
INTERRUPTED = "interrupted"
CHECKPOINT_MISMATCH = "checkpoint_mismatch"
RETRIES_EXHAUSTED = "retries_exhausted"
BACKEND_UNAVAILABLE = "backend_unavailable"
ADMISSION_REJECTED = "admission_rejected"
REQUEST_ERROR = "request_error"
DEADLINE_EXCEEDED = "deadline_exceeded"
RANK_LOST = "rank_lost"
RANK_JOIN = "rank_join"
PLAN_INFEASIBLE = "plan_infeasible"

#: diagnostics flags -> class, in priority order (fatal classes outrank
#: capacity: a key-contract violation must never look retryable just because
#: an overflow flag fired in the same attempt)
_FATAL_FLAGS = (
    ("key_contract_violations", KEY_CONTRACT),
    ("conservation_violations", CONSERVATION),
    ("data_corruption_partitions", DATA_CORRUPTION),
    ("count_overflow_risk", COUNT_OVERFLOW_RISK),
)
_CAPACITY_FLAGS = ("shuffle_overflow_r_tuples", "shuffle_overflow_s_tuples",
                   "local_overflow", "hot_overflow")


def classify_diagnostics(diag: dict) -> str:
    """Map a ``JoinResult.diagnostics`` dict to a failure-class string."""
    for flag, cls in _FATAL_FLAGS:
        if diag.get(flag, 0):
            return cls
    if any(diag.get(flag, 0) for flag in _CAPACITY_FLAGS):
        return CAPACITY_OVERFLOW
    return OK


#: classes a same-config rerun can plausibly fix.  Two families:
#:   * sizing — regrow-and-rerun repairs it (the engine's capacity loop);
#:   * transient infrastructure — nothing is wrong with the query, the
#:     substrate hiccupped (grid ``TransientFault`` pairs, a probe-phase
#:     tunnel outage): re-dispatch later on the same shapes.
#: Everything else (key contracts, conservation, corruption, admission /
#: deadline verdicts) is fatal for the attempt: retrying cannot fix data,
#: and retrying a rejected or expired query would double-bill its tenant.
RETRYABLE_SIZING = frozenset({CAPACITY_OVERFLOW})
RETRYABLE_TRANSIENT = frozenset({BACKEND_UNAVAILABLE, COORDINATOR_TIMEOUT})
DEFAULT_RETRYABLE = RETRYABLE_SIZING | RETRYABLE_TRANSIENT


def is_retryable_class(failure_class: str,
                       policy: Optional["RetryPolicy"] = None) -> bool:
    """Policy-driven retryability predicate, shared by the engine's
    capacity loop, the grid's transient-pair retries, and the service's
    dispatch path.  Without a policy the :data:`DEFAULT_RETRYABLE` set
    applies; a :class:`RetryPolicy` narrows or widens it through its
    ``retryable_classes`` field (e.g. the engine's regrow loop passes a
    sizing-only policy — a tunnel outage must fall through to the breaker,
    not spin the capacity doubler)."""
    classes = policy.retryable_classes if policy is not None \
        else DEFAULT_RETRYABLE
    return failure_class in classes


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    ``delay_s(attempt)`` is the sleep AFTER failed attempt ``attempt``
    (0-based): ``base_delay_s * multiplier**attempt`` capped at
    ``max_delay_s``, then scaled by a jitter factor in ``[1-jitter,
    1+jitter]`` drawn from ``Random((seed << 16) ^ attempt)`` — the same
    (seed, attempt) always yields the same delay, so backoff schedules are
    replayable in tests (fake clock) and across processes (no thundering
    re-sync because each process seeds with its rank).

    ``max_elapsed_s``: optional wall-clock budget — :func:`execute` stops
    retrying (re-raises) once the clock since the first attempt exceeds it,
    the deadline discipline bench.py's backend wait needs.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.0
    seed: int = 0
    max_elapsed_s: Optional[float] = None
    #: failure classes :func:`is_retryable_class` accepts under this policy
    retryable_classes: frozenset = DEFAULT_RETRYABLE

    def delay_s(self, attempt: int) -> float:
        d = min(self.max_delay_s,
                self.base_delay_s * self.multiplier ** attempt)
        if self.jitter and d > 0:
            u = random.Random((self.seed << 16) ^ attempt).random()
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return d

    def schedule(self) -> Tuple[float, ...]:
        """The full backoff schedule (one sleep between each attempt pair)."""
        return tuple(self.delay_s(a) for a in range(self.max_attempts - 1))


class RetriesExhausted(RuntimeError):
    """A retryable failure persisted through every attempt."""

    failure_class = RETRIES_EXHAUSTED

    def __init__(self, label: str, attempts: int, last_error: BaseException):
        super().__init__(
            f"{label}: {attempts} attempt(s) exhausted; last error: "
            f"{last_error!r}")
        self.label = label
        self.attempts = attempts
        self.last_error = last_error


def execute(fn: Callable, policy: RetryPolicy, *,
            retryable: Tuple[Type[BaseException], ...] = (
                ConnectionError, TimeoutError, OSError),
            sleep: Callable[[float], None] = time.sleep,
            clock: Callable[[], float] = time.monotonic,
            measurements=None,
            on_retry: Optional[Callable] = None,
            label: str = "retry") -> object:
    """Call ``fn()`` under ``policy``.

    Exceptions in ``retryable`` trigger backoff-and-retry (``RETRYN`` and
    ``BACKOFFMS`` counters + a ``retry`` trace event per attempt), as does
    any exception whose ``failure_class`` satisfies
    :func:`is_retryable_class` under ``policy`` — the one predicate the
    engine's capacity loop, the grid's transient-pair retries, and the
    service's dispatch path all share.  Anything else propagates
    immediately.  When attempts or the ``max_elapsed_s`` budget run out,
    raises :class:`RetriesExhausted` chaining the last error.
    ``sleep``/``clock`` are injectable for fake-clock tests.
    """

    def _should_retry(e: BaseException) -> bool:
        if isinstance(e, retryable):
            return True
        cls = getattr(e, "failure_class", None)
        return cls is not None and is_retryable_class(cls, policy)

    t0 = clock()
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except Exception as e:
            if not _should_retry(e):
                raise
            last = e
            out_of_time = (policy.max_elapsed_s is not None
                           and clock() - t0 >= policy.max_elapsed_s)
            if attempt == policy.max_attempts - 1 or out_of_time:
                raise RetriesExhausted(label, attempt + 1, last) from last
            delay = policy.delay_s(attempt)
            if measurements is not None:
                measurements.incr(RETRYN)
                measurements.incr(BACKOFFMS, int(delay * 1000))
                measurements.event("retry", site=label, attempt=attempt + 1,
                                   delay_s=round(delay, 6), error=repr(e))
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
    raise RetriesExhausted(label, policy.max_attempts, last) from last  # pragma: no cover - loop always returns or raises above
