"""Seeded chaos/soak harness with shrinking fault-schedule repros.

The soak invariant this module enforces end to end: **every join run,
under any schedule of injected faults, either passes verification or
terminates with a classified failure** (``diagnostics["failure_class"]``
or an exception carrying one).  A run that returns ``ok=True`` with a
wrong count, or dies with an unclassified exception, is a VIOLATION —
the silent-corruption outcome the integrity checksums
(robustness/verify.py) exist to rule out.

Pieces:

  * :func:`generate_schedule` — a seeded schedule of fault arms drawn from
    the :data:`CHAOS_SITES` subset of :data:`faults.SITES` (the sites the
    array-join path actually consults; arming the grid/checkpoint sites
    here would just warn and never fire).
  * :class:`ChaosRunner` — executes one schedule against a cached engine
    on known-oracle inputs and classifies the outcome
    (``pass`` | ``classified`` | ``violation``).
  * :func:`soak` — N seeded runs; returns outcomes plus a summary the
    callers (bench.py ``--chaos``, tools_chaos.py, tests/test_chaos.py)
    assert the invariant over.
  * :func:`shrink` — greedy delta-debugging of a violating schedule down
    to a minimal still-violating arm set; :func:`write_repro` persists the
    ``(seed, arms)`` pair that replays it deterministically.

Engine-heavy: import lazily (the robustness/__init__ discipline for
degrade.py), e.g. ``from tpu_radix_join.robustness import chaos``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from tpu_radix_join.robustness import faults
from tpu_radix_join.robustness.retry import DEVICE_UNAVAILABLE

#: sites the ``join_arrays`` path consults, i.e. the arms that can fire in
#: a soak run (faults.SITES minus the grid/checkpoint/stream/coordinator
#: vocabulary, which only the out-of-core and multihost paths hit)
CHAOS_SITES: Tuple[str, ...] = (
    faults.SHUFFLE_OVERFLOW,
    faults.DEVICE_INIT,
    faults.EXCHANGE_CORRUPT,
)

#: failure class carried by an :class:`faults.InjectedFault` raised at a
#: site (exceptions from *corrupting* sites instead surface through the
#: engine's own classification)
_SITE_CLASSES = {faults.DEVICE_INIT: DEVICE_UNAVAILABLE}

PASS = "pass"
CLASSIFIED = "classified"
VIOLATION = "violation"


def _violation_bundle(m, schedule: "Schedule", detail: str,
                      bundle_dir: Optional[str]) -> Optional[str]:
    """Forensics bundle for a soak VIOLATION: the run's registry + ring
    plus the violating ``(seed, arms)`` schedule.  Never escalates — a
    bundle-write error must not turn the harness's verdict into a crash."""
    if not bundle_dir:
        return None
    try:
        from tpu_radix_join.observability.postmortem import write_bundle
        return write_bundle(bundle_dir, m, reason="chaos_violation",
                            failure_class=None, chaos=schedule,
                            extra={"detail": detail})
    except Exception:           # noqa: BLE001 — forensics must not mask
        return None


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A replayable fault schedule: the injector seed plus the armed
    ``(site, arm-kwargs)`` pairs.  Determinism is inherited from
    :class:`faults.FaultInjector` (per-site ``random.Random(seed:site)``),
    so ``(seed, arms)`` IS the repro."""

    seed: int
    arms: Tuple[Tuple[str, Tuple[Tuple[str, int], ...]], ...]

    def arm_dicts(self) -> List[Tuple[str, Dict[str, int]]]:
        return [(site, dict(kw)) for site, kw in self.arms]

    def to_json(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "arms": [[site, dict(kw)] for site, kw in self.arms]}

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> "Schedule":
        return cls(seed=int(obj["seed"]),
                   arms=tuple((str(site),
                               tuple(sorted((str(k), int(v))
                                            for k, v in kw.items())))
                              for site, kw in obj["arms"]))

    def without(self, index: int) -> "Schedule":
        return dataclasses.replace(
            self, arms=self.arms[:index] + self.arms[index + 1:])


@dataclasses.dataclass(frozen=True)
class RunOutcome:
    schedule: Schedule
    status: str                       # PASS | CLASSIFIED | VIOLATION
    failure_class: Optional[str]      # set when CLASSIFIED
    matches: Optional[int]            # set when the join returned
    detail: str = ""
    bundle: Optional[str] = None      # forensics bundle path (violations)

    def to_json(self) -> Dict[str, Any]:
        out = {"schedule": self.schedule.to_json(), "status": self.status,
               "failure_class": self.failure_class,
               "matches": self.matches, "detail": self.detail}
        if self.bundle:
            # the repro artifact names the evidence next to the (seed,
            # arms) pair; absent for non-violating runs (shape stable)
            out["bundle"] = self.bundle
        return out


def generate_schedule(seed: int) -> Schedule:
    """1-3 distinct arms over :data:`CHAOS_SITES`, fully determined by
    ``seed``.  The corruption and device-init sites are consulted once per
    run, so their arm is always ``at=1``; the shuffle-overflow site is
    consulted once per retry attempt, so its hit index varies — ``at=2``
    exercises injection into an already-retried attempt."""
    rng = random.Random(seed)
    sites = rng.sample(CHAOS_SITES, rng.randint(1, len(CHAOS_SITES)))
    arms = []
    for site in sites:
        at = rng.randint(1, 2) if site == faults.SHUFFLE_OVERFLOW else 1
        arms.append((site, (("at", at),)))
    return Schedule(seed=seed, arms=tuple(arms))


class ChaosRunner:
    """Executes fault schedules against one cached engine.

    The engine, its mesh, and its compile cache are built once and reused
    across the soak (per-run construction would recompile the pipeline
    every time); the ``engine.device_init`` site — which in production
    fires in the constructor — is therefore consulted explicitly at the
    top of each run, modeling a fresh bring-up per schedule.

    Inputs are oracle-friendly by construction: R's keys are a permutation
    of 1..n (unique, covering) and S's are uniform over 1..n, so every
    outer tuple matches exactly one inner tuple and the true count is
    exactly ``n`` — any bit of injected corruption moves the count off the
    oracle, making silent wrong answers detectable without a second join.
    """

    def __init__(self, num_nodes: int = 4, size: int = 1 << 12,
                 verify: str = "check", data_seed: int = 0,
                 config_overrides: Optional[Dict[str, Any]] = None,
                 bundle_dir: Optional[str] = None):
        from tpu_radix_join.core.config import JoinConfig
        from tpu_radix_join.operators.hash_join import HashJoin
        from tpu_radix_join.performance.measurements import Measurements
        self._measurements_cls = Measurements
        self.bundle_dir = bundle_dir
        self.oracle = size
        rng = np.random.default_rng(data_seed)
        self._rk = (rng.permutation(size) + 1).astype(np.uint32)
        self._sk = rng.integers(1, size + 1, size=size).astype(np.uint32)
        self._rid = np.arange(size, dtype=np.uint32)
        cfg = JoinConfig(num_nodes=num_nodes, verify=verify,
                         **(config_overrides or {}))
        self.config = cfg
        self.engine = HashJoin(cfg)
        self.measurements: List[Any] = []   # one registry per run, in order

    def _batches(self):
        import jax.numpy as jnp
        from tpu_radix_join.data.tuples import TupleBatch
        # fresh uncommitted arrays per run: the exchange-corruption site
        # mutates its input host-side, and a shared committed batch would
        # leak one run's damage into the next
        return (TupleBatch(key=jnp.asarray(self._rk),
                           rid=jnp.asarray(self._rid), key_hi=None),
                TupleBatch(key=jnp.asarray(self._sk),
                           rid=jnp.asarray(self._rid), key_hi=None))

    def run(self, schedule: Schedule) -> RunOutcome:
        out = self._run(schedule)
        if out.status == VIOLATION:
            out = dataclasses.replace(out, bundle=_violation_bundle(
                self.measurements[-1], schedule, out.detail,
                self.bundle_dir))
        return out

    def _bind(self, m) -> None:
        """Per-run registry hook: the base runner's engine records no
        counters (matching production one-shot runs where the registry
        outlives the engine); :class:`RecoveryChaosRunner` overrides to
        point the cached engine at this run's registry so RANKLOST /
        RECOVERN / MEPOCH land where the soak can read them."""

    def _run(self, schedule: Schedule) -> RunOutcome:
        m = self._measurements_cls()
        self.measurements.append(m)
        self._bind(m)
        inj = faults.FaultInjector(seed=schedule.seed, measurements=m)
        for site, kw in schedule.arm_dicts():
            inj.arm(site, **kw)
        try:
            with inj:
                # the constructor-time site, consulted per run because the
                # engine is cached (see class docstring)
                faults.check(faults.DEVICE_INIT, m)
                result = self.engine.join_arrays(*self._batches())
        except faults.InjectedFault as e:
            # the exception's own class wins (TransientFault carries
            # backend_unavailable); the site table covers the bare
            # InjectedFault sites
            cls = getattr(e, "failure_class", None) or _SITE_CLASSES.get(
                e.site)
            if cls is None:
                return RunOutcome(schedule, VIOLATION, None, None,
                                  f"unclassified injected fault: {e!r}")
            return RunOutcome(schedule, CLASSIFIED, cls, None, repr(e))
        except Exception as e:
            cls = getattr(e, "failure_class", None)
            if cls is None:
                return RunOutcome(schedule, VIOLATION, None, None,
                                  f"unclassified exception: {e!r}")
            return RunOutcome(schedule, CLASSIFIED, cls, None, repr(e))
        if result.ok:
            if result.matches != self.oracle:
                return RunOutcome(
                    schedule, VIOLATION, None, result.matches,
                    f"silent wrong count: {result.matches} != oracle "
                    f"{self.oracle}")
            return RunOutcome(schedule, PASS, None, result.matches)
        cls = (result.diagnostics or {}).get("failure_class")
        if not cls or cls == "ok":
            return RunOutcome(schedule, VIOLATION, cls, result.matches,
                              "ok=False without a failure class")
        return RunOutcome(schedule, CLASSIFIED, cls, result.matches)


def soak(runs: int, base_seed: int = 0, runner: Optional[ChaosRunner] = None,
         verify: str = "check",
         on_outcome: Optional[Callable[[RunOutcome], None]] = None):
    """N seeded schedules (seeds ``base_seed .. base_seed+runs-1``) through
    one runner.  Returns ``(outcomes, summary)``; asserting the no-violation
    invariant is the caller's job (tests want to assert it, the violation
    demo wants to harvest them)."""
    runner = runner or ChaosRunner(verify=verify)
    outcomes = []
    for i in range(runs):
        out = runner.run(generate_schedule(base_seed + i))
        outcomes.append(out)
        if on_outcome:
            on_outcome(out)
    summary = {
        "runs": runs,
        "base_seed": base_seed,
        "verify": runner.config.verify,
        "pass": sum(o.status == PASS for o in outcomes),
        "classified": sum(o.status == CLASSIFIED for o in outcomes),
        "violations": sum(o.status == VIOLATION for o in outcomes),
        "failure_classes": sorted({o.failure_class for o in outcomes
                                   if o.failure_class}),
    }
    return outcomes, summary


#: the elastic-recovery soak vocabulary: every array-path site PLUS the
#: membership sites — rank death and rank join (both consulted at every
#: ``_check_cancel`` phase boundary — hit 1 is "start", 2 is "sized", 3+
#: are the per-attempt "probe" boundaries, so a seeded hit index IS a
#: seeded phase boundary) — and the compute-straggle site (consulted
#: once per attempt, inside the pipeline)
RECOVERY_SITES: Tuple[str, ...] = CHAOS_SITES + (
    faults.RANK_DEATH, faults.RANK_JOIN, faults.COMPUTE_STRAGGLE)


def generate_recovery_schedule(seed: int) -> Schedule:
    """Always one ``membership.rank_death`` arm at a seeded phase
    boundary (``at`` in 1..3 — start/sized/probe), plus 0-2 arms from
    :data:`CHAOS_SITES` so rank loss composes with the faults it can
    race (a corruption before the death, an overflow retry around it).

    The membership interleavings ride the same seed: roughly half the
    schedules also arm ``membership.rank_join`` at its own seeded
    boundary (join-during-recovery when the admission lands around the
    death's boundary), and roughly half arm ``compute.straggle``
    (straggle-then-die: a live-but-slow rank races the death — whichever
    site's boundary fires first owns the abort, and the invariant is the
    same either way: oracle-exact or classified, never a double count)."""
    rng = random.Random(seed)
    arms = [(faults.RANK_DEATH, (("at", rng.randint(1, 3)),))]
    if rng.random() < 0.5:
        arms.append((faults.RANK_JOIN, (("at", rng.randint(1, 3)),)))
    if rng.random() < 0.5:
        arms.append((faults.COMPUTE_STRAGGLE, (("at", 1),)))
    for site in rng.sample(CHAOS_SITES, rng.randint(0, 2)):
        at = rng.randint(1, 2) if site == faults.SHUFFLE_OVERFLOW else 1
        arms.append((site, (("at", at),)))
    return Schedule(seed=seed, arms=tuple(arms))


class RecoveryChaosRunner(ChaosRunner):
    """:class:`ChaosRunner` with the elastic path armed.

    The cached engine runs with ``elastic=True``: a fired
    ``membership.rank_death`` must end in the exact oracle count
    (recovered, PASS) — never a hang, never an overclaim; any escaping
    rank loss still classifies as ``rank_lost``.  The default geometry
    shrinks to 8 network partitions (``network_fanout_bits=3``): each
    recovered partition is its own masked out-of-core join, and partition
    count is the knob that bounds the soak's recompute wall.

    The growth/hedging sites get real state per run (:meth:`_bind`): a
    fresh single-process membership view (so ``membership.rank_join``
    admissions land in a clean epoch sequence) with ``elastic_grow`` on,
    and a fresh :class:`PartitionManifest` (the hedge's fence).  The
    straggle slowdown factor is seeded per schedule
    (``random.Random(f"{seed}:straggle")`` — the faults.py determinism
    convention) and hedging is on, so a fired ``compute.straggle``
    exercises detect→hedge→score instead of just sleeping.  After every
    run the manifest is audited: a PASS whose winning-line total differs
    from the oracle is a double-count — a VIOLATION even though the
    splice looked right (the invariant hedge-never-double-counts)."""

    def __init__(self, num_nodes: int = 4, size: int = 1 << 11,
                 verify: str = "check", data_seed: int = 0,
                 config_overrides: Optional[Dict[str, Any]] = None,
                 bundle_dir: Optional[str] = None):
        overrides = dict(config_overrides or {})
        overrides.setdefault("network_fanout_bits", 3)
        super().__init__(num_nodes=num_nodes, size=size, verify=verify,
                         data_seed=data_seed, config_overrides=overrides,
                         bundle_dir=bundle_dir)
        self.engine.elastic = True
        self.engine.elastic_grow = True
        self.engine.hedge = "on"
        self.engine.straggle_unit_s = 0.02   # bounded soak wall
        self.audits: List[Dict[str, Any]] = []   # one manifest audit per run

    def _bind(self, m) -> None:
        import tempfile

        from tpu_radix_join.robustness.checkpoint import PartitionManifest
        from tpu_radix_join.robustness.membership import (LeaseBoard,
                                                          MembershipView)
        self.engine.measurements = m
        # fresh membership + manifest per run: epochs, admissions, and
        # fence lines must not leak across schedules (a large lease so an
        # injected joiner's one-shot lease never lapses mid-soak)
        run_dir = tempfile.mkdtemp(prefix="tpu_rj_chaos_")
        board = LeaseBoard(run_dir, rank=0, num_ranks=1, lease_s=300.0,
                           measurements=m)
        self.engine.membership = MembershipView(board, measurements=m)
        self.engine.partition_manifest = PartitionManifest(
            os.path.join(run_dir, "parts.manifest"),
            fingerprint={"chaos_oracle": self.oracle}, measurements=m)

    def run(self, schedule: Schedule) -> RunOutcome:
        self.engine.straggle_factor = random.Random(
            f"{schedule.seed}:straggle").uniform(2.0, 6.0)
        out = super().run(schedule)
        aud = self.engine.partition_manifest.audit()
        self.audits.append(aud)
        if out.status == PASS and aud["total"] != self.oracle:
            out = dataclasses.replace(
                out, status=VIOLATION,
                detail=f"manifest double-count: winning lines sum to "
                       f"{aud['total']} != oracle {self.oracle} "
                       f"(fenced_duplicates={aud['fenced_duplicates']})")
            out = dataclasses.replace(out, bundle=_violation_bundle(
                self.measurements[-1], schedule, out.detail,
                self.bundle_dir))
        return out


def soak_recovery(runs: int, base_seed: int = 0,
                  runner: Optional[RecoveryChaosRunner] = None,
                  on_outcome: Optional[Callable[[RunOutcome], None]] = None):
    """Rank-death soak: N seeded recovery schedules through one elastic
    runner.  The summary adds the recovery acceptance signals on top of
    the base invariant fields: ``ranklost``/``recovered_partitions``/
    ``max_epoch`` totals across the soak, and ``wdogtrip`` — which must
    stay 0 (a recovered run never books a watchdog death; a nonzero
    value means a stall was killed instead of triaged).  The growth and
    hedging arms add their own: ``rankjoin`` (admissions), ``hedged`` /
    ``hedgewin`` / ``specwaste`` (speculation accounting), and
    ``manifest_exact`` — runs whose post-run manifest audit summed
    exactly to the oracle (the zero-double-count invariant; audited
    mismatches on PASS runs are already VIOLATIONs)."""
    from tpu_radix_join.performance.measurements import (HEDGED, HEDGEWIN,
                                                         MEPOCH, RANKJOIN,
                                                         RANKLOST, RECOVERN,
                                                         SPECWASTE, WDOGTRIP)
    runner = runner or RecoveryChaosRunner()
    outcomes = []
    for i in range(runs):
        out = runner.run(generate_recovery_schedule(base_seed + i))
        outcomes.append(out)
        if on_outcome:
            on_outcome(out)
    regs = runner.measurements[-runs:]
    summary = {
        "runs": runs,
        "base_seed": base_seed,
        "verify": runner.config.verify,
        "pass": sum(o.status == PASS for o in outcomes),
        "classified": sum(o.status == CLASSIFIED for o in outcomes),
        "violations": sum(o.status == VIOLATION for o in outcomes),
        "failure_classes": sorted({o.failure_class for o in outcomes
                                   if o.failure_class}),
        "ranklost": sum(int(m.counters.get(RANKLOST, 0)) for m in regs),
        "rankjoin": sum(int(m.counters.get(RANKJOIN, 0)) for m in regs),
        "hedged": sum(int(m.counters.get(HEDGED, 0)) for m in regs),
        "hedgewin": sum(int(m.counters.get(HEDGEWIN, 0)) for m in regs),
        "specwaste": sum(int(m.counters.get(SPECWASTE, 0)) for m in regs),
        "recovered_partitions": sum(int(m.counters.get(RECOVERN, 0))
                                    for m in regs),
        "max_epoch": max((int(m.counters.get(MEPOCH, 0)) for m in regs),
                         default=0),
        "wdogtrip": sum(int(m.counters.get(WDOGTRIP, 0)) for m in regs),
        "manifest_exact": sum(
            a["total"] == runner.oracle
            for a in getattr(runner, "audits", [])[-runs:]),
    }
    return outcomes, summary


#: sites a resident serve loop consults per query: the per-query dispatch
#: outage (service/session.py) plus the engine-interior sites join_arrays
#: hits — a session soak exercises breaker trips and engine failures in
#: the same stream.  serve.cache_poison corrupts a stored result-cache
#: entry in place (service/resultcache.py); the digest re-verification
#: must drop it and re-execute, so a poisoned cache can cause a miss but
#: never a silent wrong count.
SESSION_SITES: Tuple[str, ...] = (
    faults.BACKEND_DISPATCH,
    faults.SHUFFLE_OVERFLOW,
    faults.EXCHANGE_CORRUPT,
    faults.CACHE_POISON,
)


def generate_session_schedule(seed: int, queries: int = 6) -> Schedule:
    """1-3 arms over :data:`SESSION_SITES`, each firing at a seeded query
    index within the stream (every session site is consulted once per
    query, so the hit index IS the query index)."""
    rng = random.Random(seed)
    sites = rng.sample(SESSION_SITES, rng.randint(1, len(SESSION_SITES)))
    arms = []
    for site in sites:
        arms.append((site, (("at", rng.randint(1, max(1, queries - 1))),)))
    return Schedule(seed=seed, arms=tuple(arms))


class SessionChaosRunner:
    """Executes fault schedules against a resident :class:`JoinSession`.

    Each ``run`` streams ``queries`` requests through ONE freshly built
    session while the schedule's arms fire at seeded query indices.  The
    soak invariant is the service's failure-isolation contract: **every
    query ends in a classified outcome and the session survives the whole
    stream** — a query that dies unclassified, a silent wrong count, or an
    exception escaping the serve loop is a VIOLATION.  The breaker is
    configured aggressively (threshold 1, zero cooldown) so a single
    armed ``backend.dispatch`` outage exercises the full
    trip -> degraded-serve -> half-open-probe -> close cycle inside one
    short stream.
    """

    def __init__(self, num_nodes: int = 4, size: int = 1 << 12,
                 verify: str = "check", queries: int = 6,
                 data_seed: int = 0,
                 config_overrides: Optional[Dict[str, Any]] = None,
                 bundle_dir: Optional[str] = None):
        from tpu_radix_join.core.config import JoinConfig, ServiceConfig
        from tpu_radix_join.performance.measurements import Measurements
        self._measurements_cls = Measurements
        self.bundle_dir = bundle_dir
        self.size = size
        self.queries = queries
        self.data_seed = data_seed
        self.config = JoinConfig(num_nodes=num_nodes, verify=verify,
                                 **(config_overrides or {}))
        # the result cache is LIVE in the soak (every query shares one
        # content fingerprint, so queries 2..N are cache hits) — that is
        # what gives the serve.cache_poison arm a stored entry to corrupt
        self.service = ServiceConfig(breaker_threshold=1,
                                     breaker_cooldown_s=0.0,
                                     result_cache_max=4)
        self.measurements: List[Any] = []   # one registry per run, in order

    def run(self, schedule: Schedule) -> RunOutcome:
        out = self._run(schedule)
        if out.status == VIOLATION:
            out = dataclasses.replace(out, bundle=_violation_bundle(
                self.measurements[-1], schedule, out.detail,
                self.bundle_dir))
        return out

    def _run(self, schedule: Schedule) -> RunOutcome:
        from tpu_radix_join.service import (UNCLASSIFIED, JoinSession,
                                            QueryRequest)
        m = self._measurements_cls()
        self.measurements.append(m)
        inj = faults.FaultInjector(seed=schedule.seed, measurements=m)
        for site, kw in schedule.arm_dicts():
            inj.arm(site, **kw)
        session = JoinSession(self.config, self.service, measurements=m)
        outs = []
        try:
            with inj:
                for i in range(self.queries):
                    # cycle 3 distinct contents: the first lap of the
                    # stream executes (misses), later laps hit the result
                    # cache — so engine-interior arms and the cache-poison
                    # arm both get live consultations in one stream
                    request = QueryRequest(
                        query_id=f"q{i}", tuples_per_node=self.size,
                        seed=self.data_seed + (i % 3))
                    session.submit(request)
                    outs.append(session.run_next())
        except Exception as e:      # noqa: BLE001 — the invariant itself
            return RunOutcome(schedule, VIOLATION, None, None,
                              f"session died at query {len(outs)}: {e!r}")
        finally:
            session.close()
        detail = " ".join(f"{o.query_id}={o.status}/{o.failure_class}"
                          for o in outs)
        for o in outs:
            if o.failure_class == UNCLASSIFIED:
                return RunOutcome(schedule, VIOLATION, None, o.matches,
                                  f"unclassified query outcome: {detail}")
            if (o.status == "ok" and o.expected is not None
                    and o.matches != o.expected):
                return RunOutcome(
                    schedule, VIOLATION, None, o.matches,
                    f"silent wrong count on {o.query_id}: {o.matches} != "
                    f"oracle {o.expected} ({detail})")
        classes = sorted({o.failure_class for o in outs
                          if o.failure_class != "ok"})
        last_ok = next((o.matches for o in reversed(outs)
                        if o.status == "ok"), None)
        if not classes:
            return RunOutcome(schedule, PASS, None, last_ok, detail)
        return RunOutcome(schedule, CLASSIFIED, ",".join(classes),
                          last_ok, detail)


def soak_session(runs: int, base_seed: int = 0,
                 runner: Optional[SessionChaosRunner] = None,
                 verify: str = "check",
                 on_outcome: Optional[Callable[[RunOutcome], None]] = None):
    """N seeded session streams (:func:`generate_session_schedule`) through
    one :class:`SessionChaosRunner`; same return shape as :func:`soak`.
    A violating schedule shrinks with the same :func:`shrink` (the
    session runner's decisions are seed-deterministic too)."""
    runner = runner or SessionChaosRunner(verify=verify)
    outcomes = []
    for i in range(runs):
        out = runner.run(generate_session_schedule(base_seed + i,
                                                   runner.queries))
        outcomes.append(out)
        if on_outcome:
            on_outcome(out)
    summary = {
        "runs": runs,
        "base_seed": base_seed,
        "verify": runner.config.verify,
        "queries_per_run": runner.queries,
        "pass": sum(o.status == PASS for o in outcomes),
        "classified": sum(o.status == CLASSIFIED for o in outcomes),
        "violations": sum(o.status == VIOLATION for o in outcomes),
        "failure_classes": sorted({c for o in outcomes if o.failure_class
                                   for c in o.failure_class.split(",")}),
    }
    return outcomes, summary


def shrink(schedule: Schedule,
           violates: Callable[[Schedule], bool]) -> Schedule:
    """Greedy ddmin over arms: repeatedly drop any single arm whose removal
    keeps the schedule violating, to a fixpoint.  Every candidate is
    re-executed (the fault decisions are seed-deterministic, so a kept
    reduction is guaranteed replayable), giving a 1-minimal repro: removing
    any remaining arm makes the violation disappear."""
    if not violates(schedule):
        raise ValueError("shrink() needs a violating schedule to start from")
    shrunk = True
    while shrunk and len(schedule.arms) > 1:
        shrunk = False
        for i in range(len(schedule.arms)):
            cand = schedule.without(i)
            if violates(cand):
                schedule = cand
                shrunk = True
                break
    return schedule


def write_repro(outcome: RunOutcome, path) -> str:
    """Persist a violating run's minimal repro as one JSON object — the
    ``(seed, arms)`` pair plus what went wrong — and return the JSON line
    (printed by the soak CLIs so the repro survives even if the artifact
    dir does not)."""
    line = json.dumps(outcome.to_json(), sort_keys=True)
    with open(path, "w") as f:
        f.write(line + "\n")
    return line


# --------------------------------------------------------------------- fleet
#: sites the fleet supervisor's dispatch loop consults (service/fleet.py):
#: the worker-kill site fires right after a query hits a worker's pipe, so
#: the hit index IS the dispatched-query index (replay attempts re-consult
#: it — a schedule can kill the replay's worker too)
FLEET_SITES: Tuple[str, ...] = (
    faults.FLEET_WORKER_KILL,
)


def generate_fleet_schedule(seed: int, queries: int = 4) -> Schedule:
    """One ``fleet.worker_kill`` arm at a seeded dispatch index — mid-
    stream worker death, fully determined by ``seed``.  Kept to a single
    site (the only one the supervisor consults) so shrinking degenerates
    to "the kill did it"; the interesting variation is WHERE in the
    stream the kill lands."""
    rng = random.Random(seed)
    site = rng.choice(FLEET_SITES)
    return Schedule(seed=seed,
                    arms=((site, (("at", rng.randint(1, max(1, queries))),)),))


class FleetChaosRunner:
    """Executes ``fleet.worker_kill`` schedules against ONE resident
    :class:`~tpu_radix_join.service.fleet.FleetSupervisor`.

    The supervisor is shared across runs by design: worker boot is the
    expensive part (a JAX import + device init per subprocess), and a
    crash-only supervisor is *supposed* to keep serving across arbitrary
    worker deaths — reusing it across schedules IS the soak.  The
    invariant per run: **every dispatched query returns exactly one
    outcome, oracle-exact (``matches == expected``) or classified, the
    journal audit counts zero double-executions, and the supervisor
    survives the stream**.  An escaped exception, an unclassified
    outcome, a silent wrong count, or ``double_exec > 0`` is a
    VIOLATION.

    ``batched=True`` dispatches each run's queries as ONE co-batchable
    group through ``dispatch_batch`` (the supervisor must have a batch
    window armed) — the worker-kill site then fires between the group's
    back-to-back request writes, i.e. MID-BATCH, and the invariant holds
    that failover re-dispatches the stranded members without a single
    double-execution.
    """

    def __init__(self, supervisor, queries: int = 3, size: int = 1 << 10,
                 data_seed: int = 0, bundle_dir: Optional[str] = None,
                 batched: bool = False):
        self.supervisor = supervisor
        self.queries = queries
        self.size = size
        self.data_seed = data_seed
        self.bundle_dir = bundle_dir
        self.batched = batched
        self.measurements: List[Any] = []

    def run(self, schedule: Schedule) -> RunOutcome:
        out = self._run(schedule)
        if out.status == VIOLATION and self.measurements:
            out = dataclasses.replace(out, bundle=_violation_bundle(
                self.measurements[-1], schedule, out.detail,
                self.bundle_dir))
        return out

    def _run(self, schedule: Schedule) -> RunOutcome:
        from tpu_radix_join.service import UNCLASSIFIED
        sup = self.supervisor
        m = sup.measurements
        if m is not None:
            self.measurements.append(m)
        inj = faults.FaultInjector(seed=schedule.seed, measurements=m)
        for site, kw in schedule.arm_dicts():
            inj.arm(site, **kw)
        outs = []
        try:
            with inj:
                # seed-qualified ids keep fingerprints distinct across
                # runs — the journal dedup must only collapse genuine
                # re-submissions, not the soak's fresh queries
                requests = [{"query_id": f"s{schedule.seed}q{i}",
                             "tenant": f"t{i % 2}",
                             "tuples_per_node": self.size,
                             "seed": self.data_seed}
                            for i in range(self.queries)]
                if self.batched:
                    # one co-batchable group through dispatch_batch: the
                    # kill arm lands between the group's request writes
                    outs = sup.dispatch_batch(requests)
                else:
                    for request in requests:
                        outs.append(sup.dispatch(request))
        except Exception as e:      # noqa: BLE001 — the invariant itself
            return RunOutcome(schedule, VIOLATION, None, None,
                              f"supervisor died at query {len(outs)}: {e!r}")
        detail = " ".join(
            f"{o.get('query_id')}={o.get('status')}/{o.get('failure_class')}"
            for o in outs)
        audit = sup.journal.audit()
        if audit.double_exec:
            return RunOutcome(schedule, VIOLATION, None, None,
                              f"{audit.double_exec} double-executed "
                              f"fingerprint(s) in the journal: {detail}")
        for o in outs:
            if o is None:
                return RunOutcome(schedule, VIOLATION, None, None,
                                  f"query vanished without an outcome: "
                                  f"{detail}")
            if o.get("failure_class") == UNCLASSIFIED:
                return RunOutcome(schedule, VIOLATION, None, o.get("matches"),
                                  f"unclassified query outcome: {detail}")
            if (o.get("status") == "ok" and o.get("expected") is not None
                    and o.get("matches") != o.get("expected")):
                return RunOutcome(
                    schedule, VIOLATION, None, o.get("matches"),
                    f"silent wrong count on {o.get('query_id')}: "
                    f"{o.get('matches')} != oracle {o.get('expected')} "
                    f"({detail})")
        classes = sorted({o["failure_class"] for o in outs
                          if o.get("failure_class")
                          and o["failure_class"] != "ok"})
        last_ok = next((o.get("matches") for o in reversed(outs)
                        if o.get("status") == "ok"), None)
        if not classes:
            return RunOutcome(schedule, PASS, None, last_ok, detail)
        return RunOutcome(schedule, CLASSIFIED, ",".join(classes),
                          last_ok, detail)


def soak_fleet(runs: int, base_seed: int = 0,
               runner: Optional[FleetChaosRunner] = None,
               supervisor=None,
               on_outcome: Optional[Callable[[RunOutcome], None]] = None):
    """N seeded ``fleet.worker_kill`` streams through one
    :class:`FleetChaosRunner`; same return shape as :func:`soak_session`,
    plus the supervisor-side exactly-once accounting (failovers, replays,
    restarts, the final journal audit)."""
    if runner is None:
        if supervisor is None:
            raise ValueError("soak_fleet needs a runner or a supervisor")
        runner = FleetChaosRunner(supervisor)
    outcomes = []
    for i in range(runs):
        out = runner.run(generate_fleet_schedule(base_seed + i,
                                                 runner.queries))
        outcomes.append(out)
        if on_outcome:
            on_outcome(out)
    sup = runner.supervisor
    audit = sup.journal.audit()
    summary = {
        "runs": runs,
        "base_seed": base_seed,
        "queries_per_run": runner.queries,
        "pass": sum(o.status == PASS for o in outcomes),
        "classified": sum(o.status == CLASSIFIED for o in outcomes),
        "violations": sum(o.status == VIOLATION for o in outcomes),
        "failure_classes": sorted({c for o in outcomes if o.failure_class
                                   for c in o.failure_class.split(",")}),
        "failovers": sup.failovers,
        "replays": sup.replays,
        "worker_restarts": sup.restarts,
        "double_exec": audit.double_exec,
        "unacked": audit.unacked,
    }
    return outcomes, summary
