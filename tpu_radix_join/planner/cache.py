"""Warm-start plan cache + multi-host run manifest.

Planning is cheap; the engine's *sizing pre-pass* is not — it is one extra
host-dispatched program per cold join (the ``_run_hist`` capacity
measurement, ~one dispatch floor on chip).  The cache persists, per
(profile, shapes, config) key:

  * the chosen :class:`~tpu_radix_join.planner.plan.JoinPlan`, and
  * the engine's **converged window capacities** (cap_r, cap_s after any
    capacity-overflow retries),

so a warm second run skips both planning and the pre-pass: no JHIST timer,
one CKPTLOAD instead.

Every entry is a :class:`~tpu_radix_join.robustness.checkpoint.
CheckpointManager` file, which buys the discipline for free: atomic
tmp+fsync+rename writes, corruption -> miss (never a crash), and an exact
fingerprint guard — the profile fingerprint is *part of* each entry's
fingerprint, so capacities measured under one set of calibration constants
can never warm-start a run under different ones
(:class:`CheckpointMismatch` is caught and surfaced as a miss + trace
event, and the stale entry is overwritten on the next store).

The key is (profile, shapes, config) — not data content — so a warm
capacity is an *educated guess* for a rerun over different data of the
same shape: the engine's capacity-overflow detect-and-retry loop remains
the correctness backstop, exactly as for a cold mis-sizing.

The **manifest** covers multi-host resume: rank 0 records the rank count
and profile fingerprint next to the cached plans; a later run resuming
against the same directory with a different topology or profile fails
fast with :class:`ManifestMismatch` instead of desynchronizing the SPMD
ranks (every rank must execute the identical program).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional, Tuple

from tpu_radix_join.planner.plan import JoinPlan, PlanError
from tpu_radix_join.planner.profile import DeviceProfile
from tpu_radix_join.robustness.checkpoint import (CheckpointManager,
                                                  CheckpointMismatch)

MANIFEST_NAME = "manifest.json"


class ManifestMismatch(ValueError):
    """Plan-cache directory belongs to a different topology or profile."""


def _canonical(doc: dict) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


class PlanCache:
    """On-disk plan + capacity cache rooted at ``cache_dir``."""

    def __init__(self, cache_dir: str, profile: DeviceProfile,
                 measurements=None):
        self.cache_dir = cache_dir
        self.profile = profile
        self.measurements = measurements
        # in-process hot layer (resident sessions, service/session.py):
        # repeated same-shape queries inside one process resolve from
        # memory — no JSON re-parse, no fingerprint re-check — while the
        # disk entry remains the cross-process/cold-start truth.  Keyed by
        # entry path, so the fingerprint discipline is inherited: a
        # different profile or config hashes to a different path.  Each
        # hot entry carries the (mtime_ns, size) of the disk file it was
        # parsed from; a cheap stat on every hot hit keeps it coherent
        # with external writers (another PlanCache over the same dir,
        # corruption) — an out-of-date hot entry falls back to the disk
        # path and its stale/corrupt handling, never serves stale data.
        self._hot: dict = {}
        os.makedirs(cache_dir, exist_ok=True)

    # ------------------------------------------------------------- keys

    def _key_fields(self, r_tuples: int, s_tuples: int,
                    config_fp: dict) -> dict:
        return {"r_tuples": int(r_tuples), "s_tuples": int(s_tuples),
                "config": config_fp}

    @staticmethod
    def _stat_sig(path: str):
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def _entry(self, key_fields: dict) -> CheckpointManager:
        digest = hashlib.sha256(
            _canonical(key_fields).encode()).hexdigest()[:16]
        path = os.path.join(self.cache_dir, f"plan_{digest}.json")
        fingerprint = {"profile": self.profile.fingerprint(), **key_fields}
        return CheckpointManager(path, fingerprint,
                                 measurements=self.measurements)

    # ------------------------------------------------------------ lookup

    def lookup(self, r_tuples: int, s_tuples: int, config_fp: dict
               ) -> Tuple[Optional[JoinPlan], Optional[dict]]:
        """(plan, capacities) on a hit; (None, None) on a miss.  A
        fingerprint conflict (same shapes, different profile constants) or
        a corrupt entry is a miss, recorded as a trace event — a stale
        entry must degrade to a cold start, never a wrong warm one."""
        entry = self._entry(self._key_fields(r_tuples, s_tuples, config_fp))
        m = self.measurements
        if entry.path in self._hot:
            plan, caps, sig = self._hot[entry.path]
            if sig == self._stat_sig(entry.path):
                if m is not None:
                    m.event("plan_cache_hit", path=entry.path, hot=True,
                            strategy=plan.strategy if plan else None,
                            warm_capacities=caps is not None)
                return plan, caps
            # disk changed underneath us: re-validate the slow way
            del self._hot[entry.path]
        # stat BEFORE the load: if a writer lands between the two, the
        # recorded signature is older than the content and the next hot
        # hit falls back to disk — conservative, never stale
        sig = self._stat_sig(entry.path)
        try:
            state = entry.load()
        except CheckpointMismatch as e:
            if m is not None:
                m.event("plan_cache_stale", path=entry.path, error=str(e))
            return None, None
        if state is None:
            return None, None
        plan = None
        if "plan" in state:
            try:
                plan = JoinPlan.from_dict(state["plan"])
            except (TypeError, PlanError) as e:
                if m is not None:
                    m.event("plan_cache_corrupt", path=entry.path,
                            error=repr(e))
                return None, None
        caps = state.get("capacities")
        self._hot[entry.path] = (plan, caps, sig)
        if m is not None:
            m.event("plan_cache_hit", path=entry.path, hot=False,
                    strategy=plan.strategy if plan else None,
                    warm_capacities=caps is not None)
        return plan, caps

    def store(self, r_tuples: int, s_tuples: int, config_fp: dict,
              plan: Optional[JoinPlan] = None,
              capacities: Optional[dict] = None) -> bool:
        """Persist a plan and/or the engine's converged window capacities
        (the engine stores capacity-only entries when it runs unplanned).
        Overwrites stale entries; save failures degrade to a trace event,
        same as checkpoints."""
        entry = self._entry(self._key_fields(r_tuples, s_tuples, config_fp))
        # merge with the existing entry (a planned run stores the plan
        # first, the engine adds capacities after converging) — read via an
        # uninstrumented manager: CKPTLOAD counts *warm starts*, not the
        # read-modify-write here
        probe = CheckpointManager(entry.path, entry.fingerprint,
                                  measurements=None)
        try:
            state = probe.load() or {}
        except CheckpointMismatch:
            state = {}          # stale entry: overwrite
        state.pop("done", None)
        if plan is not None:
            state["plan"] = plan.to_dict()
        if capacities is not None:
            state["capacities"] = {k: int(v) for k, v in capacities.items()}
        # keep the hot layer coherent with what just hit (or failed to hit)
        # the disk: the merged state is what a fresh lookup would parse
        hot_plan, hot_caps, _ = self._hot.get(entry.path, (None, None, None))
        if plan is not None:
            hot_plan = plan
        if capacities is not None:
            hot_caps = dict(state["capacities"])
        ok = entry.save(state, done=True)
        if ok:
            self._hot[entry.path] = (hot_plan, hot_caps,
                                     self._stat_sig(entry.path))
        else:
            self._hot.pop(entry.path, None)
        return ok

    # ---------------------------------------------------------- manifest

    def manifest_path(self) -> str:
        return os.path.join(self.cache_dir, MANIFEST_NAME)

    def write_manifest(self, num_ranks: int, rank: int = 0) -> bool:
        """Rank 0 stamps the directory with the run topology + profile.
        Non-zero ranks are no-ops — one writer, everyone checks."""
        if rank != 0:
            return True
        mgr = CheckpointManager(
            self.manifest_path(),
            {"kind": "plan_cache_manifest"},
            measurements=None)          # manifest writes don't count CKPTSAVE
        return mgr.save({"num_ranks": int(num_ranks),
                         "profile": self.profile.fingerprint()}, done=True)

    def check_manifest(self, num_ranks: int) -> None:
        """Raise :class:`ManifestMismatch` when this directory was written
        by a different topology or profile; silently pass when no manifest
        exists yet (fresh directory)."""
        path = self.manifest_path()
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, json.JSONDecodeError):
            # corrupt manifest: treat like a fresh dir (entries still carry
            # their own fingerprints, so safety does not depend on it)
            if self.measurements is not None:
                self.measurements.event("manifest_corrupt", path=path)
            return
        saved_ranks = state.get("num_ranks")
        saved_profile = state.get("profile")
        if saved_ranks != int(num_ranks):
            raise ManifestMismatch(
                f"plan cache {self.cache_dir} was written by a "
                f"{saved_ranks}-rank run; this run has {num_ranks} ranks — "
                f"resuming would desynchronize the SPMD program. Use a "
                f"fresh --plan-cache-dir or rerun at the original size.")
        if saved_profile != self.profile.fingerprint():
            raise ManifestMismatch(
                f"plan cache {self.cache_dir} was written under profile "
                f"{(saved_profile or {}).get('name')!r} with different "
                f"constants than {self.profile.name!r} — cached capacities "
                f"are not transferable across calibrations. Use a fresh "
                f"--plan-cache-dir.")
