"""Device profiles: versioned, cited calibration constants.

A profile is a small JSON document holding every hardware constant the cost
model (planner/cost_model.py) consumes.  Two rules keep it honest:

  * **Versioned schema** — ``schema_version`` gates compatibility; loading
    a newer schema than this code understands raises instead of guessing.
  * **Cited constants** — every constant is ``{"value": x, "source": tag}``
    where the tag names the measurement it came from (a PERF_NOTES table,
    a chip artifact path, or a ``calibrate:`` microbenchmark).  A constant
    without a source is rejected at load time, and a tier-1 test walks
    :data:`REQUIRED_CONSTANTS` so the stage model can never silently grow
    an uncited coefficient (tests/test_planner.py).

The checked-in ``profiles/v5e_lite.json`` encodes the committed round-1..3
measurements of the v5e "lite" behind the axon tunnel (PERF_NOTES.md);
:func:`calibrate` refreshes the refreshable subset from on-device
microbenchmarks, and ``tools_make_report.py --emit-profile`` distills a
round's chip artifacts into a profile the same way.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Dict, Optional

# v2 adds ``ici_bytes_per_s`` — the exchange constant the codec-aware
# wire-time term consumes (cost_model.plan_exchange).  v1 profiles load
# through a shim that derives it from the cited ``ici_gbps`` (see
# load_profile), so old files keep working without edits.
# v3 adds per-constant *provenance*: a constant entry may carry a
# ``"provenance"`` dict next to its value/source — fit origin, ledger run
# ids, sample count, 95% confidence interval, fit residual, and a
# freshness timestamp (planner/calibrate.py writes these).  v1/v2 files
# load unchanged (provenance is additive; absent means "committed
# snapshot, citation in the source tag").
# v4 adds ``partition_pass_unit_ms`` — ms per million tuples per streaming
# pass of the fused Pallas radix-partition kernel (ops/pallas/partition.py;
# the kernel makes two passes over the ids and the lanes cross HBM twice).
# v1-v3 profiles load through a shim deriving it from the cited hbm_gbps
# (8 B of ids traffic per tuple per pass at streaming bandwidth).
# v5 adds ``radix_sort_pass_unit_ms`` — ms per million tuples per DIGIT
# pass of the Pallas LSD radix sort's slot kernel
# (ops/pallas/radix_sort.py; per digit pass the kernel streams the key
# lane twice and writes the slot permutation once; the per-lane scatters
# are priced separately from hbm_gbps).  v1-v4 profiles load through a
# shim deriving it as 12/hbm_gbps; calibrate.py re-fits it from
# ``--sort-bench`` ledger rows with provenance.
# v6 adds ``result_cache_lookup_ms`` — the host-side price of one
# fingerprint + LRU probe of the serving result cache
# (service/resultcache.py; the planner's serve_cached strategy row is
# this constant alone).  v1-v5 profiles load through a shim deriving it
# as dispatch_floor_ms / 10 — a pure-host hash lookup is at least an
# order of magnitude under one device round trip.
SCHEMA_VERSION = 6

#: Constants the cost model reads.  Adding a term to cost_model.py means
#: adding its constant here AND to every shipped profile, with a source tag
#: — the conftest-level citation check enforces the pairing.
REQUIRED_CONSTANTS = (
    # XLA sort emitter cost: ms per stage-unit at the 33.5M reference size
    # (stage model: t = unit * (M / 33.5M) * U(M), U = k(k+1)/2)
    "sort_stage_unit_ms",
    # measured penalty of the 2-key lexicographic (full-range) sort vs the
    # packed single-lane sort at equal element count
    "full_range_sort_factor",
    # per-program host dispatch round-trip floor (does not pipeline)
    "dispatch_floor_ms",
    # sustained HBM bandwidth of one elementwise pass (r+w)
    "hbm_gbps",
    # device memory envelope the in-core engine may occupy
    "hbm_bytes",
    # block-scatter loop discipline: sustained M elements/s of the
    # per-destination DMA-slice permutation (the only fast dest-grouping
    # engine; the one-shot gather is the measured ~24x cliff)
    "scatter_loop_melems_s",
    # random-gather rate, the cliff side of the same measurement
    "gather_melems_s",
    # per-chip interconnect bandwidth the all_to_all shuffle rides
    "ici_gbps",
    # the same link expressed in bytes/s — the unit the codec-aware wire
    # time consumes (wire_ms = wire_bytes / ici_bytes_per_s * 1e3, with
    # wire_bytes taken from the packed WireSpec, not a hardcoded 8 B/tuple).
    # Schema v2; v1 profiles are shimmed to ici_gbps * 1e9 at load.
    "ici_bytes_per_s",
    # fused Pallas radix-partition kernel: ms per million tuples per
    # streaming pass (the kernel is two passes over the ids; the cost model
    # charges unit * Mtuples * 2).  Schema v4; v1-v3 profiles are shimmed
    # to 8.0 / hbm_gbps at load (4 B read + 4 B written per tuple per pass
    # at the profile's streaming bandwidth).
    "partition_pass_unit_ms",
    # Pallas LSD radix sort: ms per million tuples per digit pass of the
    # slot kernel (cost_model.radix_sort_ms charges
    # unit * Mtuples * passes + one per-lane scatter pass per digit; the
    # pass count shrinks with the workload's key bound via
    # data/tuples.effective_key_bits).  Schema v5; older profiles are
    # shimmed to 12.0 / hbm_gbps at load (the kernel reads the 4 B key
    # lane in both phases and writes 4 B of slots).  calibrate.py fits it
    # from --sort-bench ledger rows (sort_kernel_ms / passes / Mtuples).
    "radix_sort_pass_unit_ms",
    # serving result cache: ms per fingerprint + LRU probe on the host
    # (service/resultcache.py — sha256 over the canonical request spec
    # plus one OrderedDict move-to-end; no device work at all).  The
    # serve_cached strategy row is this constant alone, which is what
    # makes the planner prefer it over every execution arm.  Schema v6;
    # v1-v5 profiles are shimmed to dispatch_floor_ms / 10 at load.
    "result_cache_lookup_ms",
)

#: Reference element count of the sort stage model's unit (PERF_NOTES
#: round 2: 0.147 ms/stage-unit measured at the 33.5M packed union).
SORT_REF_ELEMS = 33_554_432


class ProfileError(ValueError):
    """Malformed, uncited, or incompatible profile document."""


@dataclasses.dataclass(frozen=True)
class DeviceProfile:
    """Immutable view of one device's calibration constants."""

    name: str
    constants: Dict[str, dict]          # key -> {"value": float, "source": str}
    schema_version: int = SCHEMA_VERSION
    notes: str = ""

    def __post_init__(self):
        if self.schema_version > SCHEMA_VERSION:
            raise ProfileError(
                f"profile {self.name!r} has schema_version "
                f"{self.schema_version}; this build understands "
                f"<= {SCHEMA_VERSION}")
        for key in REQUIRED_CONSTANTS:
            if key not in self.constants:
                raise ProfileError(
                    f"profile {self.name!r} is missing constant {key!r}")
        for key, entry in self.constants.items():
            if (not isinstance(entry, dict) or "value" not in entry
                    or not str(entry.get("source", "")).strip()):
                raise ProfileError(
                    f"profile {self.name!r} constant {key!r} must be "
                    f"{{'value': ..., 'source': <measurement tag>}} — an "
                    f"uncited constant cannot be audited against chip logs")

    def value(self, key: str) -> float:
        try:
            return float(self.constants[key]["value"])
        except KeyError:
            raise ProfileError(
                f"profile {self.name!r} has no constant {key!r}") from None

    def source(self, key: str) -> str:
        return str(self.constants[key]["source"])

    def provenance(self, key: str) -> Optional[dict]:
        """The schema-v3 provenance block of one constant (run ids, sample
        count, CI, residual, freshness), or None for a committed/v1/v2
        entry that carries only its citation string."""
        entry = self.constants.get(key) or {}
        prov = entry.get("provenance")
        return dict(prov) if isinstance(prov, dict) else None

    def freshness(self) -> Optional[float]:
        """Newest ``fitted_at_epoch_s`` across the constants' provenance
        blocks — what ``--profile auto`` compares against its freshness
        window.  None when no constant was ever fitted."""
        stamps = [p["fitted_at_epoch_s"]
                  for p in (self.provenance(k) for k in self.constants)
                  if p and isinstance(p.get("fitted_at_epoch_s"),
                                      (int, float))]
        return max(stamps) if stamps else None

    def fingerprint(self) -> dict:
        """Stable identity for cache keys / multi-host manifests: a plan or
        capacity cached under one profile must never warm-start a run under
        different constants."""
        return {"name": self.name, "schema_version": self.schema_version,
                "constants": {k: self.constants[k]["value"]
                              for k in sorted(self.constants)}}

    def to_dict(self) -> dict:
        return {"schema_version": self.schema_version, "name": self.name,
                "notes": self.notes, "constants": self.constants}

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def replace_constants(self, name: Optional[str] = None,
                          **updates: dict) -> "DeviceProfile":
        """New profile with some constants replaced (each update a full
        ``{"value", "source"}`` entry — recalibration never drops a
        citation)."""
        merged = {**self.constants, **updates}
        return dataclasses.replace(self, name=name or self.name,
                                   constants=merged)


def _profiles_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "profiles")


def load_profile(name_or_path: str = "v5e_lite") -> DeviceProfile:
    """Load a profile by bare name (resolved against the packaged
    ``profiles/`` directory) or by explicit JSON path."""
    path = name_or_path
    if not os.path.exists(path):
        candidate = os.path.join(_profiles_dir(), f"{name_or_path}.json")
        if os.path.exists(candidate):
            path = candidate
        else:
            raise ProfileError(
                f"no profile {name_or_path!r}: not a file, and "
                f"{candidate} does not exist")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ProfileError(f"unreadable profile {path}: {e!r}") from e
    try:
        constants = dict(doc["constants"])
        version = int(doc.get("schema_version", 1))
        if version < 2 and "ici_bytes_per_s" not in constants:
            # schema-v1 shim: the codec-aware wire time (schema v2) reads
            # ici_bytes_per_s; derive it from the v1 profile's cited
            # ici_gbps so old files load unchanged.  The source tag records
            # the derivation, keeping the citation chain auditable.
            entry = constants.get("ici_gbps")
            if isinstance(entry, dict) and "value" in entry:
                constants["ici_bytes_per_s"] = {
                    "value": float(entry["value"]) * 1e9,
                    "source": ("shim:derived from ici_gbps "
                               "(schema v1 profile; "
                               f"{entry.get('source', 'uncited')})")}
        if version < 4 and "partition_pass_unit_ms" not in constants:
            # schema v1-v3 shim: the partition cost term (schema v4) reads
            # partition_pass_unit_ms; derive it from the cited hbm_gbps —
            # one kernel pass streams 4 B of ids in + 4 B of slots out per
            # tuple, so at bandwidth B GB/s a million tuples cost 8e6/B ns
            # = 8/B ms.
            entry = constants.get("hbm_gbps")
            if isinstance(entry, dict) and entry.get("value"):
                constants["partition_pass_unit_ms"] = {
                    "value": round(8.0 / float(entry["value"]), 5),
                    "source": ("shim:derived from hbm_gbps "
                               f"(schema v{version} profile; "
                               f"{entry.get('source', 'uncited')})")}
        if version < 5 and "radix_sort_pass_unit_ms" not in constants:
            # schema v1-v4 shim: the radix-sort cost arm (schema v5) reads
            # radix_sort_pass_unit_ms; derive it from the cited hbm_gbps —
            # per digit pass the slot kernel streams the 4 B key lane in
            # both grid phases and writes 4 B of slots, 12 B/tuple, so a
            # million tuples cost 12/B ms at B GB/s.
            entry = constants.get("hbm_gbps")
            if isinstance(entry, dict) and entry.get("value"):
                constants["radix_sort_pass_unit_ms"] = {
                    "value": round(12.0 / float(entry["value"]), 5),
                    "source": ("shim:derived from hbm_gbps "
                               f"(schema v{version} profile; "
                               f"{entry.get('source', 'uncited')})")}
        if version < 6 and "result_cache_lookup_ms" not in constants:
            # schema v1-v5 shim: the serve_cached strategy row (schema v6)
            # reads result_cache_lookup_ms; derive it from the cited
            # dispatch_floor_ms — a host-side hash probe touches no device,
            # so a tenth of the dispatch round trip is a conservative
            # ceiling (the measured v5e_lite value is far smaller still).
            entry = constants.get("dispatch_floor_ms")
            if isinstance(entry, dict) and entry.get("value"):
                constants["result_cache_lookup_ms"] = {
                    "value": round(float(entry["value"]) / 10.0, 5),
                    "source": ("shim:derived from dispatch_floor_ms "
                               f"(schema v{version} profile; "
                               f"{entry.get('source', 'uncited')})")}
        return DeviceProfile(
            name=doc["name"], constants=constants,
            schema_version=version,
            notes=doc.get("notes", ""))
    except KeyError as e:
        raise ProfileError(f"profile {path} missing field {e}") from e


#: filename the fitter writes next to a ledger; what ``--profile auto``
#: prefers over the committed snapshot while it is fresh
FITTED_PROFILE_BASENAME = "profile_fitted.json"
DEFAULT_PROFILE = "v5e_lite"

#: how old a fitted profile may be before ``auto`` falls back to the
#: committed snapshot (override: TPU_RADIX_PROFILE_FRESH_S)
DEFAULT_FRESH_S = 30 * 86400.0


def resolve_profile(spec: str, ledger_dir: Optional[str] = None,
                    fresh_s: Optional[float] = None) -> str:
    """Resolve the driver's ``--profile`` value.  Anything but ``auto``
    passes through.  ``auto`` prefers ``<ledger_dir>/profile_fitted.json``
    (planner/calibrate.py output) when it loads AND its newest fit is
    within the freshness window; otherwise the committed snapshot.  The
    decision is returned as a loadable name-or-path — callers print it so
    a run's profile choice is never silent."""
    if spec != "auto":
        return spec
    if ledger_dir is None:
        from tpu_radix_join.observability.ledger import default_ledger_dir
        ledger_dir = default_ledger_dir()
    if fresh_s is None:
        fresh_s = float(os.environ.get("TPU_RADIX_PROFILE_FRESH_S",
                                       DEFAULT_FRESH_S))
    candidate = os.path.join(ledger_dir, FITTED_PROFILE_BASENAME)
    if os.path.exists(candidate):
        try:
            fitted_at = load_profile(candidate).freshness()
        except ProfileError:
            return DEFAULT_PROFILE     # an unloadable fit never wins
        if fitted_at is not None and time.time() - fitted_at <= fresh_s:
            return candidate
    return DEFAULT_PROFILE


def format_provenance(profile: DeviceProfile,
                      stale: Optional[dict] = None,
                      now_s: Optional[float] = None) -> str:
    """Per-constant provenance/staleness table — the constants half of the
    ``--plan explain`` output.  ``stale`` is planner/calibrate.py's
    ``detect_stale`` result (or any mapping/iterable of constant names);
    a flagged constant's row says STALE and names the drift that
    indicted it."""
    stale = stale or {}
    now_s = time.time() if now_s is None else now_s
    header = ["constant", "value", "origin", "n", "ci95", "residual",
              "age_h", "stale", "runs"]
    rows = []
    for key in sorted(profile.constants):
        prov = profile.provenance(key) or {}
        origin = (prov.get("origin")
                  or profile.source(key).split(":", 1)[0] or "committed")
        n = prov.get("n")
        ci = prov.get("ci95")
        resid = prov.get("residual")
        ts = prov.get("fitted_at_epoch_s")
        runs = prov.get("runs") or []
        runs_cell = ",".join(str(r) for r in runs)
        if len(runs_cell) > 40:
            runs_cell = runs_cell[:37] + "..."
        cell = ""
        if key in stale:
            info = stale[key] if isinstance(stale, dict) else None
            cell = "STALE"
            if isinstance(info, dict) and info.get("mean_drift_pct"):
                cell += f" ({info['mean_drift_pct']:.0f}% drift)"
        rows.append([
            key, f"{profile.value(key):g}", str(origin),
            str(n) if n else "-",
            (f"[{ci[0]:g}, {ci[1]:g}]"
             if isinstance(ci, (list, tuple)) and len(ci) == 2 else "-"),
            f"{resid:.3f}" if isinstance(resid, (int, float)) else "-",
            (f"{(now_s - ts) / 3600:.1f}"
             if isinstance(ts, (int, float)) else "-"),
            cell, runs_cell])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    fmt = lambda cells: "| " + " | ".join(
        c.ljust(widths[i]) for i, c in enumerate(cells)) + " |"
    lines = [f"profile {profile.name} (schema v{profile.schema_version}) "
             f"constants — provenance/staleness:",
             fmt(header),
             "|" + "|".join("-" * (w + 2) for w in widths) + "|"]
    lines += [fmt(r) for r in rows]
    flagged = [k for k in sorted(profile.constants) if k in stale]
    if flagged:
        lines.append(f"stale: {', '.join(flagged)} — re-fit with "
                     f"tools_profile_fit.py refresh")
    return "\n".join(lines)


def sort_stage_units(elems: int) -> float:
    """U(M) = k(k+1)/2 for k = ceil(log2 M): the XLA sort emitter's
    stage-count term, validated to <1% against the measured flat-sort
    times at 16M/33.5M (PERF_NOTES round 3 'sort floor, quantified')."""
    if elems <= 1:
        return 1.0
    k = math.ceil(math.log2(elems))
    return k * (k + 1) / 2


def calibrate(base: Optional[DeviceProfile] = None,
              name: Optional[str] = None,
              sort_elems: int = 1 << 21) -> DeviceProfile:
    """Refresh the microbenchmark-measurable constants on the current JAX
    backend; constants with no cheap on-device probe (memory envelope when
    the backend hides it) keep the base profile's cited values.

    Methodology matches PERF_NOTES: amortized async dispatches closed by
    one host readback, compile excluded.  Sources are tagged
    ``calibrate:<benchmark>`` so a calibrated profile is distinguishable
    from the committed chip tables at a glance.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    base = base or load_profile()

    def timed(fn, *args, iters=10):
        jax.block_until_ready(fn(*args))          # compile warmup
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])
        return (time.perf_counter() - t0) / iters

    updates = {}
    # HBM envelope: one elementwise pass, read+write
    n = 1 << 22
    x = jnp.arange(n, dtype=jnp.uint32)
    dt = timed(jax.jit(lambda a: a + jnp.uint32(1)), x)
    updates["hbm_gbps"] = {"value": round(2 * 4 * n / dt / 1e9, 2),
                           "source": "calibrate:elementwise_pass"}
    # sort emitter stage unit, normalized to the 33.5M reference size
    keys = jnp.asarray(np.random.default_rng(0).integers(
        0, 1 << 31, sort_elems, dtype=np.uint32))
    dt = timed(jax.jit(lambda a: jax.lax.sort(a, is_stable=False)), keys)
    unit = dt * 1e3 / (sort_elems / SORT_REF_ELEMS) / sort_stage_units(
        sort_elems)
    # the citation RECORDS THE MEASURED IMPL: sort_stage_unit_ms models
    # the XLA sort emitter specifically, and with the ops/sorting switch
    # in play a probe that silently routed through the Pallas radix sort
    # would cross-attribute radix passes to the stage model (and vice
    # versa for a fitted radix_sort_pass_unit_ms).  lax.sort is called
    # directly here — impl pinned, not resolved — and the tag says so.
    updates["sort_stage_unit_ms"] = {
        "value": round(unit, 5),
        "source": "calibrate:flat_sort impl=xla(jax.lax.sort)"}
    # dispatch floor: the trivial-program round trip
    tiny = jnp.zeros((8,), jnp.uint32)
    fn = jax.jit(lambda a: a + jnp.uint32(1))
    jax.block_until_ready(fn(tiny))
    t0 = time.perf_counter()
    for _ in range(20):
        jax.block_until_ready(fn(tiny))
    updates["dispatch_floor_ms"] = {
        "value": round((time.perf_counter() - t0) / 20 * 1e3, 3),
        "source": "calibrate:empty_dispatch"}
    # memory envelope, where the backend reports it
    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    if stats and stats.get("bytes_limit"):
        updates["hbm_bytes"] = {"value": int(stats["bytes_limit"]),
                                "source": "calibrate:memory_stats"}
    return base.replace_constants(
        name=name or f"{base.name}+calibrated", **updates)
