"""Profile auto-calibration: fit REQUIRED_CONSTANTS from ledger evidence.

The committed profile (profiles/v5e_lite.json) is a snapshot of rounds
1..3's hand-reduced chip tables; every run since then has been paying to
re-measure the same constants and throwing the evidence away.  This
module closes the loop: it reads the cross-run telemetry ledger
(observability/ledger.py), extracts per-constant samples from the row
kinds that carry them, robust-fits each constant, and emits a schema-v3
profile whose provenance blocks cite the exact run ids behind every
number — so a fitted constant is *more* auditable than a committed one,
not less.

Per-constant stage models (sample extraction):

  * ``sort_stage_unit_ms`` — bench rows: the single-chip join is
    sort-dominated (PERF_NOTES round 1: ~75% of wall), so the measured
    throughput inverts through the stage model
    ``t = unit * (M / SORT_REF) * U(M)`` at the 2x16M packed union.
  * ``dispatch_floor_ms`` — run rows: the SDISPATCH phase is the
    directly-bracketed dispatch round trip; tiny runs (<= 64K tuples)
    additionally contribute their JTOTAL as an intercept sample, since
    at that size the floor IS the wall time.
  * ``ici_bytes_per_s`` — run rows: WIREBYTES / JMPI is the achieved
    wire rate of the exchange the codec actually shipped.
  * ``partition_pass_unit_ms`` — ``--partition-bench`` rows: the fused
    arm's kernel wall inverts over two passes at the row's element count
    (ops/pallas/partition.py makes exactly two streaming passes).
  * ``radix_sort_pass_unit_ms`` — ``--sort-bench`` rows: the Pallas LSD
    radix arm's slot-kernel wall inverts over the digit passes the row's
    key bound ran (ops/pallas/radix_sort.py skips passes the bound
    proves constant, so the row carries its actual pass count).
  * anything — ``kind="obs"`` rows carry a pre-reduced
    ``{"constant": ..., "value": ...}`` observation (the extension point
    for dedicated probes).

Staleness: a persistently drifting plan (PLANDRIFT, planner/audit.py)
indicts the constant behind its dominant cost term.  ``detect_stale``
attributes each audited run's drift to one constant via the
term->constant map and flags constants whose drift recurs — the signal
``--plan explain`` surfaces and ``tools_profile_fit.py refresh`` acts on.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Tuple

from tpu_radix_join.planner.profile import (SORT_REF_ELEMS, DeviceProfile,
                                            load_profile, sort_stage_units)

#: cost-model term -> the profile constant that prices it
#: (cost_model.py's stage models; ``overlap`` is a negative credit and
#: ``probe``/``sort`` both ride the sort emitter's unit)
TERM_TO_CONSTANT = {
    # the sort term rides plan_sort's chosen arm — xla rows price it by
    # the stage unit, pallas rows by radix_sort_pass_unit_ms; staleness
    # attribution keeps the stage unit as the default blame (the xla arm
    # is the one the committed evidence fitted; a drifting pallas row
    # shows up in --sort-bench refits instead)
    "sort": "sort_stage_unit_ms",
    "probe": "sort_stage_unit_ms",
    "scan": "hbm_gbps",
    "stage": "hbm_gbps",
    "pack": "hbm_gbps",
    "shuffle": "ici_bytes_per_s",
    "dispatch": "dispatch_floor_ms",
    "scatter": "scatter_loop_melems_s",
    # destination grouping under plan_partition: the fused-pallas arm is
    # priced by the partition pass unit; the sort arm folds into the same
    # term (its drift still indicts the partition row in --plan explain)
    "partition": "partition_pass_unit_ms",
}

#: the bench metric whose stage model we can invert for the sort unit
BENCH_SORT_METRIC = "single_chip_join_throughput"

#: --partition-bench A/B rows: the fused arm's wall and element count
#: invert directly to ms per million tuples per pass (the kernel makes
#: two passes, ops/pallas/partition.py)
BENCH_PARTITION_METRIC = "partition_fused_speedup"

#: --sort-bench A/B rows: the Pallas radix arm's kernel wall inverts to
#: ms per million tuples per digit pass (the row carries the pass count
#: its key bound actually ran, so bounded rows fit the same unit)
BENCH_RADIX_SORT_METRIC = "radix_sort_speedup"

#: runs at or below this global size are pure dispatch floor
SMALL_RUN_ELEMS = 1 << 16

#: fits below this sample count are refused (tools_profile_fit exits 2):
#: the committed backfill yields exactly 2 bench rows, and a single
#: sample has no spread to report a CI from
DEFAULT_MIN_SAMPLES = 2

DEFAULT_DRIFT_THRESHOLD_PCT = 25.0
DEFAULT_MIN_PERSIST = 3


class UnderSampledError(ValueError):
    """The ledger holds too few samples to fit anything at the requested
    ``min_samples`` — the caller must gather evidence, not get a profile
    that merely echoes its base."""


@dataclasses.dataclass(frozen=True)
class Sample:
    """One reduced observation of one constant, traceable to its row."""

    value: float
    run_id: str


@dataclasses.dataclass(frozen=True)
class Fit:
    """Robust fit of one constant: the median estimate plus the spread
    evidence the provenance block publishes."""

    value: float
    n: int
    ci95: Tuple[float, float]
    residual: float                     # MAD / |median|, relative spread
    runs: Tuple[str, ...]


# ------------------------------------------------------------ sample extraction
def _sort_unit_from_bench(row: dict) -> Optional[Sample]:
    if row.get("metric") != BENCH_SORT_METRIC:
        return None
    value = float(row.get("value") or 0.0)
    size = int(row.get("size") or 0)
    if value <= 0 or size <= 0:
        return None
    union = 2 * size                    # packed R||S union the sort sees
    t_ms = union / value * 1e3          # measured wall from throughput
    units = (union / SORT_REF_ELEMS) * sort_stage_units(union)
    if units <= 0:
        return None
    return Sample(t_ms / units, str(row.get("run_id", "?")))


def _partition_unit_from_bench(row: dict) -> Optional[Sample]:
    """Invert a --partition-bench row to ms/Mtuple/pass: the fused arm's
    kernel wall over two passes at the row's element count (the bench also
    publishes the reduced ``partition_unit_ms`` tag; recomputing from the
    primary measurement keeps the fit independent of the reduction)."""
    if row.get("metric") != BENCH_PARTITION_METRIC:
        return None
    size = int(row.get("size") or 0)
    kernel_ms = float(row.get("partition_kernel_ms") or 0.0)
    rid = str(row.get("run_id", "?"))
    if size > 0 and kernel_ms > 0:
        return Sample(kernel_ms / (2.0 * size / 1e6), rid)
    unit = float(row.get("partition_unit_ms") or 0.0)
    if unit > 0:
        return Sample(unit, rid)
    return None


def _radix_sort_unit_from_bench(row: dict) -> Optional[Sample]:
    """Invert a --sort-bench row to ms/Mtuple/pass: the Pallas arm's slot
    kernel wall over the digit passes the row's key bound ran (the bench
    also publishes the reduced ``sort_pass_unit_ms`` tag; recomputing
    from the primary measurement keeps the fit independent of the
    reduction)."""
    if row.get("metric") != BENCH_RADIX_SORT_METRIC:
        return None
    size = int(row.get("size") or 0)
    passes = int(row.get("sort_passes") or 0)
    kernel_ms = float(row.get("sort_kernel_ms") or 0.0)
    rid = str(row.get("run_id", "?"))
    if size > 0 and passes > 0 and kernel_ms > 0:
        return Sample(kernel_ms / (passes * size / 1e6), rid)
    unit = float(row.get("sort_pass_unit_ms") or 0.0)
    if unit > 0:
        return Sample(unit, rid)
    return None


def collect_samples(rows: List[dict]) -> Dict[str, List[Sample]]:
    """Constant -> samples, pooled across every row kind that carries
    evidence for it.  Rows that lack a given signal simply contribute
    nothing — a ledger of pure bench rows fits only the sort unit."""
    out: Dict[str, List[Sample]] = {}

    def add(key: str, value: float, run_id) -> None:
        if value > 0 and math.isfinite(value):
            out.setdefault(key, []).append(Sample(value, str(run_id)))

    for row in rows:
        kind = row.get("kind")
        rid = row.get("run_id", "?")
        if kind == "bench":
            s = _sort_unit_from_bench(row)
            if s is not None:
                out.setdefault("sort_stage_unit_ms", []).append(s)
            s = _partition_unit_from_bench(row)
            if s is not None:
                out.setdefault("partition_pass_unit_ms", []).append(s)
            s = _radix_sort_unit_from_bench(row)
            if s is not None:
                out.setdefault("radix_sort_pass_unit_ms", []).append(s)
        elif kind == "run":
            times = row.get("times_us") or {}
            counters = row.get("counters") or {}
            wl = row.get("workload") or {}
            sd_us = float(times.get("SDISPATCH") or 0.0)
            if sd_us > 0:
                add("dispatch_floor_ms", sd_us / 1e3, rid)
            # tiny-run intercept: at <= 64K tuples the whole wall is floor
            jt_us = float(times.get("JTOTAL") or 0.0)
            gsize = int(wl.get("global_size") or 0)
            if jt_us > 0 and 0 < gsize <= SMALL_RUN_ELEMS:
                add("dispatch_floor_ms", jt_us / 1e3, rid)
            wire = float(counters.get("WIREBYTES") or 0.0)
            jmpi_us = float(times.get("JMPI") or 0.0)
            if wire > 0 and jmpi_us > 0:
                add("ici_bytes_per_s", wire / (jmpi_us / 1e6), rid)
        elif kind == "obs":
            key = row.get("constant")
            if isinstance(key, str) and key:
                try:
                    add(key, float(row.get("value")), rid)
                except (TypeError, ValueError):
                    pass
    return out


# ----------------------------------------------------------------- robust fit
def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def robust_fit(samples: List[Sample]) -> Fit:
    """Median estimate with MAD residual and an IQR-based ~95% CI
    (med +/- 1.58 * IQR / sqrt(n), the boxplot-notch approximation) —
    robust to the occasional cold-cache or contended-run outlier that a
    mean fit would chase."""
    if not samples:
        raise UnderSampledError("robust_fit needs at least one sample")
    vals = sorted(s.value for s in samples)
    n = len(vals)
    med = _quantile(vals, 0.5)
    mad = _quantile(sorted(abs(v - med) for v in vals), 0.5)
    iqr = _quantile(vals, 0.75) - _quantile(vals, 0.25)
    half = 1.58 * iqr / math.sqrt(n)
    ci = (min(med - half, med), max(med + half, med))
    residual = mad / abs(med) if med else 0.0
    runs = []
    for s in samples:                   # unique, first-seen order
        if s.run_id not in runs:
            runs.append(s.run_id)
    return Fit(value=med, n=n, ci95=ci, residual=residual,
               runs=tuple(runs))


# ---------------------------------------------------------------- profile fit
def fit_profile(rows: List[dict],
                base: Optional[DeviceProfile] = None,
                name: Optional[str] = None,
                min_samples: int = DEFAULT_MIN_SAMPLES,
                fitted_at: Optional[float] = None,
                ) -> Tuple[DeviceProfile, Dict[str, Fit]]:
    """Fit every constant the ledger has >= ``min_samples`` samples for;
    the rest keep the base profile's cited value.  EVERY constant leaves
    with a provenance block — fitted ones cite their run ids and CI,
    inherited ones say so explicitly (``origin: "committed"``) — so the
    schema-v3 acceptance bar ("provenance on every constant") holds even
    for a sparse ledger.  Raises UnderSampledError when nothing fits."""
    base = base or load_profile()
    fitted_at = time.time() if fitted_at is None else float(fitted_at)
    samples = collect_samples(rows)
    fits: Dict[str, Fit] = {}
    constants: Dict[str, dict] = {}
    for key, entry in base.constants.items():
        pool = samples.get(key) or []
        if len(pool) >= max(1, int(min_samples)):
            fit = robust_fit(pool)
            fits[key] = fit
            constants[key] = {
                "value": fit.value,
                "source": (f"fit:ledger n={fit.n} "
                           f"(was: {entry.get('source', 'uncited')})"),
                "provenance": {
                    "origin": "fit", "runs": list(fit.runs)[:8],
                    "n": fit.n,
                    "ci95": [fit.ci95[0], fit.ci95[1]],
                    "residual": round(fit.residual, 6),
                    "fitted_at_epoch_s": round(fitted_at, 3)},
            }
        else:
            constants[key] = {
                "value": entry["value"],
                "source": entry.get("source", "uncited"),
                "provenance": {"origin": "committed", "runs": [],
                               "n": len(pool)},
            }
    if not fits:
        raise UnderSampledError(
            f"no constant has >= {min_samples} ledger samples "
            f"(sampled: { {k: len(v) for k, v in samples.items()} })")
    prof = DeviceProfile(
        name=name or f"{base.name}+fitted",
        constants=constants,
        notes=(f"fitted from ledger ({sum(f.n for f in fits.values())} "
               f"samples across {len(fits)} constants); unfitted "
               f"constants inherited from {base.name}"))
    return prof, fits


# ------------------------------------------------------------------ staleness
def _dominant_constant(table: dict) -> Optional[str]:
    """The constant behind the audit table's dominant cost term:
    prefer the term with the largest measured-vs-predicted gap (only the
    shuffle term has a measured twin), else the largest predicted term.
    Terms with no priced constant (overlap credit) never attract blame."""
    best_key, best_score = None, -1.0
    for t in table.get("terms") or []:
        key = TERM_TO_CONSTANT.get(t.get("term"))
        if key is None:
            continue
        pred = float(t.get("predicted_ms") or 0.0)
        if pred <= 0:
            continue
        act = t.get("actual_ms")
        score = abs(float(act) - pred) if act is not None else pred
        if score > best_score:
            best_key, best_score = key, score
    return best_key


def detect_stale(rows: List[dict],
                 threshold_pct: float = DEFAULT_DRIFT_THRESHOLD_PCT,
                 min_persist: int = DEFAULT_MIN_PERSIST) -> Dict[str, dict]:
    """Constants whose predicted cost keeps missing the clock: each
    audited run row with ``drift_pct >= threshold_pct`` blames its
    dominant term's constant; a constant blamed ``min_persist`` or more
    times is stale.  Returns ``{constant: {hits, mean_drift_pct, runs}}``
    (only the stale ones — usable directly as format_provenance's
    ``stale`` argument)."""
    blame: Dict[str, dict] = {}
    for row in rows:
        if row.get("kind") != "run":
            continue
        table = row.get("plan_vs_actual")
        if not isinstance(table, dict):
            continue
        drift = table.get("drift_pct")
        if drift is None or float(drift) < threshold_pct:
            continue
        key = _dominant_constant(table)
        if key is None:
            continue
        info = blame.setdefault(key, {"hits": 0, "drifts": [], "runs": []})
        info["hits"] += 1
        info["drifts"].append(float(drift))
        rid = str(row.get("run_id", "?"))
        if rid not in info["runs"]:
            info["runs"].append(rid)
    out: Dict[str, dict] = {}
    for key, info in blame.items():
        if info["hits"] >= max(1, int(min_persist)):
            out[key] = {"hits": info["hits"],
                        "mean_drift_pct": round(
                            sum(info["drifts"]) / len(info["drifts"]), 1),
                        "runs": info["runs"][:8]}
    return out


def diff_profiles(a: DeviceProfile, b: DeviceProfile) -> List[dict]:
    """Per-constant relative deltas between two profiles (b vs a), for
    the fitted-vs-committed diff table tools_profile_fit.py prints."""
    out = []
    for key in sorted(set(a.constants) | set(b.constants)):
        va = a.value(key) if key in a.constants else None
        vb = b.value(key) if key in b.constants else None
        rel = (abs(vb - va) / abs(va)
               if va not in (None, 0) and vb is not None else None)
        out.append({"constant": key, "a": va, "b": vb,
                    "rel_delta": round(rel, 4) if rel is not None else None})
    return out
