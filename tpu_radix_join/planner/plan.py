"""Strategy selection: enumerate, cost, pick, explain.

``plan_join`` turns a (profile, workload) pair into a concrete
:class:`JoinPlan` — the knobs the driver feeds ``JoinConfig`` — plus the
full per-strategy cost table, so ``--plan explain`` can show *why* the
winner won and a misprediction is debuggable against the chip logs
(compare the losing row's terms to the measured phase columns).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

from tpu_radix_join.planner.cost_model import (StrategyCost, Workload,
                                               enumerate_strategies,
                                               network_fanout_bits,
                                               pick_chunk_tuples,
                                               plan_exchange, plan_sort,
                                               wide_sort_factor)
from tpu_radix_join.planner.profile import DeviceProfile

# v2 adds ``grid_pipeline`` (the chunked engine's pipelined/synchronous
# knob); v3 adds ``exchange_codec``/``exchange_stages`` (the bit-packed
# wire codec and staged all_to_all); v4 adds ``predicted_terms`` (the
# winning row's per-term ms breakdown, the predicted half of the
# plan-vs-actual audit — planner/audit.py); v5 adds ``sort_impl`` (the
# sort-engine arm plan_sort priced for the winning row: the Pallas LSD
# radix sort vs the XLA sort emitter).  Older files load with the
# fields' defaults ("auto" pipeline, "off" codec, fused exchange, empty
# term table, "auto" sort).
PLAN_SCHEMA_VERSION = 5


class PlanError(ValueError):
    """No feasible strategy, or a malformed plan file."""


class PlanInfeasibleError(PlanError):
    """The workload cannot fit the armed memory budget — refused at plan
    time with a retry-taxonomy class so callers classify it like every
    other failure (robustness/retry.py), instead of OOMing at dispatch.
    Raised both by the analytic gate (no feasible cost row) and by the
    graftcheck static-memory gate (traced live-set peak exceeds the
    budget: ``static_memory_gate``)."""

    failure_class = "plan_infeasible"   # == robustness.retry.PLAN_INFEASIBLE


@dataclasses.dataclass(frozen=True)
class JoinPlan:
    """The planner's decision, in driver vocabulary.

    ``engine`` routes between the in-core SPMD pipeline (HashJoin) and the
    out-of-core chunked grid (ops/chunked.py).  The remaining fields map
    1:1 onto JoinConfig / CLI flags; ``strategy``/``predicted_ms`` record
    the winning cost row for BENCH artifacts and cache provenance.
    """

    engine: str                       # "incore" | "chunked"
    fused: bool = True                # False -> measure_phases (phase split)
    probe: str = "sort"               # "sort" | "bucket"
    two_level: bool = False
    key_range: str = "auto"           # "narrow" | "full" | "auto"
    network_fanout_bits: int = 5
    local_fanout_bits: int = 5
    chunk_tuples: Optional[int] = None   # chunked engine only
    grid_pipeline: str = "auto"          # chunked engine: "off"|"on"|"auto"
    exchange_codec: str = "off"          # wire codec: "off" | "pack"
    exchange_stages: int = 1             # 1 = fused all_to_all, k>1 staged
    #: the sort-engine arm for the winning row's flat sorts
    #: (cost_model.plan_sort): "pallas" binds the LSD radix kernel,
    #: "xla" the lax.sort emitter, "auto" leaves the per-site runtime
    #: select in charge (strategies whose sorts the 1-D kernel cannot
    #: express anyway — batched bucket sorts, the chunked grid)
    sort_impl: str = "auto"
    pipeline_repeats: bool = False
    strategy: str = ""
    predicted_ms: float = 0.0
    #: the winning StrategyCost row's per-term ms breakdown (sort, scan,
    #: shuffle, ...) — what the plan-vs-actual audit compares measured
    #: phase columns against
    predicted_terms: dict = dataclasses.field(default_factory=dict)
    profile_name: str = ""
    schema_version: int = PLAN_SCHEMA_VERSION

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, doc: dict) -> "JoinPlan":
        doc = dict(doc)
        version = int(doc.get("schema_version", 1))
        if version > PLAN_SCHEMA_VERSION:
            raise PlanError(
                f"plan schema_version {version} is newer than this build "
                f"understands (<= {PLAN_SCHEMA_VERSION})")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(doc) - known
        if unknown:
            raise PlanError(f"unknown plan fields {sorted(unknown)}")
        if doc.get("engine") not in ("incore", "chunked"):
            raise PlanError(f"plan engine must be incore|chunked, "
                            f"got {doc.get('engine')!r}")
        return cls(**doc)

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "JoinPlan":
        try:
            with open(path) as f:
                return cls.from_dict(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            raise PlanError(f"unreadable plan file {path}: {e!r}") from e

    def config_kwargs(self) -> dict:
        """JoinConfig overrides this plan implies (in-core engine only)."""
        return {
            "probe_algorithm": self.probe,
            "two_level": self.two_level,
            "key_range": self.key_range,
            "network_fanout_bits": self.network_fanout_bits,
            "local_fanout_bits": self.local_fanout_bits,
            "measure_phases": not self.fused,
            "exchange_codec": self.exchange_codec,
            "exchange_stages": self.exchange_stages,
            "sort_impl": self.sort_impl,
        }


# network radix bits now live in cost_model.network_fanout_bits so the
# exchange pricing (plan_exchange) derives the wire geometry from the same
# fanout the plan binds
_fanout_bits = network_fanout_bits


def static_memory_gate(workload: Workload) -> int:
    """graftcheck feasibility gate: trace the fused pipeline at this
    workload's geometry (abstract — no arrays, no dispatch) and walk its
    live set.  Returns the machine-wide static peak bytes; raises
    :class:`PlanInfeasibleError` when the workload arms a
    ``memory_budget_bytes`` the peak cannot fit — a *classified* refusal
    at plan time where the analytic ``incore_resident_bytes`` gate (a
    resident-set model) would have admitted the plan and the dispatch
    would have OOMed on the transient live set.

    Lazy-imports ``analysis.jaxpr`` (the planner stays importable
    without tracing) and needs ``workload.num_nodes`` host devices."""
    from tpu_radix_join.analysis.jaxpr.memory import peak_live_bytes
    from tpu_radix_join.analysis.jaxpr.trace import build_entries

    n = max(1, workload.num_nodes)
    per_node = max(8, -(-max(workload.r_tuples, workload.s_tuples) // n))
    cap = max(8, 1 << (-(-per_node // n) - 1).bit_length())
    view = build_entries(num_nodes=n, per_node=per_node, cap=cap,
                         entries=("pipeline",))[0]
    peak = peak_live_bytes(view.jaxpr)
    budget = workload.memory_budget_bytes
    if budget is not None and peak > budget:
        raise PlanInfeasibleError(
            f"static-memory gate: the fused pipeline's traced live-set "
            f"peak is {peak} bytes at {per_node} tuples/node x {n} nodes "
            f"(wire cap {cap}), exceeding the armed memory budget "
            f"{budget} bytes ({peak / max(1, budget):.2f}x) — refusing "
            f"at plan time; shrink the workload, raise the budget, or "
            f"route through the chunked engine")
    return int(peak)


def plan_join(profile: DeviceProfile, workload: Workload,
              static_gate: bool = False
              ) -> Tuple[JoinPlan, List[StrategyCost]]:
    """Pick the cheapest feasible strategy (ties break toward the earlier
    row — fused before split, narrow before full) and bind it to driver
    knobs.

    ``static_gate=True`` additionally runs :func:`static_memory_gate`
    on incore winners when the workload arms a memory budget — the
    jaxpr-derived live-set check on top of the analytic resident-set
    row gate."""
    costs = enumerate_strategies(profile, workload)
    feasible = [c for c in costs if c.feasible]
    if not feasible:
        raise PlanInfeasibleError(
            "no feasible strategy for this workload — every cost row is "
            "infeasible:\n" + explain_table(costs))
    best = min(feasible, key=lambda c: c.cost_ms)
    bits = _fanout_bits(workload)
    xplan = plan_exchange(profile, workload, fanout_bits=bits)
    kw = dict(network_fanout_bits=bits,
              exchange_codec=xplan.codec,
              exchange_stages=xplan.stages,
              pipeline_repeats=workload.repeats > 1,
              strategy=best.strategy, predicted_ms=best.cost_ms,
              predicted_terms={k: round(v, 4)
                               for k, v in best.terms.items()},
              profile_name=profile.name)
    if best.strategy in ("chunked_grid", "chunked_grid_pipelined"):
        # the single-node grid engine never exchanges — keep the plan's
        # codec fields at their inert defaults
        plan = JoinPlan(engine="chunked",
                        chunk_tuples=pick_chunk_tuples(profile, workload),
                        grid_pipeline=("on" if best.strategy.endswith(
                            "_pipelined") else "off"),
                        key_range="auto" if workload.key_bound is None
                        else ("full" if not _narrow(workload) else "narrow"),
                        pipeline_repeats=False,
                        **{k: v for k, v in kw.items()
                           if k not in ("pipeline_repeats", "exchange_codec",
                                        "exchange_stages")})
    elif best.strategy == "incore_fused_twolevel":
        plan = JoinPlan(engine="incore", probe="bucket", two_level=True,
                        key_range="auto", **kw)
    else:
        # incore_{fused,split}_sort_{narrow,full}
        fused = "_fused_" in best.strategy
        narrow = best.strategy.endswith("_narrow")
        key_range = "narrow" if narrow else "full"
        if workload.key_bits == 64:
            key_range = "auto"     # wide keys have no range discipline
        # re-price the winning row's sort with the same geometry
        # enumerate_strategies used, and bind the chosen engine arm so
        # the driver forces it instead of re-deciding per site
        full_factor = (wide_sort_factor(profile) if workload.key_bits == 64
                       else profile.value("full_range_sort_factor"))
        splan = plan_sort(
            profile, workload.union_per_node,
            lanes=(1 if narrow else workload.lanes),
            key_bound=(None if narrow else workload.key_bound),
            key_bits=workload.key_bits,
            lane_factor=(1.0 if narrow else full_factor))
        plan = JoinPlan(engine="incore", fused=fused, key_range=key_range,
                        sort_impl=splan.impl, **kw)
        if not fused:
            # the split cannot pipeline (fence per program)
            plan = dataclasses.replace(plan, pipeline_repeats=False)
    if (static_gate and plan.engine == "incore"
            and workload.memory_budget_bytes is not None):
        static_memory_gate(workload)
    return plan, costs


def _narrow(w: Workload) -> bool:
    from tpu_radix_join.ops.merge_count import MAX_MERGE_KEY
    return (w.key_bits == 32
            and (w.key_bound is None or w.key_bound - 1 <= MAX_MERGE_KEY))


def explain_table(costs: List[StrategyCost],
                  chosen: Optional[JoinPlan] = None,
                  actuals: Optional[dict] = None,
                  static: Optional[dict] = None,
                  critpath: Optional[dict] = None) -> str:
    """Human-readable per-strategy predicted-cost table (the ``--plan
    explain`` payload).  Terms are columns so a reader can line each up
    against the measured phase columns in a chip perf artifact.

    ``actuals`` (a plan-vs-actual audit summary — planner/audit.py
    ``actuals_for_explain``) adds measured ``actual_ms``/``drift%``
    columns, filled on the row of the strategy that actually ran.
    ``static`` (a graftcheck cross-validation summary —
    analysis/jaxpr/crossval.py ``static_for_explain``) adds the
    ``STATIC-DRIFT`` column: jaxpr-derived exchange bytes/tuple vs the
    cost model's ``bytes_per_tuple``, filled on the chosen row — an
    execution-free grounding signal next to the runtime drift.
    ``critpath`` (planner/audit.py ``critpath_for_explain``) adds the
    ``critical_path`` column: the *measured bounding rank's* path length
    — what predicted_ms should be priced against on a skewed mesh, where
    the mean flatters the plan."""
    term_keys: List[str] = []
    for c in costs:
        for k in c.terms:
            if k not in term_keys:
                term_keys.append(k)
    header = (["strategy", "feasible", "predicted_ms"]
              + (["actual_ms", "drift%"] if actuals else [])
              + (["critical_path"] if critpath else [])
              + (["STATIC-DRIFT"] if static else [])
              + [f"{k}_ms" for k in term_keys] + ["note"])
    rows = []
    for c in costs:
        is_chosen = chosen is not None and c.strategy == chosen.strategy
        mark = " *" if is_chosen else ""
        act_cells = []
        if actuals:
            if c.strategy == actuals.get("strategy"):
                a, d = actuals.get("actual_ms"), actuals.get("drift_pct")
                act_cells = [f"{a:.1f}" if a is not None else "-",
                             f"{d:.1f}" if d is not None else "-"]
            else:
                act_cells = ["", ""]
        cp_cells = []
        if critpath:
            b = critpath.get("bound_ms")
            if c.strategy == critpath.get("strategy") and b is not None:
                cp_cells = [f"{b:.1f}@r{critpath.get('bound_rank')}"]
            else:
                cp_cells = [""]
        static_cells = []
        if static:
            sd = static.get("drift_pct")
            static_cells = [f"{sd:+.2f}%" if is_chosen and sd is not None
                            else ""]
        rows.append([c.strategy + mark,
                     "yes" if c.feasible else "NO",
                     f"{c.cost_ms:.1f}" if c.feasible else "-"]
                    + act_cells
                    + cp_cells
                    + static_cells
                    + [f"{c.terms[k]:.1f}" if k in c.terms else ""
                       for k in term_keys]
                    + [c.note])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    fmt = lambda cells: "| " + " | ".join(
        c.ljust(widths[i]) for i, c in enumerate(cells)) + " |"
    lines = [fmt(header),
             "|" + "|".join("-" * (w + 2) for w in widths) + "|"]
    lines += [fmt(r) for r in rows]
    if chosen is not None:
        lines.append(f"chosen: {chosen.strategy} "
                     f"(predicted {chosen.predicted_ms:.1f} ms/join, "
                     f"profile {chosen.profile_name})")
        if chosen.engine == "incore":
            lines.append(
                f"exchange: codec={chosen.exchange_codec} "
                f"stages={chosen.exchange_stages} "
                f"({'fused' if chosen.exchange_stages <= 1 else 'staged'} "
                f"all_to_all)")
            lines.append(
                f"sort: impl={chosen.sort_impl} "
                + {"pallas": "(LSD radix kernel, ops/pallas/radix_sort.py)",
                   "xla": "(lax.sort emitter)"}.get(
                       chosen.sort_impl,
                       "(runtime auto-select per sort site)"))
    if critpath and critpath.get("bound_ms") is not None:
        wf = critpath.get("wait_fraction")
        lines.append(
            f"critical path: {critpath['bound_ms']:.1f} ms bound by "
            f"rank {critpath.get('bound_rank')}"
            + (f" (wait fraction {wf * 100:.1f}%)" if wf is not None
               else "")
            + " — plan terms priced against the bounding rank")
    if static:
        lines.append(
            f"static: jaxpr {static.get('entry', '?')} ships "
            f"{static.get('static_bytes', 0)} B/node over "
            f"{sum(static.get('collectives', {}).values())} collectives "
            f"({static.get('static_bytes_per_tuple', 0.0):.3f} B/tuple "
            f"vs plan {static.get('plan_bytes_per_tuple', 0.0):.3f}; "
            f"drift {static.get('drift_pct', 0.0):+.2f}%)")
    return "\n".join(lines)
