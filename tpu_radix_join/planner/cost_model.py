"""Analytic per-strategy join cost from a calibrated device profile.

The stage model here is the one PERF_NOTES.md carries as prose, validated
against the committed round-1..3 chip measurements:

  * the XLA sort emitter costs ``unit * (M / 33.5M) * U(M)`` stage-units
    (``U = k(k+1)/2``, ``k = ceil(log2 M)``) — predicts the measured flat
    sorts at 16M/33.5M to within a few percent;
  * every non-sort pass is bandwidth-bound at the sustained HBM envelope;
  * each host-dispatched program pays a non-pipelining dispatch floor
    (~100 ms through the tunnel), which is why the fused pipeline beats
    the phase split and why ``--pipeline-repeats`` closes the driver gap;
  * the only fast destination-grouping engine is itself a sort
    (``scatter_to_blocks``' loop discipline), which is why the two-level
    bucket path trails the flat sort champion.

Every coefficient comes from the :class:`~tpu_radix_join.planner.profile.
DeviceProfile` — never a literal here — so the model recalibrates with the
hardware and every term stays citable to a measurement tag.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

from tpu_radix_join.data.tuples import make_wire_spec
from tpu_radix_join.ops.merge_count import MAX_MERGE_KEY
from tpu_radix_join.planner.profile import (DeviceProfile, SORT_REF_ELEMS,
                                            sort_stage_units)

#: Bytes per tuple on the wire / in HBM per lane (uint32 key + uint32 rid;
#: wide keys add a third uint32 lane).
LANE_BYTES = 4

#: Working-set multiplier of the in-core engine over the raw relation
#: bytes: inputs + the packed union + sort double-buffering + shuffle
#: receive windows (allocation slack).  Conservative by design — crossing
#: the budget routes to the chunked grid, whose only cost is time.
INCORE_WORKING_FACTOR = 6.0

#: Program counts per discipline (dispatch-floor multiplier).  The sizing
#: pre-pass is one program (skipped single-node and on plan-cache warm
#: starts); the fused pipeline is one; the phase split runs shuffle+probe
#: (sort path) or shuffle+LP+build+probe (bucket path) separately.
PROGRAMS = {
    "fused": 1,
    "split_sort": 2,
    "split_bucket": 4,
}

#: Pending-readback window of the pipelined grid (ops/chunked.py
#: ``readback_depth`` default): per-pair host round trips batch through it,
#: so the modeled dispatch floor amortizes by the same factor.
GRID_READBACK_DEPTH = 2


@dataclasses.dataclass(frozen=True)
class Workload:
    """What the planner knows before running: global relation sizes, the
    static key bound (``Relation.key_bound()``; None = unknown), mesh
    size, repeat count, and an optional memory-budget override (defaults
    to the profile's HBM envelope)."""

    r_tuples: int
    s_tuples: int
    key_bound: Optional[int] = None      # exclusive upper bound on keys
    key_bits: int = 32
    num_nodes: int = 1
    repeats: int = 1
    memory_budget_bytes: Optional[int] = None

    def budget(self, profile: DeviceProfile) -> float:
        if self.memory_budget_bytes is not None:
            return float(self.memory_budget_bytes)
        return profile.value("hbm_bytes")

    @property
    def lanes(self) -> int:
        """HBM lanes per tuple (key [+ key_hi] + rid)."""
        return 3 if self.key_bits == 64 else 2

    @property
    def union_per_node(self) -> int:
        return max(1, (self.r_tuples + self.s_tuples) // max(
            1, self.num_nodes))


@dataclasses.dataclass(frozen=True)
class StrategyCost:
    """One row of the ``--explain`` table: a strategy, its feasibility,
    the predicted per-join cost, and the per-term breakdown (ms) so a
    misprediction is debuggable against the chip logs term by term."""

    strategy: str
    cost_ms: float
    feasible: bool
    terms: Dict[str, float]
    note: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------- primitives

def sort_ms(profile: DeviceProfile, elems: int, lane_factor: float = 1.0,
            rows: int = 1) -> float:
    """Stage-model cost of sorting ``elems`` total elements, optionally as
    ``rows`` independent batched rows (stage count follows row length —
    the batched-sort discount of the PERF_NOTES round-2 table)."""
    if elems <= 0:
        return 0.0
    row_len = max(2, elems // max(1, rows))
    return (profile.value("sort_stage_unit_ms")
            * (elems / SORT_REF_ELEMS)
            * sort_stage_units(row_len) * lane_factor)


def hbm_pass_ms(profile: DeviceProfile, byts: float) -> float:
    """One read+write streaming pass over ``byts`` bytes."""
    return 2.0 * byts / profile.value("hbm_gbps") / 1e9 * 1e3


def shuffle_ms(profile: DeviceProfile, w: Workload,
               bytes_per_tuple: Optional[float] = None) -> float:
    """all_to_all wire time per chip: each relation ships its non-local
    share (``local * (N-1)/N``) over ICI (PERF_NOTES mesh-scaling model).

    ``bytes_per_tuple`` is the wire footprint per tuple slot under the
    active exchange codec — by default the raw lane width (8 B narrow /
    12 B wide), or a :func:`~tpu_radix_join.data.tuples.make_wire_spec`
    estimate when the bit-packed codec is being priced (plan_exchange).
    """
    n = w.num_nodes
    if n <= 1:
        return 0.0
    if bytes_per_tuple is None:
        bytes_per_tuple = w.lanes * LANE_BYTES
    local = (w.r_tuples + w.s_tuples) / n
    wire_bytes = bytes_per_tuple * local * (n - 1) / n
    return wire_bytes / profile.value("ici_bytes_per_s") * 1e3


def dispatch_ms(profile: DeviceProfile, programs: int) -> float:
    return profile.value("dispatch_floor_ms") * programs


def scatter_loop_ms(profile: DeviceProfile, elems: int) -> float:
    """The block-scatter loop discipline's permutation cost (the second
    radix pass's destination grouping)."""
    return elems / profile.value("scatter_loop_melems_s") / 1e6 * 1e3


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """The cost model's destination-grouping decision: fused Pallas
    partition kernel vs the sort-backed scatter loop, with both arms'
    prices kept for the explain table."""

    impl: str               # "pallas" | "sort"
    partition_ms: float     # the chosen arm
    fused_ms: float         # two streaming kernel passes + the lane scatter
    sort_ms: float          # the scatter-loop (sort-rate-bound) arm
    note: str = ""


def plan_partition(profile: DeviceProfile, elems: int,
                   pallas_ok: Optional[bool] = None) -> PartitionPlan:
    """Price both destination-grouping arms and pick the cheaper available.

    The fused arm is the Pallas radix-partition kernel
    (ops/pallas/partition.py): two streaming passes over the ids at
    ``partition_pass_unit_ms`` each, after which every lane crosses HBM
    once more through the collision-free slot scatter — priced as one
    HBM pass over the lane bytes.  The sort arm is the block-scatter loop
    discipline the engine falls back to (``scatter_loop_melems_s``).
    ``pallas_ok=None`` probes the backend (ops/radix auto-select's own
    rule); tests pass an explicit bool to price either arm portably.
    """
    if pallas_ok is None:
        from tpu_radix_join.ops.pallas.partition import (
            pallas_partition_available)
        pallas_ok = pallas_partition_available()
    fused = (profile.value("partition_pass_unit_ms") * elems / 1e6 * 2.0
             + hbm_pass_ms(profile, elems * 2 * LANE_BYTES))
    sort_arm = scatter_loop_ms(profile, elems)
    if pallas_ok and fused <= sort_arm:
        return PartitionPlan(
            impl="pallas", partition_ms=fused, fused_ms=fused,
            sort_ms=sort_arm,
            note=(f"fused pallas partition {fused:.2f} ms vs "
                  f"{sort_arm:.2f} ms scatter loop"))
    return PartitionPlan(
        impl="sort", partition_ms=sort_arm, fused_ms=fused,
        sort_ms=sort_arm,
        note=("pallas unavailable: scatter loop" if not pallas_ok else
              f"scatter loop {sort_arm:.2f} ms beats fused {fused:.2f} ms"))


def radix_sort_ms(profile: DeviceProfile, elems: int, passes: int,
                  lanes: int = 2) -> float:
    """LSD radix-sort cost (ops/pallas/radix_sort.py): each digit pass
    runs the slot kernel — priced per tuple by ``radix_sort_pass_unit_ms``
    (the key lane streams through both grid phases plus the slot
    writeback) — and then moves every lane across HBM once through the
    collision-free permutation scatter.  Linear in ``passes``, which is
    how the bounded-key pass skip shows up in the plan."""
    if elems <= 0 or passes <= 0:
        return 0.0
    return passes * (profile.value("radix_sort_pass_unit_ms") * elems / 1e6
                     + hbm_pass_ms(profile, elems * lanes * LANE_BYTES))


@dataclasses.dataclass(frozen=True)
class SortPlan:
    """The cost model's sort-engine decision: the Pallas LSD radix sort
    vs the XLA sort emitter, with both arms' prices kept for the explain
    table (mirrors :class:`PartitionPlan` for destination grouping)."""

    impl: str               # "pallas" | "xla"
    sort_ms: float          # the chosen arm
    pallas_ms: float        # bounded LSD digit passes + per-lane scatters
    xla_ms: float           # the stage-model lax.sort arm
    passes: int             # digit passes the radix arm would run
    note: str = ""


def plan_sort(profile: DeviceProfile, elems: int, lanes: int = 2,
              key_bound: Optional[int] = None, key_bits: int = 32,
              lane_factor: float = 1.0, rows: int = 1,
              pallas_ok: Optional[bool] = None) -> SortPlan:
    """Price both sort arms and pick the cheaper available.

    The radix arm's pass count comes from the workload's static key bound
    through the same :func:`~tpu_radix_join.ops.pallas.radix_sort.
    num_radix_passes` rule the kernel itself skips passes by, so a
    16-bit-bounded key is priced at 2 passes, not 4.  Availability and
    the small-sort floor mirror ops/sorting's auto-select
    (``PALLAS_SORT_MIN_ELEMS``) so the plan never binds an arm the
    runtime would refuse; batched (``rows > 1``) sorts are structurally
    xla — the 1-D kernel cannot express them.  ``pallas_ok=None`` probes
    the backend; tests pass an explicit bool to price either arm
    portably."""
    from tpu_radix_join.ops.pallas.radix_sort import num_radix_passes
    from tpu_radix_join.ops.sorting import PALLAS_SORT_MIN_ELEMS
    xla = sort_ms(profile, elems, lane_factor, rows)
    passes = num_radix_passes(key_bound, key_bits)
    pal = radix_sort_ms(profile, elems, passes, lanes)
    if rows > 1:
        return SortPlan(
            impl="xla", sort_ms=xla, pallas_ms=pal, xla_ms=xla,
            passes=passes,
            note=f"batched {rows}-row sort: the radix kernel is 1-D only")
    if pallas_ok is None:
        from tpu_radix_join.ops.sorting import pallas_sort_available
        pallas_ok = pallas_sort_available()
    if not pallas_ok:
        return SortPlan(
            impl="xla", sort_ms=xla, pallas_ms=pal, xla_ms=xla,
            passes=passes, note="pallas unavailable: lax.sort")
    if elems < PALLAS_SORT_MIN_ELEMS:
        return SortPlan(
            impl="xla", sort_ms=xla, pallas_ms=pal, xla_ms=xla,
            passes=passes,
            note=(f"{elems} elems under the {PALLAS_SORT_MIN_ELEMS} "
                  f"pallas sort floor"))
    if pal <= xla:
        return SortPlan(
            impl="pallas", sort_ms=pal, pallas_ms=pal, xla_ms=xla,
            passes=passes,
            note=(f"{passes}-pass radix {pal:.2f} ms vs "
                  f"{xla:.2f} ms lax.sort"))
    return SortPlan(
        impl="xla", sort_ms=xla, pallas_ms=pal, xla_ms=xla, passes=passes,
        note=f"lax.sort {xla:.2f} ms beats {passes}-pass radix {pal:.2f} ms")


def network_fanout_bits(w: Workload) -> int:
    """Network radix bits: at least enough partitions to cover the mesh,
    at most the default 32-way fanout, and never more partitions than
    tuples per node (tiny relations would leave most partitions empty and
    pay histogram width for nothing)."""
    floor_bits = max(0, math.ceil(math.log2(max(1, w.num_nodes))))
    per_node = max(1, w.r_tuples // max(1, w.num_nodes))
    size_cap = max(1, per_node.bit_length() - 3)
    return max(floor_bits, min(5, size_cap))


@dataclasses.dataclass(frozen=True)
class ExchangePlan:
    """The cost model's exchange-layer decision: which wire codec and how
    many staged column groups, with both arms' prices kept for the explain
    table (``wire_off_ms`` is what the raw 8/12 B lanes would have cost)."""

    codec: str              # "off" | "pack"
    stages: int             # 1 = fused all_to_all, k > 1 = staged groups
    bytes_per_tuple: float  # wire footprint per slot under the chosen codec
    wire_ms: float          # shuffle wire time under the chosen codec
    pack_ms: float          # codec compute (pack + unpack passes); 0 if off
    wire_off_ms: float      # the raw-lane arm, for comparison
    note: str = ""


def plan_exchange(profile: DeviceProfile, w: Workload,
                  fanout_bits: Optional[int] = None) -> ExchangePlan:
    """Price both exchange arms and pick the cheaper.

    The packed arm's bytes/tuple comes from the same ``WireSpec`` geometry
    the engine ships (data/tuples.make_wire_spec) — key bits implied by the
    workload's static key bound minus the network fanout bits, rid bits by
    the relation sizes — so the planner and the wire agree on the payload
    width.  Pack compute is two extra streaming passes over the packed
    words (sender pack, receiver unpack), priced at the HBM envelope;
    packing wins exactly when the ICI bytes saved outrun that.

    Packing also wins on *memory*: within half the residency budget of the
    envelope, the smaller live exchange footprint buys headroom the ms
    model cannot see (exchange buffers are part of the in-core working
    set), so under pressure the packed arm is chosen whenever it actually
    shrinks the wire — the decisive shape knob alongside the byte ratio.

    Stages mirror the engine's ``exchange_stages=0`` auto rule: blocks big
    enough to matter (>= 4096 slots) exchange in 4 column groups, bounding
    live exchange memory to ~1/4 at no modeled wire cost (the groups ride
    the same link back to back).
    """
    n = w.num_nodes
    raw_bpt = w.lanes * LANE_BYTES
    if n <= 1:
        return ExchangePlan(codec="off", stages=1, bytes_per_tuple=raw_bpt,
                            wire_ms=0.0, pack_ms=0.0, wire_off_ms=0.0,
                            note="single node: no exchange")
    if fanout_bits is None:
        fanout_bits = network_fanout_bits(w)
    # per-(sender, destination) block capacity estimate — uniform split of
    # the per-node share; only the header amortization depends on it
    cap_est = max(1, w.union_per_node // n)
    spec = make_wire_spec(cap_est, fanout_bits, wide=(w.key_bits == 64),
                          key_bound=w.key_bound,
                          rid_bound=max(w.r_tuples, w.s_tuples))
    wire_off = shuffle_ms(profile, w)
    wire_pack = shuffle_ms(profile, w, spec.bytes_per_tuple)
    local = (w.r_tuples + w.s_tuples) / n
    pack_cost = 2.0 * hbm_pass_ms(profile, spec.bytes_per_tuple * local)
    stages = 4 if cap_est >= 4096 else 1
    cheaper = wire_pack + pack_cost < wire_off
    pressured = (spec.bytes_per_tuple < raw_bpt
                 and incore_resident_bytes(w) > 0.5 * w.budget(profile))
    if cheaper or pressured:
        why = (f"pack {spec.bytes_per_tuple:.2f} B/tuple vs {raw_bpt} B raw"
               + ("" if cheaper else
                  "; chosen for memory headroom near the residency budget"))
        return ExchangePlan(
            codec="pack", stages=stages,
            bytes_per_tuple=spec.bytes_per_tuple, wire_ms=wire_pack,
            pack_ms=pack_cost, wire_off_ms=wire_off, note=why)
    return ExchangePlan(
        codec="off", stages=stages, bytes_per_tuple=raw_bpt,
        wire_ms=wire_off, pack_ms=0.0, wire_off_ms=wire_off,
        note=(f"raw {raw_bpt} B/tuple; pack would cost "
              f"{wire_pack + pack_cost:.2f} ms vs {wire_off:.2f} ms wire"))


def wide_sort_factor(profile: DeviceProfile) -> float:
    """Derived 3-lane (64-bit hi/lo + rid) sort penalty: one extra lane
    costs ``full_range_sort_factor - 1``; the wide path carries two
    (PERF_NOTES round-5: 127 ms key_bits=64 escape vs 48 ms packed)."""
    return 1.0 + 2.0 * (profile.value("full_range_sort_factor") - 1.0)


def incore_resident_bytes(w: Workload) -> float:
    """Modeled per-chip residency of the in-core engine."""
    return (w.union_per_node * w.lanes * LANE_BYTES * INCORE_WORKING_FACTOR)


def pick_chunk_tuples(profile: DeviceProfile, w: Workload) -> int:
    """Largest power-of-two chunk whose grid working set (one inner chunk +
    one outer chunk, sorted) fits the memory budget; clamped to [2^16,
    2^24] (the LD kernels' 128M-tuple chunking downscaled to this chip)."""
    budget = w.budget(profile)
    cap = int(budget / (2 * w.lanes * LANE_BYTES * INCORE_WORKING_FACTOR))
    cap = max(1, cap)
    chunk = 1 << max(16, min(24, cap.bit_length() - 1))
    return chunk


# --------------------------------------------------------------- strategies

def _narrow_feasible(w: Workload) -> Tuple[bool, str]:
    if w.key_bits == 64:
        return False, "64-bit keys always take the wide 3-lane path"
    if w.key_bound is None:
        return True, "key bound unknown; narrow assumed (engine re-checks)"
    if w.key_bound - 1 > MAX_MERGE_KEY:
        return (False, f"max key {w.key_bound - 1:#x} exceeds the 31-bit "
                       f"packing limit {MAX_MERGE_KEY:#x}")
    return True, ""


def enumerate_strategies(profile: DeviceProfile,
                         w: Workload) -> list[StrategyCost]:
    """Cost every discipline combination for this workload.  Order is the
    tie-break preference (first feasible minimum wins in plan_join)."""
    union = w.union_per_node
    union_bytes = union * w.lanes * LANE_BYTES
    narrow_ok, narrow_why = _narrow_feasible(w)
    full_factor = (wide_sort_factor(profile) if w.key_bits == 64
                   else profile.value("full_range_sort_factor"))
    sizing = 0 if w.num_nodes == 1 else 1   # the n==1 sort probe skips it
    fits = incore_resident_bytes(w) <= w.budget(profile)
    mem_note = ("" if fits else
                f"resident ~{incore_resident_bytes(w) / 1e9:.1f} GB exceeds "
                f"the {w.budget(profile) / 1e9:.1f} GB budget")
    # codec-aware exchange: the shuffle term consumes the chosen arm's
    # actual wire bytes/tuple (plan_exchange), not a hardcoded lane width;
    # the packed arm's codec compute shows up as its own "pack" column
    xplan = plan_exchange(profile, w)
    shuf = xplan.wire_ms
    xch = ({"shuffle": shuf, "pack": xplan.pack_ms}
           if xplan.pack_ms > 0 else {"shuffle": shuf})
    scan = hbm_pass_ms(profile, union_bytes)

    def amortized_dispatch(programs: int, pipelinable: bool = True) -> float:
        # pipelined repeats overlap the per-join round trip; the floor is
        # paid once per program per *batch*, not per join (PERF_NOTES
        # "pipelined driver repeats").  The phase split cannot pipeline —
        # its host timers need a fence per program — so it pays per join.
        progs = programs + sizing
        if w.repeats > 1 and pipelinable:
            return dispatch_ms(profile, progs) / w.repeats
        return dispatch_ms(profile, progs)

    rows = []

    def add(name, feasible, terms, note=""):
        rows.append(StrategyCost(
            strategy=name, feasible=feasible,
            cost_ms=round(sum(terms.values()), 3),
            terms={k: round(v, 3) for k, v in terms.items()}, note=note))

    for key_mode, lane_factor, key_ok, key_why in (
            ("narrow", 1.0, narrow_ok, narrow_why),
            ("full", full_factor, True, "")):
        if w.key_bits == 64 and key_mode == "narrow":
            add("incore_fused_sort_narrow", False,
                {"sort": 0.0}, note=narrow_why)
            continue
        # the sort term rides plan_sort's chosen engine arm: the narrow
        # discipline sorts one packed lane whose word mixes key and rid
        # bits (the static key bound no longer bounds it — worst-case
        # passes), the full discipline sorts the raw key lane(s) so the
        # workload's bound skips radix passes
        splan = plan_sort(
            profile, union,
            lanes=(1 if key_mode == "narrow" else w.lanes),
            key_bound=(None if key_mode == "narrow" else w.key_bound),
            key_bits=w.key_bits, lane_factor=lane_factor)
        sort = splan.sort_ms
        sort_note = f"sort arm: {splan.note}"
        add(f"incore_fused_sort_{key_mode}", key_ok and fits,
            {"sort": sort, "scan": scan, **xch,
             "dispatch": amortized_dispatch(PROGRAMS["fused"])},
            note=key_why or mem_note or sort_note)
        add(f"incore_split_sort_{key_mode}", key_ok and fits,
            {"sort": sort, "scan": scan, **xch,
             "dispatch": amortized_dispatch(PROGRAMS["split_sort"],
                                            pipelinable=False)},
            note=(key_why or mem_note
                  or f"{sort_note}; pays one dispatch floor per split "
                     f"program"))

    # two-level bucket discipline: the second radix pass groups tuples by
    # destination bucket — priced by plan_partition as the cheaper of the
    # fused Pallas partition kernel and the sort-rate-bound block-scatter
    # loop (the pre-kernel path) — plus batched per-bucket sorts; always
    # full-range by construction (no packed merge).
    nb = 32                                      # local fanout 5
    pplan = plan_partition(profile, union)
    twolevel = {
        "partition": pplan.partition_ms,
        "sort": sort_ms(profile, union, 1.0, rows=nb),
        "scan": scan,
        **xch,
        "dispatch": amortized_dispatch(PROGRAMS["fused"]),
    }
    add("incore_fused_twolevel", fits, twolevel,
        note=mem_note or f"second radix pass: {pplan.note}")

    # chunked out-of-core grid: every (inner, outer) chunk pair probed
    # once; per-pair cost is a resident-sized sort + scan + one host
    # dispatch (the grid loop is host-driven, no pipelining).
    chunk = pick_chunk_tuples(profile, w)
    pairs = (math.ceil(w.r_tuples / chunk) * math.ceil(w.s_tuples / chunk))
    pair_union = min(2 * chunk, w.r_tuples + w.s_tuples)
    grid = {
        "sort": pairs * sort_ms(profile, pair_union, full_factor),
        "scan": pairs * hbm_pass_ms(profile,
                                    pair_union * w.lanes * LANE_BYTES),
        "dispatch": dispatch_ms(profile, pairs),
    }
    grid_ok = w.num_nodes == 1   # the grid loop is a single-node engine
    add("chunked_grid", grid_ok, grid,
        note="the out-of-core grid runs single-node (ops/chunked.py)"
             if not grid_ok else
             f"chunk={chunk} tuples, {pairs} pair(s); the only discipline "
             f"whose working set is bounded by the slab, not the relation"
             if not fits else f"chunk={chunk} tuples, {pairs} pair(s)")

    # pipelined grid (ops/chunked.py pipeline="on"): sort-reuse collapses
    # the per-pair union sort to one inner-chunk sort per grid ROW (the
    # binary-search probe needs no packing, so no full_factor on 32-bit
    # keys; wide keys keep the per-pair union sort); the prefetch stage
    # hides min(stage, compute) of every pair after the first; deferred
    # readbacks amortize the dispatch floor over the pending window.
    grid_rows = math.ceil(w.r_tuples / chunk)
    outer_chunk = min(chunk, w.s_tuples)
    chunk_bytes = outer_chunk * w.lanes * LANE_BYTES
    stage = hbm_pass_ms(profile, chunk_bytes)       # prefetch copy per pair
    if w.key_bits == 64:
        # wide pairs keep the per-pair union sort (no presorted probe yet)
        sort_pl = pairs * sort_ms(profile, pair_union, full_factor)
        probe = pairs * hbm_pass_ms(profile,
                                    pair_union * w.lanes * LANE_BYTES)
    else:
        # one inner sort per grid ROW (sort-reuse); the binary-search probe
        # is gather-bound — log2(inner) dependent touches per outer key —
        # so it prices like sorting the outer chunk, not like streaming it
        sort_pl = grid_rows * sort_ms(profile, min(chunk, w.r_tuples))
        probe = pairs * sort_ms(profile, outer_chunk)
    pipelined = {
        "sort": sort_pl,
        "probe": probe,
        "stage": pairs * stage,
        "overlap": -max(0, pairs - 1) * min(stage, (sort_pl + probe)
                                            / max(1, pairs)),
        "dispatch": dispatch_ms(profile, pairs)
        / min(max(1, pairs), GRID_READBACK_DEPTH),
    }
    # a 1x1 grid has nothing to overlap or reuse — the engine's pipeline
    # "auto" resolves it to the synchronous loop, so the row mirrors that
    add("chunked_grid_pipelined", grid_ok and pairs > 1, pipelined,
        note="the out-of-core grid runs single-node (ops/chunked.py)"
             if not grid_ok else
             "single chunk pair: nothing to overlap (pipeline auto "
             "resolves to the synchronous loop)" if pairs <= 1 else
             f"chunk={chunk} tuples, {pairs} pair(s); inner sorted once "
             f"per row, prefetch hides min(stage, compute)")
    return rows


# ------------------------------------------------------------ serving tiers

@dataclasses.dataclass(frozen=True)
class ServingContext:
    """What the serving fast paths know about one query beyond the
    workload: how many co-batchable queries share its window, how big its
    incremental delta is, and whether its relation's sorted union is
    already device-resident (service/resident.py)."""

    batch_queries: int = 1       # queries fused into one device program
    delta_tuples: int = 0        # per-query global delta size (0 = full)
    resident: bool = False       # sorted union already lives in HBM


def enumerate_serving_strategies(profile: DeviceProfile, w: Workload,
                                 ctx: ServingContext) -> list[StrategyCost]:
    """Price the serving fast-path tiers against the baseline per-query
    execution (the cheapest feasible :func:`enumerate_strategies` row).

    Kept OUT of :func:`enumerate_strategies` on purpose: plan_join binds
    its winner to driver knobs, and the serving tiers are not driver
    disciplines — they are session-level shortcuts (result cache, fused
    micro-batch, resident delta merge) whose feasibility depends on
    serving state the planner cannot see (cache contents, window
    co-arrivals, residency).  The serve loop and the throughput bench
    consume these rows to sanity-check that each tier's measured win
    matches its modeled one.
    """
    from tpu_radix_join.ops.merge_delta import batch_feasible

    base_rows = [c for c in enumerate_strategies(profile, w) if c.feasible]
    base = (min(base_rows, key=lambda c: c.cost_ms) if base_rows else None)
    base_ms = base.cost_ms if base is not None else float("inf")
    union = w.union_per_node
    union_bytes = union * w.lanes * LANE_BYTES
    rows: list[StrategyCost] = []

    def add(name, feasible, terms, note=""):
        rows.append(StrategyCost(
            strategy=name, feasible=feasible,
            cost_ms=round(sum(terms.values()), 3),
            terms={k: round(v, 3) for k, v in terms.items()}, note=note))

    # tier 0 — result cache: one host-side fingerprint + LRU probe, no
    # device work at all.  Feasible whenever the request is cacheable
    # (non-incremental); whether it HITS is runtime state, not cost.
    add("serve_cached", ctx.delta_tuples == 0,
        {"lookup": profile.value("result_cache_lookup_ms")},
        note=("incremental queries never cache-serve"
              if ctx.delta_tuples else
              f"on hit; a miss falls through to the {base.strategy if base else 'baseline'} "
              f"row at {base_ms:.0f} ms"))

    # tier 1 — fused micro-batch: Q co-batchable queries share ONE sort
    # over the composite (qid<<shift)|key lane and ONE dispatch, so the
    # per-query price divides by Q.  The composite lane is single-width
    # (narrow discipline by construction).
    q = max(1, ctx.batch_queries)
    batch_ok = (q >= 2 and w.key_bound is not None
                and batch_feasible(q, w.key_bound))
    fused_sort = sort_ms(profile, q * union)
    fused_scan = hbm_pass_ms(profile, q * union_bytes)
    add("serve_batched", batch_ok,
        {"sort": fused_sort / q, "scan": fused_scan / q,
         "dispatch": dispatch_ms(profile, 1) / q},
        note=(f"{q} queries, one program: Q dispatch floors become one"
              if batch_ok else
              "needs >= 2 co-batchable queries and a key bound whose "
              "composite (qid<<shift)|key stays below the uint32 sentinel"))

    # tier 2 — resident delta merge: sort only the delta, then two
    # searchsorted passes + one collision-free scatter over the union
    # (~3 streaming passes), then the presorted probe.  O(N+delta) where
    # the baseline re-sorts all N+delta tuples.
    d = ctx.delta_tuples
    delta_ok = ctx.resident and d > 0
    delta_per_node = max(1, d // max(1, w.num_nodes))
    add("serve_delta", delta_ok,
        {"sort_delta": sort_ms(profile, delta_per_node),
         "merge": 3.0 * hbm_pass_ms(profile, union_bytes),
         "probe": hbm_pass_ms(profile, union_bytes),
         "dispatch": dispatch_ms(profile, 1)},
        note=(f"delta/N = {d / max(1, w.r_tuples):.4f}; baseline re-sorts "
              f"the full union" if delta_ok else
              "needs a device-resident sorted union and a non-zero delta"))
    return rows
