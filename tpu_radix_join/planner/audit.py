"""Plan-vs-actual audit: close the loop between cost model and clock.

The planner predicts a per-join cost (JoinPlan.predicted_ms, with the
winning row's per-term breakdown in ``predicted_terms``); the
Measurements registry records what actually happened.  This module
compares the two after every planned join and emits:

  * ``counters["PLANDRIFT"]`` — |actual - predicted| as a percent of the
    prediction (gauge, lower is better, regress-gated via
    tools_check_regress.py) — the continuously-measured calibration
    signal ROADMAP item 2's layout search needs, and the canary for
    stale device profiles;
  * ``meta["plan_vs_actual"]`` — the full audit table (strategy,
    predicted/actual ms, drift, per-term rows with best-effort measured
    twins), which rides into forensics bundles and the ``--plan
    explain`` actuals column;
  * a ``plan_drift`` trace event.

Term-to-tag honesty: only the shuffle term has a 1:1 measured twin
(JMPI) and only under the split discipline; fused strategies run as one
program, so per-term actuals stay None and the headline JTOTAL
comparison carries the signal.  ``times0`` (a pre-join ``times_us``
snapshot) makes the audit delta-based, so accumulated registries
(resident sessions, repeated drivers) audit the *last* join, not the
running total.
"""

from __future__ import annotations

from typing import Dict, Optional

from tpu_radix_join.performance.measurements import (JHIST, JMPI, JPROC,
                                                     JTOTAL, PLANDRIFT,
                                                     SDISPATCH, SWINALLOC)

#: phase tags the audit snapshots/deltas (the measured side of the table)
PHASE_TAGS = (JTOTAL, JHIST, JMPI, JPROC, SWINALLOC, SDISPATCH)

#: cost-model term -> measured tag, where a 1:1 mapping exists.  The
#: local-processing terms (sort/scan/scatter/probe/stage/overlap) all
#: land in JPROC together, so none of them gets an individual twin.
_TERM_TAG = {"shuffle": JMPI}


def phase_snapshot(measurements) -> Dict[str, float]:
    """Pre-join ``times_us`` snapshot for delta-based auditing."""
    return {k: measurements.times_us.get(k, 0.0) for k in PHASE_TAGS}


def audit_plan(plan, measurements, repeats: int = 1,
               times0: Optional[Dict[str, float]] = None,
               critical_path: Optional[dict] = None) -> Optional[dict]:
    """Record the plan-vs-actual table for the join that just ran.

    ``plan`` is a JoinPlan or its dict; ``repeats`` divides the measured
    JTOTAL down to the per-join granularity predicted_ms speaks.
    Returns the table (also stamped into ``meta["plan_vs_actual"]``), or
    None when there is nothing to audit (no JTOTAL recorded — the join
    died before the pipeline started).

    ``critical_path`` (an observability/critpath.py result) re-prices the
    drift against the *measured bounding rank* instead of the local mean:
    the PLANDRIFT gauge the fitter calibrates on then tracks the path
    that actually bounds wall-clock, and the table carries the
    bound-rank terms under ``"critical_path"``."""
    m = measurements
    if m is None or plan is None:
        return None
    pd = plan if isinstance(plan, dict) else plan.to_dict()
    t0 = times0 or {}
    delta_ms = {}
    for tag in PHASE_TAGS:
        cur = m.times_us.get(tag)
        if cur is None and tag not in t0:
            continue
        delta_ms[tag] = ((cur or 0.0) - t0.get(tag, 0.0)) / 1e3
    jt_ms = delta_ms.get(JTOTAL, 0.0)
    if jt_ms <= 0:
        return None
    reps = max(1, int(repeats))
    actual_ms = jt_ms / reps
    predicted_ms = float(pd.get("predicted_ms") or 0.0)
    drift_pct = (round(100.0 * abs(actual_ms - predicted_ms) / predicted_ms,
                       2) if predicted_ms > 0 else None)
    terms = []
    for term, pred in (pd.get("predicted_terms") or {}).items():
        tag = _TERM_TAG.get(term)
        act = (round(delta_ms[tag] / reps, 3)
               if tag is not None and tag in delta_ms else None)
        terms.append({"term": term, "predicted_ms": round(float(pred), 3),
                      "actual_ms": act})
    table = {
        "strategy": pd.get("strategy", ""),
        "engine": pd.get("engine", ""),
        "profile_name": pd.get("profile_name", ""),
        "predicted_ms": round(predicted_ms, 3),
        "actual_ms": round(actual_ms, 3),
        "drift_pct": drift_pct,
        "repeats": reps,
        "terms": terms,
        "measured_ms": {k: round(v / reps, 3) for k, v in delta_ms.items()},
    }
    gauge_drift = drift_pct
    if critical_path and not critical_path.get("error"):
        bound_ms = critical_path.get("path_ms")
        if bound_ms:
            # the cost model predicts steady-state joins; the measured
            # path keeps compile wall (the timeline is honest about it),
            # so the on-path JCOMPILE share comes off before pricing —
            # the same exclude-from-running discipline times_us applies
            compile_ms = float((critical_path.get("phase_ms") or {})
                               .get("JCOMPILE", 0.0))
            bound_ms = round(max(0.0, float(bound_ms) - compile_ms)
                             / reps, 3)
            bound_drift = (round(100.0 * abs(bound_ms - predicted_ms)
                                 / predicted_ms, 2)
                           if predicted_ms > 0 else None)
            table["critical_path"] = {
                "bound_ms": bound_ms,
                "bound_rank": critical_path.get("bounding_rank"),
                "wait_fraction": critical_path.get("wait_fraction"),
                "drift_pct": bound_drift,
            }
            if bound_drift is not None:
                # price the gauge against the measured bounding rank,
                # not the local mean — the path that matters
                gauge_drift = bound_drift
    m.meta["plan_vs_actual"] = table
    if gauge_drift is not None:
        # gauge assignment (each audited join overwrites): the regress
        # gate reads the last join's drift, not an accumulated sum
        m.counters[PLANDRIFT] = int(round(gauge_drift))
        m.flightrec.record("gauge", PLANDRIFT, drift_pct=gauge_drift,
                           strategy=table["strategy"])
    m.event("plan_drift", strategy=table["strategy"],
            predicted_ms=table["predicted_ms"],
            actual_ms=table["actual_ms"], drift_pct=drift_pct)
    return table


def actuals_for_explain(table: Optional[dict]) -> Optional[dict]:
    """Shape an audit table for explain_table's ``actuals`` column:
    {strategy, actual_ms, drift_pct}.  None-safe passthrough."""
    if not table:
        return None
    return {"strategy": table.get("strategy"),
            "actual_ms": table.get("actual_ms"),
            "drift_pct": table.get("drift_pct")}


def critpath_for_explain(table: Optional[dict]) -> Optional[dict]:
    """Shape an audit table's bound-rank terms for explain_table's
    measured-critical-path column: {strategy, bound_ms, bound_rank,
    wait_fraction}.  None-safe passthrough (None when the run had no
    timeline to reconstruct a path from)."""
    if not table or not table.get("critical_path"):
        return None
    cp = table["critical_path"]
    return {"strategy": table.get("strategy"),
            "bound_ms": cp.get("bound_ms"),
            "bound_rank": cp.get("bound_rank"),
            "wait_fraction": cp.get("wait_fraction")}
