"""Adaptive planner: calibrated device profiles, cost-model strategy
selection, and a warm-start plan cache.

The repo implements every execution discipline the reference and the paper
imply — fused vs phase-split programs, flat vs two-level bucket probes,
narrow vs full-range key packing, the in-core engine vs the chunked
out-of-core grid — but until this subsystem the choice among them was
manual: the quantitatively validated stage model (PERF_NOTES.md: sort
floor, ~100 ms/program dispatch floor, block-scatter loop-vs-gather cliffs)
existed only as prose.  Here it lives in code:

  * :mod:`profile`    — versioned per-device calibration constants, seeded
    from the committed round-1..3 chip measurements
    (``profiles/v5e_lite.json``), refreshable on hardware via
    :func:`profile.calibrate`;
  * :mod:`cost_model` — analytic per-strategy cost from those constants;
  * :mod:`plan`       — strategy enumeration -> :class:`plan.JoinPlan` +
    the human-readable ``--explain`` table;
  * :mod:`cache`      — atomic on-disk plan + converged-window-capacity
    cache (the robustness checkpoint fingerprint discipline) so warm
    starts skip both planning and the engine's sizing pre-pass;
  * :mod:`calibrate`  — the self-calibration loop: robust-fits each
    profile constant from cross-run ledger evidence
    (observability/ledger.py), attributes persistent PLANDRIFT to the
    constant behind the drifting cost term, and emits schema-v3 profiles
    whose provenance blocks cite run ids, sample counts, CIs, and
    freshness (``tools_profile_fit.py``, ``--profile auto``).
"""

from tpu_radix_join.planner.audit import (actuals_for_explain, audit_plan,
                                          phase_snapshot)
from tpu_radix_join.planner.cache import PlanCache
from tpu_radix_join.planner.calibrate import (UnderSampledError, detect_stale,
                                              diff_profiles, fit_profile)
from tpu_radix_join.planner.cost_model import (ServingContext, StrategyCost,
                                               Workload,
                                               enumerate_serving_strategies)
from tpu_radix_join.planner.plan import (JoinPlan, PlanError,
                                         PlanInfeasibleError, explain_table,
                                         plan_join, static_memory_gate)
from tpu_radix_join.planner.profile import (DeviceProfile, calibrate,
                                            format_provenance, load_profile,
                                            resolve_profile)

__all__ = [
    "DeviceProfile", "JoinPlan", "PlanCache", "PlanError",
    "PlanInfeasibleError", "ServingContext", "StrategyCost",
    "UnderSampledError", "Workload", "actuals_for_explain", "audit_plan",
    "calibrate", "detect_stale", "diff_profiles",
    "enumerate_serving_strategies", "explain_table",
    "fit_profile", "format_provenance", "load_profile", "phase_snapshot",
    "plan_join", "resolve_profile", "static_memory_gate",
]
