"""Rule ``counter-tag``: every emitted tag has a direction home.

The regress gate (observability/regress.py) only means something for a
tag whose direction it knows: a counter emitted nowhere in the pin
registries is compared under the implicit "unmatched = cost" default,
which is silent — nobody decided it.  This rule cross-checks the two
vocabularies in *both* directions:

  * **emitted but undeclared** — every first argument of a
    ``Measurements`` ``incr``/``start``/``stop``/``add_time_us`` call
    (string literal, or an UPPER_CASE name resolved against the
    measurements-module constant table) must be declared in regress.py:
    exact membership in ``_HIGHER_BETTER`` / ``_COST_TAGS`` /
    ``NEUTRAL_TAGS`` / ``_SKIP``, or matched by a direction substring
    list.  "Explicitly neutral" is a real declaration: NEUTRAL_TAGS
    entries are workload/geometry descriptors with no regression
    direction, and saying so is the decision this rule demands.
  * **declared but dead** — an exact pin whose string appears nowhere
    in the lintable sources outside regress.py suppresses nothing and
    rots; it is flagged so removed tags take their pins with them.

The emitted-tag universe resolves UPPER_CASE names by importing
``performance.measurements`` (the vocabulary's single source of truth);
lower-case names are generic plumbing (``for k in keys: m.stop(k)``)
and are skipped — the loop's *sources* are literal/constant sites this
rule already sees.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tpu_radix_join.analysis.core import Finding, Repo, rule

EMIT_METHODS = {"incr", "start", "stop", "add_time_us"}

#: file holding the pin registries (never scanned for liveness hits)
REGRESS_REL = "tpu_radix_join/observability/regress.py"
#: the vocabulary module: UPPER_CASE str constants define tag names
MEASUREMENTS_REL = "tpu_radix_join/performance/measurements.py"


def _constant_table() -> Dict[str, str]:
    from tpu_radix_join.performance import measurements
    return {name: val for name, val in vars(measurements).items()
            if name.isupper() and isinstance(val, str)}


def _declared_sets():
    from tpu_radix_join.observability import regress
    exact = (set(regress._HIGHER_BETTER) | set(regress._COST_TAGS)
             | set(regress.NEUTRAL_TAGS) | set(regress._SKIP))
    substrings = (tuple(regress._HIGHER_BETTER_SUBSTRINGS)
                  + tuple(regress._LOWER_BETTER_SUBSTRINGS))
    pinned_exact = (set(regress._HIGHER_BETTER) | set(regress._COST_TAGS)
                    | set(regress.NEUTRAL_TAGS))
    return exact, substrings, pinned_exact


def _tag_declared(tag: str, exact, substrings) -> bool:
    t = tag.lower()
    return tag in exact or any(s in t for s in substrings)


def _emitted_tag(node: ast.Call, consts: Dict[str, str]
                 ) -> Optional[Tuple[str, str]]:
    """(tag, spelling) for an emit call, else None."""
    if (not isinstance(node.func, ast.Attribute)
            or node.func.attr not in EMIT_METHODS or not node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, f'"{arg.value}"'
    if isinstance(arg, ast.Name) and arg.id.isupper():
        if arg.id in consts:
            return consts[arg.id], arg.id
        return arg.id, arg.id        # unknown constant: flag under itself
    return None


@rule("counter-tag",
      "emitted Measurements tags must be pinned or explicitly neutral "
      "in regress.py; dead pins are flagged too",
      token="tag")
def check(repo: Repo) -> List[Finding]:
    consts = _constant_table()
    exact, substrings, pinned_exact = _declared_sets()
    out: List[Finding] = []
    emitted = set()
    for src in repo.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _emitted_tag(node, consts)
            if hit is None:
                continue
            tag, spelling = hit
            emitted.add(tag)
            if not _tag_declared(tag, exact, substrings):
                out.append(Finding(
                    rule="counter-tag", path=src.rel, line=node.lineno,
                    key=tag,
                    message=(f"tag {spelling} is emitted here but has no "
                             f"direction declaration in regress.py — add "
                             f"it to _COST_TAGS, _HIGHER_BETTER, or "
                             f"NEUTRAL_TAGS")))
    # reverse direction: exact pins must be live somewhere outside
    # regress.py (substring patterns describe artifact keys and are
    # exempt from the liveness check)
    regress_src = repo.get(REGRESS_REL)
    corpus = [s.source.lower() for s in repo.files if s.rel != REGRESS_REL]
    for tag in sorted(pinned_exact):
        needle = tag.lower()
        if not any(needle in text for text in corpus):
            line = 1
            if regress_src is not None:
                for i, text in enumerate(regress_src.source.splitlines(),
                                         start=1):
                    if f'"{tag}"' in text or f"'{tag}'" in text:
                        line = i
                        break
            out.append(Finding(
                rule="counter-tag", path=REGRESS_REL, line=line, key=tag,
                message=(f"pin for {tag!r} matches nothing in the lintable "
                         f"sources — dead pin; remove it or restore the "
                         f"emitter")))
    return out
