"""Rule ``lock-discipline``: thread-target writes happen under a lock.

Five daemon threads share state with the main thread — the hang
watchdog, the MetricsSampler, the AsyncCheckpointWriter, the grid
prefetcher, and the lease heartbeat (which rides the sampler's tick via
``LeaseBoard.sampler_extra``).  Their informal rule has been "writes
from the thread side hold the instance lock"; this rule makes it
checkable:

  * a **thread-target method** is any method a class passes to
    ``threading.Thread(target=self.<m>)``, plus the closure of
    ``self.<m>()`` calls reachable from it inside the same class, plus
    the :data:`EXTRA_THREAD_METHODS` entries (methods that run on
    *another* class's thread — the lease heartbeat runs on the
    metrics-sampler tick);
  * inside that closure, every ``self.<attr> = ...`` (plain, augmented,
    annotated, or tuple-unpacked) must sit lexically inside a ``with``
    whose context expression names a lock (``lock``/``cond``/``mutex``
    in its spelling — ``with self._lock:``, ``with self._cond:``), or
    carry ``# lint: unguarded-ok(<reason>)``.

The rule is deliberately lexical: it cannot prove a caller holds the
lock for you (use an RLock and re-enter, the metrics.py idiom), and it
does not chase writes through container mutation — rebinding instance
attributes is the race the repo's threads actually share state through.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tpu_radix_join.analysis.core import (Finding, Repo, dotted_name,
                                          is_self_attr, rule)

#: (class, method) pairs that execute on another class's thread: the
#: lease heartbeat is invoked from the MetricsSampler daemon tick (via
#: LeaseBoard.sampler_extra) *and* from the main thread's join loop
EXTRA_THREAD_METHODS = {("LeaseBoard", "heartbeat")}

LOCK_HINTS = ("lock", "cond", "mutex")


def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return name is not None and name.split(".")[-1] == "Thread"


def _thread_targets(cls: ast.ClassDef) -> Set[str]:
    """Method names the class hands to threading.Thread(target=...)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _is_thread_ctor(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = is_self_attr(kw.value)
                    if attr is not None:
                        out.add(attr)
    return out


def _closure(cls: ast.ClassDef, roots: Set[str]) -> Set[str]:
    """Transitive closure of self.<m>() calls from the root methods."""
    methods: Dict[str, ast.FunctionDef] = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen: Set[str] = set()
    frontier = [m for m in roots if m in methods]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        for node in ast.walk(methods[m]):
            if isinstance(node, ast.Call):
                callee = is_self_attr(node.func)
                if callee in methods and callee not in seen:
                    frontier.append(callee)
    return seen


def _locked_with(node: ast.With) -> bool:
    for item in node.items:
        spelled = ast.unparse(item.context_expr).lower()
        if any(h in spelled for h in LOCK_HINTS):
            return True
    return False


class _WriteScan(ast.NodeVisitor):
    """Collect self-attribute writes not lexically under a lock With."""

    def __init__(self):
        self.depth = 0
        self.writes: List[tuple] = []        # (line, attr)

    def visit_With(self, node: ast.With):
        locked = _locked_with(node)
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def _check_target(self, tgt: ast.AST, line: int):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._check_target(e, line)
            return
        attr = is_self_attr(tgt)
        if attr is not None and self.depth == 0:
            self.writes.append((line, attr))

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._check_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_target(node.target, node.lineno)
        self.generic_visit(node)


@rule("lock-discipline",
      "attribute writes in background-thread methods must hold a lock "
      "or carry # lint: unguarded-ok(reason)",
      token="unguarded")
def check(repo: Repo) -> List[Finding]:
    out: List[Finding] = []
    for src in repo.files:
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            roots = _thread_targets(cls)
            roots |= {m for c, m in EXTRA_THREAD_METHODS if c == cls.name}
            if not roots:
                continue
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            for mname in sorted(_closure(cls, roots)):
                scan = _WriteScan()
                scan.visit(methods[mname])
                for line, attr in scan.writes:
                    out.append(Finding(
                        rule="lock-discipline", path=src.rel, line=line,
                        key=f"{cls.name}.{mname}:self.{attr}",
                        message=(f"self.{attr} written in thread-target "
                                 f"method {cls.name}.{mname} without a "
                                 f"held lock — guard it (with "
                                 f"self._lock:) or annotate "
                                 f"unguarded-ok with why it is safe")))
    return out


# ===================================================== rule ``lock-order``
# lock-discipline proves each shared write holds *a* lock; lock-order
# proves the locks themselves cannot deadlock.  The known instance locks
# (MetricsSampler, LeaseBoard, AsyncCheckpointWriter, AdmissionQueue,
# CircuitBreaker — every class a daemon thread or the service path
# shares) form a graph whose edges are "acquired while holding": a
# nested ``with`` inside a lock region, a same-class method call whose
# closure acquires, or a call on a known-class instance attribute
# (``self._board = LeaseBoard(...)`` binds the attribute's class, so
# ``self._board.heartbeat()`` under ``self._lock`` contributes the
# heartbeat's acquisitions).  Any cycle in that graph is a deadlock two
# threads can realize by interleaving — the rule fails on the cycle, not
# on the eventual hang.

#: the instance-lock owners the order graph tracks (plus any class the
#: repo nests acquisitions in — edges are collected everywhere; these
#: names only resolve cross-class calls through instance attributes)
KNOWN_LOCK_CLASSES = ("MetricsSampler", "LeaseBoard",
                      "AsyncCheckpointWriter", "AdmissionQueue",
                      "CircuitBreaker")


def _lock_node(cls_name: str, expr: ast.AST) -> Optional[str]:
    """Canonical graph node for a ``with`` context expression that
    spells a lock, or None.  ``self._lock`` in class C -> ``C._lock``;
    other spellings keep their dotted text (same text == same lock)."""
    spelled = ast.unparse(expr)
    if not any(h in spelled.lower() for h in LOCK_HINTS):
        return None
    attr = is_self_attr(expr)
    if attr is not None:
        return f"{cls_name}.{attr}"
    return spelled


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _known_attr_types(cls: ast.ClassDef) -> Dict[str, str]:
    """self.<attr> = KnownClass(...) bindings anywhere in the class."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = dotted_name(node.value.func)
            if ctor is None:
                continue
            ctor = ctor.split(".")[-1]
            if ctor not in KNOWN_LOCK_CLASSES:
                continue
            for tgt in node.targets:
                attr = is_self_attr(tgt)
                if attr is not None:
                    out[attr] = ctor
    return out


def _acquires(cls_name: str, methods: Dict[str, ast.FunctionDef],
              mname: str, _seen: Optional[Set[str]] = None) -> Set[str]:
    """Locks a method's same-class closure acquires (transitive)."""
    seen = _seen if _seen is not None else set()
    if mname in seen or mname not in methods:
        return set()
    seen.add(mname)
    locks: Set[str] = set()
    for node in ast.walk(methods[mname]):
        if isinstance(node, ast.With):
            for item in node.items:
                ln = _lock_node(cls_name, item.context_expr)
                if ln is not None:
                    locks.add(ln)
        elif isinstance(node, ast.Call):
            callee = is_self_attr(node.func)
            if callee is not None:
                locks |= _acquires(cls_name, methods, callee, seen)
    return locks


class _EdgeScan(ast.NodeVisitor):
    """Collect (held_lock, acquired_lock, line) edges in one method."""

    def __init__(self, cls_name: str, methods: Dict[str, ast.FunctionDef],
                 attr_types: Dict[str, str],
                 foreign: Dict[str, Dict[str, Set[str]]]):
        self.cls = cls_name
        self.methods = methods
        self.attr_types = attr_types
        self.foreign = foreign        # class -> method -> acquired locks
        self.held: List[str] = []
        self.edges: List[Tuple[str, str, int]] = []

    def _add(self, dst: str, line: int):
        for src in self.held:
            if src != dst:
                self.edges.append((src, dst, line))

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            ln = _lock_node(self.cls, item.context_expr)
            if ln is not None:
                self._add(ln, node.lineno)
                acquired.append(ln)
        self.held.extend(acquired)
        self.generic_visit(node)
        del self.held[len(self.held) - len(acquired):len(self.held)]

    def visit_Call(self, node: ast.Call):
        if self.held:
            callee = is_self_attr(node.func)
            if callee is not None:
                # same-class call: its closure's acquisitions nest here
                for dst in _acquires(self.cls, self.methods, callee):
                    self._add(dst, node.lineno)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Attribute)):
                # self.<attr>.<m>() on a known-class instance
                owner = is_self_attr(node.func.value)
                kcls = self.attr_types.get(owner) if owner else None
                if kcls is not None:
                    for dst in self.foreign.get(kcls, {}).get(
                            node.func.attr, set()):
                        self._add(dst, node.lineno)
        self.generic_visit(node)


@rule("lock-order",
      "the acquired-while-holding graph over the known instance locks "
      "must be acyclic (no deadlock order)",
      token="lockorder")
def check_order(repo: Repo) -> List[Finding]:
    # pass 1: per-known-class method acquisition sets (for cross-class
    # call resolution) + per-class attr -> known-class bindings
    foreign: Dict[str, Dict[str, Set[str]]] = {}
    classes: List[Tuple] = []        # (src, cls, methods, attr_types)
    for src in repo.files:
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = _class_methods(cls)
            classes.append((src, cls, methods, _known_attr_types(cls)))
            if cls.name in KNOWN_LOCK_CLASSES:
                foreign[cls.name] = {
                    m: _acquires(cls.name, methods, m) for m in methods}
    # pass 2: the edge list
    edges: List[Tuple[str, str, str, int]] = []    # (src, dst, path, line)
    for src, cls, methods, attr_types in classes:
        for m in methods.values():
            scan = _EdgeScan(cls.name, methods, attr_types, foreign)
            scan.visit(m)
            edges.extend((a, b, src.rel, line) for a, b, line in scan.edges)
    # cycle detection (iterative DFS, three-color)
    adj: Dict[str, List[Tuple[str, str, int]]] = {}
    for a, b, path, line in edges:
        adj.setdefault(a, []).append((b, path, line))
    out: List[Finding] = []
    seen_cycles: Set[str] = set()
    color: Dict[str, int] = {}
    for start in sorted(adj):
        if color.get(start):
            continue
        stack: List[Tuple[str, int]] = [(start, 0)]
        path_nodes: List[str] = []
        while stack:
            node, i = stack.pop()
            if i == 0:
                color[node] = 1
                path_nodes.append(node)
            nexts = adj.get(node, [])
            if i < len(nexts):
                stack.append((node, i + 1))
                dst, fpath, fline = nexts[i]
                if color.get(dst) == 1:
                    cyc = path_nodes[path_nodes.index(dst):] + [dst]
                    canon = "->".join(_canonical_rotation(cyc[:-1]))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(Finding(
                            rule="lock-order", path=fpath, line=fline,
                            key=f"cycle:{canon}",
                            message=(f"lock-order cycle "
                                     f"{' -> '.join(cyc)}: two threads "
                                     f"interleaving these acquisitions "
                                     f"deadlock — acquire in one global "
                                     f"order or drop the outer lock "
                                     f"before the nested acquire")))
                elif not color.get(dst):
                    stack.append((dst, 0))
            else:
                color[node] = 2
                path_nodes.pop()
    return out


def _canonical_rotation(cycle: List[str]) -> List[str]:
    """Rotation starting at the lexicographically smallest node, so the
    same cycle found from different entry points dedups/baselines to
    one key."""
    if not cycle:
        return cycle
    i = cycle.index(min(cycle))
    return cycle[i:] + cycle[:i]
