"""Rule ``lock-discipline``: thread-target writes happen under a lock.

Five daemon threads share state with the main thread — the hang
watchdog, the MetricsSampler, the AsyncCheckpointWriter, the grid
prefetcher, and the lease heartbeat (which rides the sampler's tick via
``LeaseBoard.sampler_extra``).  Their informal rule has been "writes
from the thread side hold the instance lock"; this rule makes it
checkable:

  * a **thread-target method** is any method a class passes to
    ``threading.Thread(target=self.<m>)``, plus the closure of
    ``self.<m>()`` calls reachable from it inside the same class, plus
    the :data:`EXTRA_THREAD_METHODS` entries (methods that run on
    *another* class's thread — the lease heartbeat runs on the
    metrics-sampler tick);
  * inside that closure, every ``self.<attr> = ...`` (plain, augmented,
    annotated, or tuple-unpacked) must sit lexically inside a ``with``
    whose context expression names a lock (``lock``/``cond``/``mutex``
    in its spelling — ``with self._lock:``, ``with self._cond:``), or
    carry ``# lint: unguarded-ok(<reason>)``.

The rule is deliberately lexical: it cannot prove a caller holds the
lock for you (use an RLock and re-enter, the metrics.py idiom), and it
does not chase writes through container mutation — rebinding instance
attributes is the race the repo's threads actually share state through.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from tpu_radix_join.analysis.core import (Finding, Repo, dotted_name,
                                          is_self_attr, rule)

#: (class, method) pairs that execute on another class's thread: the
#: lease heartbeat is invoked from the MetricsSampler daemon tick (via
#: LeaseBoard.sampler_extra) *and* from the main thread's join loop
EXTRA_THREAD_METHODS = {("LeaseBoard", "heartbeat")}

LOCK_HINTS = ("lock", "cond", "mutex")


def _is_thread_ctor(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return name is not None and name.split(".")[-1] == "Thread"


def _thread_targets(cls: ast.ClassDef) -> Set[str]:
    """Method names the class hands to threading.Thread(target=...)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _is_thread_ctor(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = is_self_attr(kw.value)
                    if attr is not None:
                        out.add(attr)
    return out


def _closure(cls: ast.ClassDef, roots: Set[str]) -> Set[str]:
    """Transitive closure of self.<m>() calls from the root methods."""
    methods: Dict[str, ast.FunctionDef] = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen: Set[str] = set()
    frontier = [m for m in roots if m in methods]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        for node in ast.walk(methods[m]):
            if isinstance(node, ast.Call):
                callee = is_self_attr(node.func)
                if callee in methods and callee not in seen:
                    frontier.append(callee)
    return seen


def _locked_with(node: ast.With) -> bool:
    for item in node.items:
        spelled = ast.unparse(item.context_expr).lower()
        if any(h in spelled for h in LOCK_HINTS):
            return True
    return False


class _WriteScan(ast.NodeVisitor):
    """Collect self-attribute writes not lexically under a lock With."""

    def __init__(self):
        self.depth = 0
        self.writes: List[tuple] = []        # (line, attr)

    def visit_With(self, node: ast.With):
        locked = _locked_with(node)
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def _check_target(self, tgt: ast.AST, line: int):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._check_target(e, line)
            return
        attr = is_self_attr(tgt)
        if attr is not None and self.depth == 0:
            self.writes.append((line, attr))

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._check_target(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._check_target(node.target, node.lineno)
        self.generic_visit(node)


@rule("lock-discipline",
      "attribute writes in background-thread methods must hold a lock "
      "or carry # lint: unguarded-ok(reason)",
      token="unguarded")
def check(repo: Repo) -> List[Finding]:
    out: List[Finding] = []
    for src in repo.files:
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            roots = _thread_targets(cls)
            roots |= {m for c, m in EXTRA_THREAD_METHODS if c == cls.name}
            if not roots:
                continue
            methods = {n.name: n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            for mname in sorted(_closure(cls, roots)):
                scan = _WriteScan()
                scan.visit(methods[mname])
                for line, attr in scan.writes:
                    out.append(Finding(
                        rule="lock-discipline", path=src.rel, line=line,
                        key=f"{cls.name}.{mname}:self.{attr}",
                        message=(f"self.{attr} written in thread-target "
                                 f"method {cls.name}.{mname} without a "
                                 f"held lock — guard it (with "
                                 f"self._lock:) or annotate "
                                 f"unguarded-ok with why it is safe")))
    return out
