"""Rule ``sync-point``: no implicit host syncs in the engine hot paths.

Every ``np.asarray(device_array)``, ``int(jnp_scalar)``, or ``.item()``
is a blocking device→host round trip, and the *implicit* spellings are
invisible at review: an accidental one (reading a value the program
never needed on host) reads exactly like a load-bearing one.  The
sanctioned spelling is ``utils.hostsync.host_readback`` — an explicit
``jax.device_get`` that stays legal under
``jax.transfer_guard("disallow")``, so this static rule and the runtime
guard (``main.py --transfer-guard``, the tests' ``transfer_guard``
fixture) witness each other: code the rule passes runs clean under the
guard, and a guard trip points at a spelling the rule missed.

Three checks:

  * ``.item()`` — flagged everywhere in the package (there is no
    host-side use of ``.item()`` in this codebase's idiom);
  * ``int(...)``/``float(...)``/``bool(...)`` whose direct argument is
    an ``np.asarray``/``jnp.*``/``jax.*`` call — a scalar readback that
    blocks on device completion, flagged everywhere in the package;
  * any non-literal ``np.asarray(...)`` inside the **hot files**
    (``ops/chunked.py``, ``operators/hash_join.py`` — the two modules
    that drive device programs mid-join); literal list/tuple arguments
    are host-side array building and stay allowed.

A deliberate implicit sync can carry ``# lint: sync-ok(<reason>)``, but
``host_readback`` is the preferred fix: it is greppable, explicit, and
guard-clean.
"""

from __future__ import annotations

import ast
from typing import List

from tpu_radix_join.analysis.core import Finding, Repo, dotted_name, rule

#: modules that drive device programs mid-join: every np.asarray here is
#: a device readback until proven otherwise
HOT_FILES = {
    "tpu_radix_join/ops/chunked.py",
    "tpu_radix_join/operators/hash_join.py",
}
#: the sanctioned helper's home (np.asarray there IS the implementation)
EXEMPT_FILES = {"tpu_radix_join/utils/hostsync.py"}

SCALAR_CASTS = {"int", "float", "bool"}
DEVICE_ROOTS = {"jnp", "jax"}


def _is_asarray(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in ("np.asarray", "numpy.asarray"))


def _is_device_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name is not None and name.split(".")[0] in DEVICE_ROOTS


def _literal_arg(node: ast.Call) -> bool:
    """A literal list/tuple/constant first argument is host-side array
    building, not a readback — with or without a dtype argument."""
    if not node.args:
        return False
    a = node.args[0]
    return isinstance(a, (ast.List, ast.Tuple, ast.Constant))


@rule("sync-point",
      "implicit host syncs (.item(), int(jnp...), np.asarray in hot "
      "paths) must go through utils.hostsync.host_readback",
      token="sync")
def check(repo: Repo) -> List[Finding]:
    out: List[Finding] = []
    for src in repo.files:
        if src.rel in EXEMPT_FILES:
            continue
        hot = src.rel in HOT_FILES
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                out.append(Finding(
                    rule="sync-point", path=src.rel, line=node.lineno,
                    key=".item()",
                    message=(".item() is an implicit blocking device "
                             "readback — use int(host_readback(...)) "
                             "(utils/hostsync.py)")))
                continue
            fname = dotted_name(node.func)
            if (fname in SCALAR_CASTS and len(node.args) == 1
                    and (_is_asarray(node.args[0])
                         or _is_device_call(node.args[0]))):
                inner = dotted_name(node.args[0].func)
                out.append(Finding(
                    rule="sync-point", path=src.rel, line=node.lineno,
                    key=f"{fname}({inner})",
                    message=(f"{fname}({inner}(...)) is an implicit "
                             f"scalar sync — spell it "
                             f"{fname}(host_readback(...))")))
                continue
            if hot and _is_asarray(node) and not _literal_arg(node):
                out.append(Finding(
                    rule="sync-point", path=src.rel, line=node.lineno,
                    key="np.asarray",
                    message=("np.asarray in an engine hot path is an "
                             "implicit device→host transfer — use "
                             "host_readback (explicit, transfer-guard-"
                             "clean) or annotate sync-ok with a reason")))
    return out
