"""graftlint core: rule registry, repo walker, findings, baseline.

Twelve PRs of conventions — every hot sort through ``ops/sorting.py``,
every counter tag pinned in ``regress.py``, every failure classified,
no implicit host syncs in the engine — live only in docstrings and
review memory.  This package turns each one into an AST rule so the
convention is *enforced* at tier-1 time, not rediscovered in a perf
postmortem.

Mechanics
---------
* A **rule** is a function ``fn(repo) -> [Finding]`` registered with
  :func:`rule`; each carries an id (``sort-bypass``), a one-line doc,
  and an annotation *token*.
* The **walker** (:func:`load_repo`) parses the lintable source set
  once — the ``tpu_radix_join`` package plus the repo-root ``bench.py``
  and ``tools_*.py`` — and hands every rule the same parsed
  :class:`SourceFile` list.  ``tests/`` and ``experiments/`` are out of
  scope by design: fixtures deliberately violate conventions.
* A finding renders as ``path:line:rule-id: message`` and carries a
  stable ``key`` (the offending symbol — a call name, a tag, an
  attribute) so baseline entries survive line drift.
* **Inline waiver**: a line comment ``# lint: <token>-ok(<reason>)``
  suppresses that line's findings for rules declaring ``<token>`` —
  but only with a non-empty reason; a bare ``...-ok()`` suppresses
  nothing.
* **Baseline** (:data:`BASELINE_NAME` at the repo root): committed
  suppressions for findings kept deliberately.  Every entry must carry
  a ``reason``; a reasonless entry is a load error (exit 2 at the CLI),
  and an entry matching no current finding is *stale* — reported
  always, a failure under ``--strict`` (a fixed finding must take its
  suppression with it).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

BASELINE_NAME = "LINT_BASELINE.json"

#: line comment that waives one rule on one line; the reason is mandatory
ANNOTATION_RE = re.compile(r"#\s*lint:\s*([A-Za-z0-9_-]+)-ok\(([^)#]*)\)")


class LintError(Exception):
    """Configuration/IO failure (unreadable file, bad baseline schema):
    the CLI maps this to exit 2, distinct from exit 1 (findings)."""


# --------------------------------------------------------------------- model
@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    key: str           # stable content token for baseline matching
    message: str

    def record(self) -> str:
        return f"{self.path}:{self.line}:{self.rule}"

    def render(self) -> str:
        return f"{self.record()}: {self.message}"


@dataclass
class SourceFile:
    path: str                                   # absolute
    rel: str                                    # repo-relative
    source: str
    tree: ast.Module
    #: line -> [(token, reason)] from ``# lint: token-ok(reason)``
    annotations: Dict[int, List[Tuple[str, str]]] = field(default_factory=dict)

    def waived(self, line: int, token: str) -> bool:
        return any(t == token and r.strip()
                   for t, r in self.annotations.get(line, ()))


@dataclass
class Repo:
    root: str
    files: List[SourceFile]

    def get(self, rel: str) -> Optional[SourceFile]:
        for f in self.files:
            if f.rel == rel:
                return f
        return None


@dataclass(frozen=True)
class Rule:
    id: str
    doc: str
    token: str          # annotation token: ``# lint: <token>-ok(reason)``
    fn: Callable[[Repo], List[Finding]]


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, doc: str, token: str):
    """Register a rule function under ``rule_id``."""
    def deco(fn):
        if rule_id in RULES:
            raise LintError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, doc, token, fn)
        return fn
    return deco


# -------------------------------------------------------------------- walker
def _parse_annotations(source: str) -> Dict[int, List[Tuple[str, str]]]:
    out: Dict[int, List[Tuple[str, str]]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        if "lint:" not in line:
            continue
        for m in ANNOTATION_RE.finditer(line):
            out.setdefault(lineno, []).append((m.group(1), m.group(2)))
    return out


def lintable_paths(root: str) -> List[str]:
    """The default source set: the package, bench.py, and the tools."""
    paths: List[str] = []
    pkg = os.path.join(root, "tpu_radix_join")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if name.endswith(".py"):
                paths.append(os.path.join(dirpath, name))
    for name in sorted(os.listdir(root)):
        if name == "bench.py" or (name.startswith("tools_")
                                  and name.endswith(".py")):
            paths.append(os.path.join(root, name))
    return paths


def load_repo(root: str, paths: Optional[List[str]] = None) -> Repo:
    root = os.path.abspath(root)
    files = []
    for path in (paths if paths is not None else lintable_paths(root)):
        path = os.path.abspath(path)
        try:
            with open(path) as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            raise LintError(f"cannot lint {path}: {e}") from e
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        files.append(SourceFile(path=path, rel=rel, source=source, tree=tree,
                                annotations=_parse_annotations(source)))
    return Repo(root=root, files=files)


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> List[dict]:
    """Validated suppression entries.  Schema: ``{"suppressions": [
    {"rule": ..., "path": ..., "key": ..., "reason": <non-empty>}]}``."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise LintError(f"cannot read baseline {path}: {e}") from e
    except ValueError as e:
        raise LintError(f"baseline {path} is not valid JSON: {e}") from e
    entries = data.get("suppressions")
    if not isinstance(entries, list):
        raise LintError(f"baseline {path} has no 'suppressions' list")
    for i, e in enumerate(entries):
        for k in ("rule", "path", "key", "reason"):
            if not isinstance(e.get(k), str) or not e[k].strip():
                raise LintError(
                    f"baseline {path} entry {i} needs a non-empty {k!r} "
                    f"(every suppression carries a reason)")
        if e["rule"] not in RULES:
            raise LintError(
                f"baseline {path} entry {i} names unknown rule {e['rule']!r}")
    return entries


def apply_baseline(findings: List[Finding], entries: List[dict]):
    """(kept, suppressed, stale_entries): drop findings a suppression
    matches; entries matching nothing are stale."""
    kept, suppressed = [], []
    used = [False] * len(entries)
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if (e["rule"] == f.rule and e["path"] == f.path
                    and e["key"] == f.key):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    stale = [e for i, e in enumerate(entries) if not used[i]]
    return kept, suppressed, stale


# --------------------------------------------------------------------- runner
@dataclass
class LintResult:
    findings: List[Finding]          # live (non-baselined) findings
    suppressed: List[Finding]        # matched by a baseline entry
    stale: List[dict]                # baseline entries matching nothing
    rules: List[str]                 # rule ids that ran

    def exit_code(self, strict: bool = False) -> int:
        """0/1 contract shared with tools_check_regress: findings (or,
        under strict, stale suppressions) fail; exit 2 is reserved for
        LintError at the CLI."""
        if self.findings:
            return 1
        if strict and self.stale:
            return 1
        return 0


def run_lint(root: str, rule_ids: Optional[List[str]] = None,
             baseline_path: Optional[str] = None,
             paths: Optional[List[str]] = None) -> LintResult:
    """Run ``rule_ids`` (default: all registered) over the repo at
    ``root``, applying inline waivers then the baseline."""
    # populate RULES on first use without an import cycle at module load
    from tpu_radix_join.analysis import register_builtin_rules
    register_builtin_rules()
    ids = list(RULES) if rule_ids is None else list(rule_ids)
    unknown = [r for r in ids if r not in RULES]
    if unknown:
        raise LintError(f"unknown rule id(s): {', '.join(unknown)} "
                        f"(known: {', '.join(sorted(RULES))})")
    repo = load_repo(root, paths=paths)
    by_rel = {f.rel: f for f in repo.files}
    findings: List[Finding] = []
    for rid in ids:
        r = RULES[rid]
        for f in r.fn(repo):
            src = by_rel.get(f.path)
            if src is not None and src.waived(f.line, r.token):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    entries: List[dict] = []
    if baseline_path and os.path.exists(baseline_path):
        entries = load_baseline(baseline_path)
    kept, suppressed, stale = apply_baseline(findings, entries)
    # a stale entry for a rule that did not run this invocation is not
    # stale — the finding it suppresses was never looked for
    stale = [e for e in stale if e["rule"] in ids]
    return LintResult(findings=kept, suppressed=suppressed, stale=stale,
                      rules=ids)


# ----------------------------------------------------------------- ast utils
def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.sort`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_self_attr(node: ast.AST) -> Optional[str]:
    """The attribute name when ``node`` is ``self.<attr>``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None
