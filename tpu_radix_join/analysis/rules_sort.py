"""Rule ``sort-bypass``: hot sorts must route through the sort switch.

PR 12 centralised every hot reorder behind ``ops/sorting.py``
(``sort_unstable`` / ``sort_kv_unstable`` / ``sort_lex_unstable``) so
the xla-vs-Pallas radix arm is one trace-time decision; PR 10 did the
same for partitioning.  A direct ``jax.lax.sort`` / ``jnp.sort`` /
``jnp.argsort`` call anywhere else silently bypasses the switch: the
site never sees the Pallas arm, never ticks SORTPASS/SORTFALLBACK, and
the planner's ``plan_sort`` prediction stops matching what traces.

Host-side ``np.sort``/``np.argsort`` are NOT flagged — numpy on host
arrays is the oracle/verification idiom, not a device sort.  The sort
switch's own module and the Pallas kernels are the allowed homes.
"""

from __future__ import annotations

import ast
from typing import List

from tpu_radix_join.analysis.core import (Finding, Repo, dotted_name, rule)

ALLOWED_FILES = ("tpu_radix_join/ops/sorting.py",)
ALLOWED_PREFIXES = ("tpu_radix_join/ops/pallas/",)

#: dotted call spellings that bypass the switch
SORT_CALLS = {
    "jax.lax.sort", "lax.sort",
    "jnp.sort", "jnp.argsort", "jnp.lexsort",
    "jax.numpy.sort", "jax.numpy.argsort", "jax.numpy.lexsort",
}
#: method receivers that mark a *host* array (never flagged)
HOST_ROOTS = {"np", "numpy"}


@rule("sort-bypass",
      "direct lax.sort/jnp.sort/argsort outside ops/sorting.py "
      "bypasses the PR 12 sort switch",
      token="sort")
def check(repo: Repo) -> List[Finding]:
    out: List[Finding] = []
    for src in repo.files:
        if (src.rel in ALLOWED_FILES
                or src.rel.startswith(ALLOWED_PREFIXES)):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in SORT_CALLS:
                out.append(Finding(
                    rule="sort-bypass", path=src.rel, line=node.lineno,
                    key=name,
                    message=(f"direct {name} call bypasses the "
                             f"ops/sorting.py sort switch — use "
                             f"sort_unstable/sort_kv_unstable (or add a "
                             f"baseline entry with a reason)")))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("argsort", "lexsort")):
                # method-call spelling: x.argsort() — flagged unless the
                # receiver is rooted at np/numpy (host oracle arrays);
                # call-chain receivers (np.abs(h).argsort()) root at the
                # innermost callee
                recv = node.func.value
                while isinstance(recv, ast.Call):
                    recv = recv.func
                root = (dotted_name(recv) or "").split(".")[0]
                if root not in HOST_ROOTS:
                    out.append(Finding(
                        rule="sort-bypass", path=src.rel, line=node.lineno,
                        key=f".{node.func.attr}()",
                        message=(f".{node.func.attr}() reorder bypasses "
                                 f"the ops/sorting.py sort switch — use "
                                 f"sort_kv_unstable")))
    return out
