"""Rule ``recompile-hazard``: compile keys and jit sites that churn.

The compile-storm monitor (PR 9, observability/compilemon.py) catches a
recompiling serve session *after* it burns wall time; the hazards it
sees are statically visible:

  * **jit in a loop** — ``jax.jit``/``jax.pmap`` called inside a
    ``for``/``while`` body builds a fresh callable (and cache entry)
    per iteration; the trace cache keys on the new wrapper, so every
    pass recompiles.  Hoist the jit or cache the wrapped callable.
  * **f-string compile keys** — an ``ast.JoinedStr`` inside the key
    tuple passed to the engine's ``_compile_timed`` (or directly among
    a ``jax.jit`` call's arguments) bakes interpolated values — floats,
    object reprs with addresses — into the cache key: unbounded key
    cardinality, one compile per distinct repr.  Keys must be tuples of
    hashable *semantic* values (the fingerprinted-path discipline of
    planner/cache.py, which hashes a canonical JSON dump instead).
  * **dynamic static specs** — ``static_argnums=``/``static_argnames=``
    built from a runtime expression rather than a literal: the spec
    silently varies per construction site, and two sites that look
    identical compile twice.

Deliberate sites (a calibration probe that *measures* compiles) carry
``# lint: recompile-ok(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import List

from tpu_radix_join.analysis.core import Finding, Repo, dotted_name, rule

JIT_CALLS = {"jax.jit", "jax.pmap"}
#: the engine's fingerprinted compile-cache entry point: its key tuples
#: are the compile keys this rule audits
COMPILE_KEY_FUNCS = {"_compile_timed", "self._compile_timed"}
STATIC_KWARGS = {"static_argnums", "static_argnames"}


def _literal_spec(node: ast.AST) -> bool:
    """True for the hashable literal spellings of a static spec."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(isinstance(e, ast.Constant) for e in node.elts)
    return False


def _contains_fstring(node: ast.AST) -> bool:
    return any(isinstance(n, ast.JoinedStr) for n in ast.walk(node))


@rule("recompile-hazard",
      "jit-in-loop, f-string compile keys, and dynamic static_arg "
      "specs cause silent recompile churn",
      token="recompile")
def check(repo: Repo) -> List[Finding]:
    out: List[Finding] = []
    for src in repo.files:
        loop_jits = set()
        for loop in ast.walk(src.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if (isinstance(node, ast.Call)
                        and dotted_name(node.func) in JIT_CALLS
                        and node.lineno not in loop_jits):
                    loop_jits.add(node.lineno)
                    out.append(Finding(
                        rule="recompile-hazard", path=src.rel,
                        line=node.lineno, key="jit-in-loop",
                        message=(f"{dotted_name(node.func)} inside a "
                                 f"loop body retraces every iteration — "
                                 f"hoist the jit out of the loop or "
                                 f"cache the wrapped callable")))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in JIT_CALLS or (name or "").endswith("_compile_timed"):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if _contains_fstring(arg):
                        out.append(Finding(
                            rule="recompile-hazard", path=src.rel,
                            line=node.lineno, key="fstring-compile-key",
                            message=("f-string inside a compile key / "
                                     "jit argument bakes interpolated "
                                     "reprs into the cache key — build "
                                     "keys from hashable semantic "
                                     "values")))
                        break
            if name in JIT_CALLS or (
                    name == "functools.partial" and node.args
                    and dotted_name(node.args[0]) in JIT_CALLS):
                for kw in node.keywords:
                    if (kw.arg in STATIC_KWARGS
                            and not _literal_spec(kw.value)):
                        out.append(Finding(
                            rule="recompile-hazard", path=src.rel,
                            line=node.lineno, key=f"dynamic-{kw.arg}",
                            message=(f"{kw.arg} built from a runtime "
                                     f"expression — use a literal "
                                     f"tuple so the static spec cannot "
                                     f"drift between sites")))
    return out
