"""Plan cross-validation: jaxpr-derived traffic vs cost-model prices.

The planner prices the exchange analytically (``plan_exchange`` →
``ExchangePlan.bytes_per_tuple`` and the shuffle wire model); the
traced program states what it *actually* moves — the summed operand
bytes of its ``all_to_all`` equations.  This module diffs the two:

* :func:`static_exchange_bytes` — per-node shipped bytes read off the
  jaxpr.  The engine's ``_exchange_stats`` defines WIREBYTES as "bytes
  each node ships", which on the traced program is exactly the sum of
  per-device all_to_all operand bytes (shard_map body avals are
  per-device) — no mesh multiplication, so the 10%% A/B in
  tests/test_jaxpr_audit.py compares like with like.
* :func:`static_for_explain` — the ``STATIC-DRIFT`` column: traced
  bytes-per-slot vs the plan's ``bytes_per_tuple``.  The per-slot basis
  makes the comparison capacity-free — pow-of-two wire-cap slack
  inflates both arms identically and cancels, so persistent drift means
  the *codec/geometry model* is wrong (a second, execution-free
  grounding signal next to PR 9's runtime staleness), not that the
  workload was padded.
"""

from __future__ import annotations

from typing import Dict, Optional

#: collectives counted for the per-phase account
_COUNTED = ("all_to_all", "psum", "pmin", "pmax", "ppermute", "all_gather",
            "reduce_scatter")


def static_exchange_bytes(view) -> int:
    """Per-node shipped bytes: summed all_to_all operand bytes (the
    traced program's own WIREBYTES)."""
    return sum(e.in_bytes() for e in view.eqns if e.prim == "all_to_all")


def collective_counts(view) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for e in view.eqns:
        if e.prim in _COUNTED:
            counts[e.prim] = counts.get(e.prim, 0) + 1
    return counts


def static_slots(view) -> int:
    """Wire slots per node shipped by the all_to_all equations, in
    uint32 lanes: operand elements / 1 lane each (key+rid = 2 lanes =
    8 bytes/tuple raw)."""
    slots = 0
    for e in view.eqns:
        if e.prim == "all_to_all":
            for v in e.invals:
                n = 1
                for d in v.shape:
                    n *= d
                slots += n
    return slots


def static_for_explain(view, xplan) -> Optional[dict]:
    """STATIC-DRIFT payload for ``explain_table``.

    ``view`` is the traced shuffle/pipeline entry; ``xplan`` the chosen
    strategy's ``ExchangePlan``.  Returns None when either side has no
    wire traffic to compare (e.g. single-node)."""
    bytes_moved = static_exchange_bytes(view)
    lanes = static_slots(view)
    bpt = float(getattr(xplan, "bytes_per_tuple", 0.0) or 0.0)
    if bytes_moved <= 0 or lanes <= 0 or bpt <= 0.0:
        return None
    # 2 uint32 lanes per tuple slot (key + rid); codec stages repack the
    # same tuple basis, so static bytes/tuple-slot is comparable to the
    # plan's bytes_per_tuple on every codec arm.
    tuple_slots = lanes / 2.0
    static_bpt = bytes_moved / tuple_slots
    drift_pct = 100.0 * (static_bpt - bpt) / bpt
    return {
        "entry": view.name,
        "static_bytes": int(bytes_moved),
        "static_bytes_per_tuple": static_bpt,
        "plan_bytes_per_tuple": bpt,
        "drift_pct": drift_pct,
        "collectives": collective_counts(view),
    }
