"""graftcheck: jaxpr-level static analysis for tpu_radix_join.

graftlint (the parent package) checks the *source text*; graftcheck
checks the *lowered program*.  The framework lives in :mod:`core`
(AvalView/EqnView/ProgramView, IR rule registry, baseline, runner), the
tracer/entry registry in :mod:`trace`, and the rules in:

  =================  ===================================================
  transfer           no implicit device_put / host callback in hot jits
  collective-axis    collectives name live mesh axes, sizes consistent,
                     all_to_all splits divide evenly
  width              uint32 lanes must not widen to i64/f64/f32
  donation           dead-after-use inputs carry donate_argnums
  static-memory      live-set peak fits the armed memory budget
  =================  ===================================================

Plan cross-validation (:mod:`crossval`) diffs jaxpr-derived exchange
bytes against the cost model — the ``STATIC-DRIFT`` column in ``--plan
explain``.  CLI: ``tools_jaxpr_audit.py`` at the repo root; tier-1
gate: ``tests/test_static_gate.py``.
"""

from tpu_radix_join.analysis.jaxpr.core import (AuditContext, AuditResult,
                                                AvalView, EqnView,
                                                IR_RULES, IRRule,
                                                JXAUDIT_BASELINE,
                                                ProgramView, ir_rule,
                                                load_ir_baseline, run_audit)

_REGISTERED = False


def register_ir_rules() -> None:
    """Import the rule modules (idempotent): importing registers."""
    global _REGISTERED
    if _REGISTERED:
        return
    from tpu_radix_join.analysis.jaxpr import (memory,      # noqa: F401
                                               rules_ir)    # noqa: F401
    _REGISTERED = True


__all__ = ["AuditContext", "AuditResult", "AvalView", "EqnView", "IR_RULES",
           "IRRule", "JXAUDIT_BASELINE", "ProgramView", "ir_rule",
           "load_ir_baseline", "run_audit", "register_ir_rules"]
