"""Static peak-memory estimate: per-equation live-set walk + the rule.

``peak_live_bytes`` walks a (Closed)Jaxpr in program order keeping a
variable-level live set: an input is live from entry until its last
textual use, an equation's outputs go live when it executes, and the
peak is sampled after each equation before dead operands retire.  The
walk recurses through nested jaxprs (pjit / scan / cond bodies) and
multiplies shard_map bodies by the mesh size — body avals are
per-device, the budget is machine-wide.  It is an *estimate*, not XLA's
allocator: no rematerialization, no buffer aliasing beyond donation,
no fusion — i.e. a slight over-count, which is the right direction for
a feasibility gate (refusing at plan time beats OOMing at dispatch).

The ``static-memory`` rule records the peak into the entry's stats
unconditionally and files a finding only when
``ctx.memory_budget_bytes`` is armed and exceeded; the planner's
feasibility gate (``planner.plan.static_memory_gate``) consumes the
same walk to refuse infeasible strategies with a classified
``PlanInfeasibleError``.
"""

from __future__ import annotations

from typing import List

from tpu_radix_join.analysis.core import Finding
from tpu_radix_join.analysis.jaxpr.core import (AuditContext, AvalView,
                                                ProgramView, ir_rule)


def _aval_bytes(var) -> int:
    return AvalView.of(var.aval).bytes


def _mesh_size(params: dict) -> int:
    mesh = params.get("mesh")
    if mesh is None:
        return 1
    try:
        size = 1
        for v in dict(mesh.shape).values():
            size *= int(v)
        return max(1, size)
    except Exception:       # noqa: BLE001 — AbstractMesh variants differ
        return 1


def _sub_jaxprs_scaled(params: dict):
    """(open_jaxpr, scale_multiplier) pairs for nested bodies: shard_map
    bodies hold per-device avals, so their contribution scales by the
    mesh size; pjit/scan/cond bodies are already in the parent basis."""
    mult = _mesh_size(params) if "jaxpr" in params and "mesh" in params \
        else 1
    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            # ClosedJaxpr also exposes .eqns — unwrap it first
            if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"),
                                               "eqns"):
                yield v.jaxpr, mult
            elif hasattr(v, "eqns"):
                yield v, mult


def _walk(jaxpr, scale: int) -> int:
    """Peak live bytes of one open jaxpr at ``scale`` bytes-per-aval
    multiplier, recursing into nested bodies."""
    # last textual use index per var (invars count as use -1 if unused)
    last_use = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if hasattr(v, "aval"):          # skip Literals
                last_use[id(v)] = idx
    for v in jaxpr.outvars:
        if hasattr(v, "aval"):
            last_use[id(v)] = len(jaxpr.eqns)
    live = sum(_aval_bytes(v) * scale
               for v in list(jaxpr.invars) + list(jaxpr.constvars))
    tracked = {id(v): _aval_bytes(v) * scale
               for v in list(jaxpr.invars) + list(jaxpr.constvars)}
    peak = live
    for idx, eqn in enumerate(jaxpr.eqns):
        out_bytes = 0
        for v in eqn.outvars:
            if hasattr(v, "aval") and id(v) not in tracked:
                b = _aval_bytes(v) * scale
                tracked[id(v)] = b
                out_bytes += b
        live += out_bytes
        # transient of a nested body: its own peak minus the operands the
        # parent already counts (approximated by the nested walk's full
        # peak — an over-count, acceptable for a refusal gate)
        nested = 0
        for sub, mult in _sub_jaxprs_scaled(dict(eqn.params)):
            nested = max(nested, _walk(sub, scale * mult))
        peak = max(peak, live + max(0, nested - out_bytes))
        for v in eqn.invars:
            if hasattr(v, "aval") and last_use.get(id(v)) == idx:
                live -= tracked.pop(id(v), 0)
        for v in eqn.outvars:
            if hasattr(v, "aval") and last_use.get(id(v), -1) <= idx:
                live -= tracked.pop(id(v), 0)
    return peak


def peak_live_bytes(closed_jaxpr) -> int:
    """Machine-wide static peak-bytes estimate for a traced program."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    return _walk(jaxpr, scale=1)


@ir_rule("static-memory",
         "static live-set peak must fit the armed memory budget",
         "jx-memory")
def rule_static_memory(view: ProgramView, ctx: AuditContext
                       ) -> List[Finding]:
    if view.jaxpr is None:
        return []
    peak = peak_live_bytes(view.jaxpr)
    view.meta.setdefault("stats", {})["peak_live_bytes"] = int(peak)
    budget = ctx.memory_budget_bytes
    if budget is None or peak <= budget:
        return []
    return [Finding(
        rule="static-memory", path=f"jaxpr:{view.name}", line=0,
        key=f"{view.name}:peak",
        message=f"[{view.name}] static live-set peak {peak} bytes "
                f"exceeds the armed budget {budget} bytes "
                f"({peak / max(1, budget):.2f}x) — the program cannot "
                f"fit; shrink capacities (network_fanout_bits / window "
                f"caps) or raise memory_budget_bytes")]
