"""The graftcheck IR rules: transfer, collective-axis, width, donation.

Each rule reads one invariant off the lowered program that the AST
rules cannot see (the static-memory rule lives in ``memory.py`` next
to its live-set walk):

* ``transfer`` — the static twin of ``jax.transfer_guard``: a
  ``device_put`` of non-trivial bytes staged *inside* a traced hot
  program is an implicit placement the runtime guard would reject on
  a real mesh, and a host callback equation is a synchronous host
  round-trip no matter what the guard says.  Scalar re-placements
  (ALIAS-semantics device_puts of () shapes, e.g. weak-typed ints
  crossing a cond) are below ``transfer_min_bytes`` and stay silent.
* ``collective-axis`` — every collective must name an axis of the
  mesh its shard_map binds, with a consistent size: an all_to_all
  whose split dimension the axis size does not divide is exactly the
  staged-exchange column-group bug (window.block_all_to_all pads to
  make this true; a program where it is false silently drops tuples
  on a real backend).
* ``width`` — a uint32 lane that widens to i64/f64 doubles the wire
  and HBM bytes of every downstream equation; widening to f32 loses
  key bits above 2**24.  Either way it is silent in the source (jnp
  promotion) and loud here.
* ``donation`` — a program input that is big, consumed, not returned,
  and not donated holds two generations of the buffer live across the
  call boundary.  The finding names the concrete ``donate_argnums``
  fix; the engine's split-path programs apply it via
  ``operators.hash_join.split_donation`` and the deliberately
  undonated entries carry registry waivers with reasons.
"""

from __future__ import annotations

from typing import List

from tpu_radix_join.analysis.core import Finding
from tpu_radix_join.analysis.jaxpr.core import (AuditContext, EqnView,
                                                ProgramView, ir_rule)

#: host-callback primitives: always a synchronous host round-trip
CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

#: collective primitives and the param key carrying their axis name(s)
COLLECTIVE_AXIS_PARAMS = {
    "psum": "axes", "pmin": "axes", "pmax": "axes",
    "all_to_all": "axis_name", "ppermute": "axis_name",
    "all_gather": "axis_name", "reduce_scatter": "axis_name",
    "axis_index": "axis_name",
}

#: 4-byte lane dtypes the wire path ships
_LANE_DTYPES = ("uint32", "int32")
#: widened dtypes that double bytes (i64/u64/f64) or drop key bits (f32)
_WIDE_DTYPES = ("int64", "uint64", "float64", "float32")


def _eqn_finding(view: ProgramView, eqn: EqnView, rule_id: str, key: str,
                 message: str) -> Finding:
    path, line = eqn.source_path_line()
    if not path:
        path, line = f"jaxpr:{view.name}", 0
    return Finding(rule=rule_id, path=path, line=line, key=key,
                   message=message)


@ir_rule("transfer",
         "no implicit device_put / host callback inside a hot program",
         "jx-transfer")
def rule_transfer(view: ProgramView, ctx: AuditContext) -> List[Finding]:
    out: List[Finding] = []
    for eqn in view.eqns:
        if eqn.prim in CALLBACK_PRIMS:
            out.append(_eqn_finding(
                view, eqn, "transfer", f"{view.name}:{eqn.prim}",
                f"[{view.name}] host callback '{eqn.prim}' staged inside "
                f"a jitted hot program — a synchronous device->host round "
                f"trip per dispatch; hoist it out of the traced path or "
                f"route the readback through utils.hostsync.host_readback "
                f"after the fence"))
        elif (eqn.prim == "device_put"
              and eqn.in_bytes() >= ctx.transfer_min_bytes):
            out.append(_eqn_finding(
                view, eqn, "transfer",
                f"{view.name}:device_put:{eqn.in_bytes()}",
                f"[{view.name}] device_put of {eqn.in_bytes()} bytes "
                f"traced into the program ({eqn.source or 'no frame'}) — "
                f"an implicit placement the transfer guard would reject; "
                f"pre-place the operand with an explicit jax.device_put "
                f"outside the jit"))
    return out


@ir_rule("collective-axis",
         "collectives name live mesh axes with consistent sizes; "
         "all_to_all splits divide evenly",
         "jx-axis")
def rule_collective_axis(view: ProgramView, ctx: AuditContext
                         ) -> List[Finding]:
    out: List[Finding] = []
    for eqn in view.eqns:
        param_key = COLLECTIVE_AXIS_PARAMS.get(eqn.prim)
        if param_key is None or not eqn.mesh_axes:
            continue
        names = eqn.params.get(param_key)
        if names is None:
            continue
        if not isinstance(names, (tuple, list)):
            names = (names,)
        for name in names:
            if not isinstance(name, str):
                continue    # positional (unnamed) axes: nothing to check
            if name not in eqn.mesh_axes:
                out.append(_eqn_finding(
                    view, eqn, "collective-axis",
                    f"{view.name}:{eqn.prim}:{name}",
                    f"[{view.name}] {eqn.prim} names axis {name!r} but "
                    f"the enclosing shard_map binds "
                    f"{sorted(eqn.mesh_axes)} — the collective would "
                    f"reduce over a dead axis"))
                continue
            size = eqn.mesh_axes[name]
            decl = eqn.params.get("axis_size")
            if decl is not None and int(decl) != size:
                out.append(_eqn_finding(
                    view, eqn, "collective-axis",
                    f"{view.name}:{eqn.prim}:{name}:size",
                    f"[{view.name}] {eqn.prim} declares axis_size "
                    f"{int(decl)} but mesh axis {name!r} has size "
                    f"{size}"))
            if eqn.prim == "all_to_all" and eqn.invals:
                split = eqn.params.get("split_axis")
                shape = eqn.invals[0].shape
                if (split is not None and int(split) < len(shape)
                        and shape[int(split)] % size != 0):
                    out.append(_eqn_finding(
                        view, eqn, "collective-axis",
                        f"{view.name}:all_to_all:{name}:divisibility",
                        f"[{view.name}] all_to_all split dim "
                        f"{int(split)} has extent {shape[int(split)]}, "
                        f"not divisible by axis {name!r} size {size} — "
                        f"the staged-exchange column groups would "
                        f"misalign (window.block_all_to_all pads "
                        f"exactly to prevent this)"))
    return out


@ir_rule("width",
         "uint32 lanes must not silently widen to i64/f64/f32",
         "jx-width")
def rule_width(view: ProgramView, ctx: AuditContext) -> List[Finding]:
    out: List[Finding] = []
    for eqn in view.eqns:
        if eqn.prim != "convert_element_type" or not eqn.invals:
            continue
        src = eqn.invals[0]
        dst = str(eqn.params.get("new_dtype", ""))
        if (src.dtype in _LANE_DTYPES and dst in _WIDE_DTYPES
                and src.bytes >= ctx.width_min_bytes):
            out.append(_eqn_finding(
                view, eqn, "width",
                f"{view.name}:{src.dtype}->{dst}:{src.bytes}",
                f"[{view.name}] {src.dtype} operand of {src.bytes} bytes "
                f"widens to {dst} ({eqn.source or 'no frame'}) — "
                f"{'key bits above 2**24 are lost' if dst == 'float32' else 'doubles the bytes of every downstream equation'}"
                f"; keep the lane uint32 (mask/shift instead of "
                f"promoting arithmetic)"))
    return out


@ir_rule("donation",
         "large dead-after-use inputs must be donated "
         "(concrete donate_argnums findings)",
         "jx-donation")
def rule_donation(view: ProgramView, ctx: AuditContext) -> List[Finding]:
    out: List[Finding] = []
    arg_of_leaf = view.meta.get("arg_of_leaf") or []
    # outputs by (shape, dtype): an input aliasing an output is returned,
    # not dead — conservative structural check (the engine's programs
    # never pass inputs through)
    out_shapes = {(o.shape, o.dtype) for o in view.out_avals}
    missing_args = set()
    for i, (aval, donated) in enumerate(zip(view.in_avals, view.donated)):
        if donated or aval.bytes < ctx.donation_min_bytes:
            continue
        if (aval.shape, aval.dtype) in out_shapes:
            continue
        arg = arg_of_leaf[i] if i < len(arg_of_leaf) else None
        missing_args.add((arg, i, aval))
    for arg, i, aval in sorted(missing_args,
                               key=lambda t: (t[0] is None, t[0], t[1])):
        where = (f"python arg {arg}" if arg is not None
                 else f"flat input {i}")
        out.append(Finding(
            rule="donation", path=f"jaxpr:{view.name}", line=0,
            key=f"{view.name}:in{i}",
            message=f"[{view.name}] {where} "
                    f"({aval.dtype}{list(aval.shape)}, {aval.bytes} "
                    f"bytes) is consumed, never returned, and not "
                    f"donated — both generations stay live across the "
                    f"dispatch; add donate_argnums=({arg},) at the "
                    f"jax.jit site (operators.hash_join.split_donation "
                    f"is the engine's donation map) or declare a "
                    f"registry waiver with the reuse reason"))
    return out
