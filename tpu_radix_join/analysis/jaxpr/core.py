"""graftcheck core: jaxpr-level findings, IR rule registry, baseline.

graftlint (``analysis/core.py``) enforces conventions the *source text*
can show; the invariants that actually break accelerator runs — an
implicit host transfer in a hot program, a collective naming the wrong
mesh axis, a uint32 lane silently widening on the wire path, a dead
input buffer the program never donated, a live set that cannot fit the
HBM budget — live in the *lowered program*.  This package traces the
engine's jitted entry points abstractly (``jax.make_jaxpr`` over
``ShapeDtypeStruct`` inputs: no devices, no dispatch, CPU tier-1 safe)
and walks the ClosedJaxpr with the same finding/waiver/baseline/exit
discipline graftlint established:

* An **IR rule** is ``fn(program: ProgramView, ctx: AuditContext)
  -> [Finding]`` registered with :func:`ir_rule` (id, doc, token).
* A **ProgramView** is one traced entry point flattened to
  :class:`EqnView` rows — primitive name, operand/result avals with
  byte sizes, params, the active mesh axes, and the ``source_info``
  summary that points a finding back at the Python line that staged
  the equation.
* **Waivers** are per-entry, per-rule, with a mandatory reason — the
  IR has no comment lines to annotate, so the entry registry
  (``trace.py``) declares them where the entry is defined (e.g. the
  fused pipeline's inputs are deliberately undonated: the retry loop
  re-feeds them).  A reasonless waiver suppresses nothing.
* **Baseline** (:data:`JXAUDIT_BASELINE`): committed suppressions with
  mandatory reasons; stale entries are reported and fail ``--strict``
  — same contract, same schema as ``LINT_BASELINE.json``.

Findings reuse :class:`analysis.core.Finding` verbatim: ``path`` is the
repo-relative source file the equation's ``source_info`` names (or the
entry name for program-scope findings), ``key`` is a stable
``entry:detail`` token, so baseline entries survive retraces.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu_radix_join.analysis.core import Finding, LintError

JXAUDIT_BASELINE = "JXAUDIT_BASELINE.json"


# --------------------------------------------------------------------- model
@dataclass(frozen=True)
class AvalView:
    """One abstract value: static shape, dtype name, and byte size."""

    shape: Tuple[int, ...]
    dtype: str
    bytes: int

    @classmethod
    def of(cls, aval) -> "AvalView":
        shape = tuple(int(d) for d in getattr(aval, "shape", ()) or ())
        dtype = str(getattr(aval, "dtype", "abstract"))
        itemsize = int(getattr(getattr(aval, "dtype", None), "itemsize", 0)
                       or 0)
        n = 1
        for d in shape:
            n *= d
        return cls(shape=shape, dtype=dtype, bytes=n * itemsize)


@dataclass(frozen=True)
class EqnView:
    """One equation of the flattened program, in rule vocabulary."""

    prim: str                        # primitive name ("all_to_all", ...)
    invals: Tuple[AvalView, ...]
    outvals: Tuple[AvalView, ...]
    params: dict
    source: str                      # "<file>:<line> (<function>)" or ""
    #: mesh axes live at this equation (inside shard_map): name -> size.
    #: Empty outside any shard_map body.
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    depth: int = 0                   # nesting depth (pjit/shard_map/scan)

    def in_bytes(self) -> int:
        return sum(v.bytes for v in self.invals)

    def source_path_line(self) -> Tuple[str, int]:
        """(repo-relative-ish path, line) parsed from the source summary;
        falls back to ("", 0) for equations with no user frame."""
        s = self.source.split(" ")[0] if self.source else ""
        if ":" not in s:
            return "", 0
        path, _, line = s.rpartition(":")
        try:
            return path, int(line)
        except ValueError:
            return "", 0


@dataclass
class ProgramView:
    """One traced entry point, ready for the IR rules.

    ``donated`` aligns with ``in_avals`` (flattened python-arg pytree
    leaves); ``waivers`` maps rule id -> reason for deliberate
    violations declared at the entry registry.  ``jaxpr`` keeps the
    underlying ClosedJaxpr for rules that need var identity (the
    static-memory live-set walk).
    """

    name: str
    eqns: List[EqnView]
    in_avals: List[AvalView]
    out_avals: List[AvalView]
    donated: List[bool]
    mesh_axes: Dict[str, int]
    num_devices: int = 1
    waivers: Dict[str, str] = field(default_factory=dict)
    meta: dict = field(default_factory=dict)
    jaxpr: object = None             # ClosedJaxpr (opaque to most rules)

    def waived(self, rule_id: str) -> bool:
        return bool(self.waivers.get(rule_id, "").strip())


@dataclass
class AuditContext:
    """Knobs the rules read: thresholds and the optional memory budget.

    ``transfer_min_bytes`` keeps scalar re-placements (e.g. a traced
    int donated across a cond) out of the transfer rule — a scalar
    device_put is a no-op on every backend; the rule hunts *bulk*
    implicit traffic.  ``memory_budget_bytes`` arms the static-memory
    rule; None leaves it informational (peak recorded, no finding).
    """

    transfer_min_bytes: int = 4096
    width_min_bytes: int = 4096
    donation_min_bytes: int = 1 << 16
    memory_budget_bytes: Optional[int] = None


@dataclass(frozen=True)
class IRRule:
    id: str
    doc: str
    token: str
    fn: Callable[[ProgramView, AuditContext], List[Finding]]


IR_RULES: Dict[str, IRRule] = {}


def ir_rule(rule_id: str, doc: str, token: str):
    """Register an IR rule function under ``rule_id``."""
    def deco(fn):
        if rule_id in IR_RULES:
            raise LintError(f"duplicate IR rule id {rule_id!r}")
        IR_RULES[rule_id] = IRRule(rule_id, doc, token, fn)
        return fn
    return deco


# ------------------------------------------------------------------ baseline
def load_ir_baseline(path: str) -> List[dict]:
    """Validated suppressions — graftlint's schema, graftcheck's rule
    table.  Every entry carries a non-empty reason or loading fails
    (exit 2 at the CLI)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise LintError(f"cannot read baseline {path}: {e}") from e
    except ValueError as e:
        raise LintError(f"baseline {path} is not valid JSON: {e}") from e
    entries = data.get("suppressions")
    if not isinstance(entries, list):
        raise LintError(f"baseline {path} has no 'suppressions' list")
    for i, e in enumerate(entries):
        for k in ("rule", "path", "key", "reason"):
            if not isinstance(e.get(k), str) or not e[k].strip():
                raise LintError(
                    f"baseline {path} entry {i} needs a non-empty {k!r} "
                    f"(every suppression carries a reason)")
        if e["rule"] not in IR_RULES:
            raise LintError(
                f"baseline {path} entry {i} names unknown IR rule "
                f"{e['rule']!r}")
    return entries


# --------------------------------------------------------------------- runner
@dataclass
class AuditResult:
    findings: List[Finding]
    suppressed: List[Finding]
    stale: List[dict]
    rules: List[str]
    entries: List[str]               # entry names audited
    #: informational per-entry measurements (peak bytes, exchange bytes)
    stats: Dict[str, dict] = field(default_factory=dict)

    def exit_code(self, strict: bool = False) -> int:
        if self.findings:
            return 1
        if strict and self.stale:
            return 1
        return 0


def run_audit(programs: List[ProgramView],
              rule_ids: Optional[List[str]] = None,
              baseline_path: Optional[str] = None,
              ctx: Optional[AuditContext] = None) -> AuditResult:
    """Run ``rule_ids`` (default: all registered) over the traced
    programs, applying per-entry waivers then the baseline."""
    from tpu_radix_join.analysis.jaxpr import register_ir_rules
    register_ir_rules()
    ctx = ctx or AuditContext()
    ids = list(IR_RULES) if rule_ids is None else list(rule_ids)
    unknown = [r for r in ids if r not in IR_RULES]
    if unknown:
        raise LintError(f"unknown IR rule id(s): {', '.join(unknown)} "
                        f"(known: {', '.join(sorted(IR_RULES))})")
    findings: List[Finding] = []
    stats: Dict[str, dict] = {}
    for view in programs:
        stats[view.name] = view.meta.setdefault("stats", {})
        for rid in ids:
            if view.waived(rid):
                continue
            findings.extend(IR_RULES[rid].fn(view, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    entries: List[dict] = []
    if baseline_path and os.path.exists(baseline_path):
        entries = load_ir_baseline(baseline_path)
    kept, suppressed = [], []
    used = [False] * len(entries)
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if (e["rule"] == f.rule and e["path"] == f.path
                    and e["key"] == f.key):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = True
            suppressed.append(f)
    stale = [e for i, e in enumerate(entries)
             if not used[i] and e["rule"] in ids]
    return AuditResult(findings=kept, suppressed=suppressed, stale=stale,
                       rules=ids, entries=[v.name for v in programs],
                       stats=stats)
