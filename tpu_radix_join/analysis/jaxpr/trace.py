"""graftcheck tracer: abstract entry-point registry + the jaxpr walker.

Every jitted program the engine dispatches on the hot path is traced
here with ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` inputs — no
arrays are materialized, no program compiles, no device executes, so
the whole registry traces in a couple of seconds on the tier-1 CPU
rig.  Downstream programs (the split probe, the bucket chain) take the
*previous* program's outputs as inputs; ``jax.eval_shape`` over the
producer supplies exactly the avals the engine would hand them, so the
audited programs are the dispatched programs, not hand-modeled twins.

Donation ground truth comes from ``operators.hash_join.split_donation``
— the same table the ``jax.jit`` sites compile with — flattened across
the pytree leaves so the donation rule checks what XLA was actually
told.  Deliberately-undonated entries (the sizing program, the fused
pipeline, the split shuffle: all re-fed by the retry/repeat loops)
carry per-entry waivers with the reason inline, mirroring graftlint's
``# lint: token-ok(reason)`` discipline at the registry level.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import jax

from tpu_radix_join.analysis.core import LintError
from tpu_radix_join.analysis.jaxpr.core import (AvalView, EqnView,
                                                ProgramView)

#: entry names build_entries understands, in dependency order
ENTRY_NAMES = ("hist", "pipeline", "shuffle", "probe", "materialize_probe",
               "lp", "bp", "bp_build", "bp_probe")

#: primitives whose params carry nested jaxprs the walker must enter
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".."))


def _summarize(eqn) -> str:
    """"path:line (function)" for the equation's staging site, repo-
    relative when the frame is inside this repo; "" for framework
    equations with no user frame."""
    try:
        from jax._src import source_info_util as siu
        s = siu.summarize(eqn.source_info)
    except Exception:       # noqa: BLE001 — attribution is best-effort
        return ""
    if s.startswith(_REPO_ROOT):
        s = os.path.relpath(s, _REPO_ROOT)
    return s


def _mesh_axes(mesh) -> Dict[str, int]:
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:       # noqa: BLE001 — AbstractMesh variants differ
        names = getattr(mesh, "axis_names", ()) or ()
        sizes = getattr(mesh, "axis_sizes", ()) or ()
        return {str(n): int(s) for n, s in zip(names, sizes)}


def _sub_jaxprs(params: dict):
    """Yield (open_jaxpr, mesh_or_None) for every nested jaxpr in an
    equation's params — pjit/scan (ClosedJaxpr), cond (branches tuple),
    shard_map (open jaxpr + mesh)."""
    mesh = params.get("mesh") if "jaxpr" in params else None
    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            # ClosedJaxpr also exposes .eqns — unwrap it first
            if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"),
                                               "eqns"):  # ClosedJaxpr
                yield v.jaxpr, mesh
            elif hasattr(v, "eqns"):                     # open Jaxpr
                yield v, mesh


def walk_eqns(jaxpr, mesh_axes: Optional[Dict[str, int]] = None,
              depth: int = 0) -> List[EqnView]:
    """Flatten a (Closed)Jaxpr to EqnViews, recursing through pjit/
    shard_map/scan/cond bodies and threading the active mesh axes."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    views: List[EqnView] = []
    for eqn in jaxpr.eqns:
        params = dict(eqn.params)
        axes = dict(mesh_axes or {})
        if eqn.primitive.name == "shard_map" and "mesh" in params:
            axes.update(_mesh_axes(params["mesh"]))
        views.append(EqnView(
            prim=eqn.primitive.name,
            invals=tuple(AvalView.of(v.aval) for v in eqn.invars),
            outvals=tuple(AvalView.of(v.aval) for v in eqn.outvars),
            params=params,
            source=_summarize(eqn),
            mesh_axes=dict(mesh_axes or {}),
            depth=depth))
        for sub, mesh in _sub_jaxprs(params):
            sub_axes = dict(axes)
            if mesh is not None:
                sub_axes.update(_mesh_axes(mesh))
            views.extend(walk_eqns(sub, sub_axes, depth + 1))
    return views


def flat_donated(args, donate_argnums: Sequence[int]) -> List[bool]:
    """Per-flattened-leaf donation flags from python-arg donate_argnums."""
    donated = set(donate_argnums)
    flags: List[bool] = []
    for i, arg in enumerate(args):
        leaves = jax.tree_util.tree_leaves(arg)
        flags.extend([i in donated] * len(leaves))
    return flags


def view_from_fn(name: str, fn, args, *, donate_argnums=(),
                 waivers: Optional[Dict[str, str]] = None,
                 num_devices: int = 1, meta: Optional[dict] = None
                 ) -> ProgramView:
    """Trace ``fn(*args)`` abstractly and package it for the IR rules."""
    closed = jax.make_jaxpr(fn)(*args)
    eqns = walk_eqns(closed)
    mesh_axes: Dict[str, int] = {}
    for e in eqns:
        if e.prim == "shard_map" and "mesh" in e.params:
            mesh_axes.update(_mesh_axes(e.params["mesh"]))
    flat_in = [AvalView.of(v.aval) for v in closed.jaxpr.invars]
    donated = flat_donated(args, donate_argnums)
    arg_of_leaf: List[Optional[int]] = []
    for i, arg in enumerate(args):
        arg_of_leaf.extend([i] * len(jax.tree_util.tree_leaves(arg)))
    if len(donated) != len(flat_in):
        # consts prepend to invars in some traces; align conservatively
        pad = len(flat_in) - len(donated)
        if pad > 0:
            donated = [False] * pad + donated
            arg_of_leaf = [None] * pad + arg_of_leaf
        else:
            donated = donated[:len(flat_in)]
            arg_of_leaf = arg_of_leaf[:len(flat_in)]
    full_meta = dict(meta or {})
    full_meta["arg_of_leaf"] = arg_of_leaf
    return ProgramView(
        name=name, eqns=eqns, in_avals=flat_in,
        out_avals=[AvalView.of(v.aval) for v in closed.jaxpr.outvars],
        donated=donated, mesh_axes=mesh_axes, num_devices=num_devices,
        waivers=dict(waivers or {}), meta=full_meta, jaxpr=closed)


# ------------------------------------------------------------ entry registry
def _batch_sds(global_n: int):
    import jax.numpy as jnp

    from tpu_radix_join.data.tuples import TupleBatch
    return TupleBatch(
        key=jax.ShapeDtypeStruct((global_n,), jnp.uint32),
        rid=jax.ShapeDtypeStruct((global_n,), jnp.uint32))


#: reasons the front-half programs keep their inputs undonated — the
#: donation rule's per-entry waivers (graftlint's ``-ok(reason)`` analog)
_FRONT_HALF_WAIVERS = {
    "hist": {"donation": "sizing program: r and s are re-fed to the "
                         "pipeline program after capacity resolution"},
    "pipeline": {"donation": "fused pipeline inputs survive the join: the "
                             "capacity-regrow retry loop and pipelined "
                             "repeats re-dispatch the same r/s buffers"},
    "shuffle": {"donation": "split front half: r and s are the retry "
                            "loop's regeneration source — a capacity "
                            "retry reruns the shuffle from the pristine "
                            "inputs"},
}


def build_entries(num_nodes: int = 8, per_node: int = 8192,
                  cap: int = 2048,
                  entries: Optional[Sequence[str]] = None
                  ) -> List[ProgramView]:
    """Trace the engine's jitted entry points into ProgramViews.

    Builds two throwaway engines (sort-probe and bucket-probe) on the
    first ``num_nodes`` local devices and traces each program with
    representative static shapes (``per_node`` tuples/node, ``cap``
    wire slots per (sender, destination) block — large enough that the
    byte-threshold rules see hot-path-scale buffers).  Requires the
    host to expose ``num_nodes`` devices (tests/conftest.py and the
    audit CLI force 8 virtual CPU devices before importing jax).
    """
    from tpu_radix_join import HashJoin, JoinConfig
    from tpu_radix_join.operators.hash_join import split_donation

    if len(jax.devices()) < num_nodes:
        raise LintError(
            f"graftcheck needs {num_nodes} devices to build the engine "
            f"mesh, found {len(jax.devices())} — force host CPU devices "
            f"before importing jax (utils/platform.force_host_cpu_devices)")
    wanted = list(entries) if entries is not None else list(ENTRY_NAMES)
    unknown = [e for e in wanted if e not in ENTRY_NAMES]
    if unknown:
        raise LintError(f"unknown entry name(s): {', '.join(unknown)} "
                        f"(known: {', '.join(ENTRY_NAMES)})")
    n = num_nodes
    rb, sb = _batch_sds(n * per_node), _batch_sds(n * per_node)
    eng = HashJoin(JoinConfig(num_nodes=n, network_fanout_bits=5))
    beng = HashJoin(JoinConfig(num_nodes=n, network_fanout_bits=5,
                               probe_algorithm="bucket",
                               local_fanout_bits=6))
    views: List[ProgramView] = []
    meta = {"num_nodes": n, "per_node": per_node, "cap": cap}

    def add(name, fn, args, donate=(), waivers=None):
        if name in wanted:
            views.append(view_from_fn(
                name, fn, args, donate_argnums=donate,
                waivers=_FRONT_HALF_WAIVERS.get(name, waivers or {}),
                num_devices=n, meta=dict(meta, entry=name)))

    add("hist", eng._histogram_fn(0), (rb, sb))
    add("pipeline", eng._pipeline_fn(per_node, per_node, cap, cap),
        (rb, sb))
    shuffle_fn = eng._shuffle_fn(cap, cap)
    add("shuffle", shuffle_fn, (rb, sb))
    if "probe" in wanted:
        # (rp_batch, rp_valid, sp_batch, sp_valid, sp_pid, sflags, s_gh)
        outs = jax.eval_shape(shuffle_fn, rb, sb)
        probe_args = tuple(outs[:5]) + tuple(outs[6:])
        add("probe", eng._probe_fn(cap, cap, 1), probe_args,
            donate=split_donation("probe"))
    if "materialize_probe" in wanted:
        mouts = jax.eval_shape(eng._shuffle_fn(cap, cap, materialize=True),
                               rb, sb)
        add("materialize_probe", eng._materialize_probe_fn(per_node),
            (mouts[0], mouts[1]),
            donate=split_donation("materialize_probe"))
    if {"lp", "bp", "bp_build", "bp_probe"} & set(wanted):
        bouts = jax.eval_shape(beng._shuffle_fn(cap, cap), rb, sb)
        lp_args = tuple(bouts[:4])
        lp_fn = beng._lp_fn(cap, cap, 1)
        add("lp", lp_fn, lp_args, donate=split_donation("lp"))
        louts = jax.eval_shape(lp_fn, *lp_args)
        bp_args = (louts[0], louts[1])
        add("bp", beng._bp_fn(cap, cap, 1), bp_args,
            donate=split_donation("bp"))
        build_fn = beng._bp_build_fn(cap, cap, 1, None, False)
        add("bp_build", build_fn, bp_args,
            donate=split_donation("bp_build"))
        if "bp_probe" in wanted:
            lanes = jax.eval_shape(build_fn, *bp_args)
            add("bp_probe", beng._bp_probe_fn(cap, cap, 1, None, False),
                tuple(lanes), donate=split_donation("bp_probe"))
    return views
