"""graftlint: project-native static analysis for tpu_radix_join.

The framework lives in :mod:`core` (registry, walker, findings,
baseline); the six convention rules each get a module:

  =================  ===================================================
  sort-bypass        hot sorts route through ops/sorting.py (PR 12)
  counter-tag        emitted tags pinned/neutral in regress.py, both ways
  failure-class      failure_class strings come from the retry taxonomy
  sync-point         no implicit host syncs in engine hot paths
  recompile-hazard   no jit-in-loop / f-string compile keys
  lock-discipline    thread-target writes hold a lock or say why not
  =================  ===================================================

CLI: ``tools_lint.py`` at the repo root; tier-1 gate:
``tests/test_lint.py::test_repo_is_lint_clean``.
"""

from tpu_radix_join.analysis.core import (BASELINE_NAME, Finding, LintError,
                                          LintResult, RULES, Repo,
                                          apply_baseline, load_baseline,
                                          load_repo, run_lint)

_REGISTERED = False


def register_builtin_rules() -> None:
    """Import the rule modules (idempotent): importing registers."""
    global _REGISTERED
    if _REGISTERED:
        return
    from tpu_radix_join.analysis import (rules_failure,     # noqa: F401
                                         rules_locks,       # noqa: F401
                                         rules_recompile,   # noqa: F401
                                         rules_sort,        # noqa: F401
                                         rules_sync,        # noqa: F401
                                         rules_tags)        # noqa: F401
    _REGISTERED = True


__all__ = ["BASELINE_NAME", "Finding", "LintError", "LintResult", "RULES",
           "Repo", "apply_baseline", "load_baseline", "load_repo",
           "run_lint", "register_builtin_rules"]
