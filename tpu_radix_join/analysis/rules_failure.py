"""Rule ``failure-class``: every failure class comes from the taxonomy.

The robustness layer's whole contract is that callers branch on
``failure_class`` *data* (robustness/retry.py's closed string set) —
chaos triage, the serve loop's retry policy, and postmortem merging all
switch on those strings.  A hand-rolled class (``"oom"``, a typo like
``"rank-lost"``) silently falls through every branch: the chaos soak
books it a violation, the retry policy treats it as fatal, and the
postmortem merge shows an unknown bucket.

Flagged spellings, anywhere a *string literal* is used:

  * ``failure_class="..."`` keyword arguments,
  * ``failure_class = "..."`` / ``x.failure_class = "..."`` assigns,
  * ``...["failure_class"] = "..."`` subscript assigns,
  * ``{"failure_class": "..."}`` dict literals.

Names (``failure_class=RANK_LOST``) are not checked — constants resolve
to the taxonomy by construction.  The taxonomy is imported from
``robustness.retry`` (its UPPER_CASE string constants) plus the
service layer's ``"unclassified"`` sentinel (service/session.py: the
class stamped before triage has run).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tpu_radix_join.analysis.core import Finding, Repo, rule

#: classes that are taxonomy members without being retry.py constants:
#: "unclassified" is service/session.py's pre-triage sentinel
EXTRA_CLASSES = {"unclassified"}


def taxonomy() -> set:
    from tpu_radix_join.robustness import retry
    return {val for name, val in vars(retry).items()
            if name.isupper() and not name.startswith("_")
            and isinstance(val, str)} | EXTRA_CLASSES


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _sites(tree: ast.Module):
    """Yield (line, class_string) for every literal failure-class use."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "failure_class":
                    s = _literal_str(kw.value)
                    if s is not None:
                        yield kw.value.lineno, s
        elif isinstance(node, ast.Assign):
            s = _literal_str(node.value)
            if s is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Name)
                        and tgt.id == "failure_class") \
                   or (isinstance(tgt, ast.Attribute)
                       and tgt.attr == "failure_class") \
                   or (isinstance(tgt, ast.Subscript)
                       and _literal_str(tgt.slice) == "failure_class"):
                    yield node.lineno, s
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (k is not None and _literal_str(k) == "failure_class"):
                    s = _literal_str(v)
                    if s is not None:
                        yield v.lineno, s


@rule("failure-class",
      "literal failure_class strings must come from the robustness/"
      "retry.py taxonomy",
      token="failure")
def check(repo: Repo) -> List[Finding]:
    classes = taxonomy()
    out: List[Finding] = []
    for src in repo.files:
        for line, s in _sites(src.tree):
            if s not in classes:
                out.append(Finding(
                    rule="failure-class", path=src.rel, line=line, key=s,
                    message=(f"failure class {s!r} is not in the "
                             f"robustness/retry.py taxonomy — use a "
                             f"declared class (or extend the taxonomy, "
                             f"never a one-off string)")))
    return out
