"""Local (second-level) radix partitioning.

Replaces ``tasks/LocalPartitioning.{h,cpp}``: the optional second radix pass
that refines each node's received tuples by the next ``LOCAL_PARTITIONING_FANOUT``
key bits so every build-probe bucket fits fast memory (histogram over bits
``[f, f+l)`` — LocalPartitioning.cpp:147-155; prefix sum :165-192; SWWC
reorder :194-250; one BuildProbe task per sub-partition :116-124).

TPU design: the reorder is a static-shape block scatter
(ops/radix.scatter_to_blocks) keyed on the local bucket id, yielding a
[num_buckets, capacity] layout whose rows are the "BuildProbe tasks" — consumed
in one shot by the dense bucketized probe (ops/build_probe.probe_count_bucketized),
the analog of draining ``TASK_QUEUE`` (HashJoin.cpp:187-204) in parallel
instead of a FIFO loop.  Bucket id uses only the local bits (network bits are
dropped, as in the reference's compressed layout); the probe compares full
keys, so tuples from different network partitions sharing local bits can never
falsely match.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.radix import scatter_to_blocks, exclusive_cumsum


class LocalPartitionResult(NamedTuple):
    """``histogram``/``offsets`` are the reference's intermediate artifacts
    (computeHistogram / computePrefixSum, LocalPartitioning.cpp:138-192),
    exposed for parity and diagnostics; the shipping pipeline consumes only
    ``blocks``/``overflow``, and XLA dead-code-eliminates the rest at zero
    runtime cost."""
    blocks: TupleBatch       # [num_buckets * capacity] lanes, sentinel-padded
    histogram: jnp.ndarray   # uint32 [num_buckets] — true per-bucket demand
    offsets: jnp.ndarray     # uint32 [num_buckets] — exclusive prefix sum
    overflow: jnp.ndarray    # uint32 — tuples that did not fit their bucket


def local_bucket_ids(batch: TupleBatch, network_fanout_bits: int,
                     local_fanout_bits: int) -> jnp.ndarray:
    """Bucket = key bits [f, f+l) (LocalPartitioning.cpp:147-155)."""
    f = jnp.uint32(network_fanout_bits)
    mask = jnp.uint32((1 << local_fanout_bits) - 1)
    return (batch.key >> f) & mask


def local_partition(
    batch: TupleBatch,
    valid: jnp.ndarray,
    network_fanout_bits: int,
    local_fanout_bits: int,
    capacity: int,
    side: str,
    impl: str | None = None,
) -> LocalPartitionResult:
    num_buckets = 1 << local_fanout_bits
    lpid = local_bucket_ids(batch, network_fanout_bits, local_fanout_bits)
    blocks, counts, overflow = scatter_to_blocks(
        batch, lpid, num_buckets, capacity, side, valid=valid, impl=impl)
    # counts IS the per-bucket histogram: scatter_to_blocks derives it from
    # run boundaries of the same (valid-masked) bucket ids, so a separate
    # histogram pass over the tuples would recompute it byte-for-byte
    # (LocalPartitioning.cpp computes its histogram separately only because
    # its reorder needs the prefix sums *before* writing).
    return LocalPartitionResult(
        blocks=blocks, histogram=counts, offsets=exclusive_cumsum(counts),
        overflow=overflow)
