"""HashJoin: the full distributed pipeline as one SPMD program.

Replaces ``operators/HashJoin.{h,cpp}`` — the 4-phase orchestration with
barriers, phase timers, and a task queue (HashJoin.cpp:45-220).  The TPU-native
shape: every phase — local histogram, global histogram (psum), assignment,
offsets (all_gather exscan), network partitioning (all_to_all), local
partitioning, build-probe — is traced into **one shard_map program** compiled
by XLA over the mesh; MPI barriers (HashJoin.cpp:50,120) become XLA program
order, and the sequential ``TASK_QUEUE`` drain (HashJoin.cpp:187-204) becomes
vectorized per-partition work in the same program.

Match counts are returned per network partition in uint32 and summed on host
in uint64 so billion-scale totals are exact without device int64 (SURVEY.md
§7.4 item 2).  The "each partition's count stays < 2**32" contract is
guarded at runtime (:meth:`HashJoin._count_risk`): the probe's max match
weight bounds every partition's count, and a workload that could wrap flips
``count_overflow_risk`` (ok=False) — the reference cannot wrap by
construction (uint64 RESULT_COUNTER, operators/HashJoin.h:26), so neither,
observably, can this pipeline.
"""

from __future__ import annotations

import contextlib
import functools
import os
import signal
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_radix_join.core.config import JoinConfig
from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.tuples import (
    CompressedBatch,
    R_PAD_KEY,
    TupleBatch,
    _sentinel_lane,
    make_wire_spec,
    partition_ids,
    valid_mask,
)
from tpu_radix_join.histograms import (
    compute_global_histogram,
    compute_local_histogram,
    compute_offsets,
    compute_partition_assignment,
)
from tpu_radix_join.ops.build_probe import (
    DENSE_BUCKET_LIMIT,
    bucket_rows_count,
    bucket_rows_sort,
    probe_count_bucketized,
    probe_count_chunked,
    probe_materialize,
    probe_materialize_chunked,
)
from tpu_radix_join.ops.merge_count import (
    MAX_MERGE_KEY,
    merge_count_per_partition,
    merge_count_per_partition_full,
    merge_count_wide_per_partition,
)
from tpu_radix_join.operators import skew
from tpu_radix_join.operators.local_partitioning import local_partition
from tpu_radix_join.ops.radix import (local_histogram, scatter_to_blocks,
                                      install_partition_observer)
from tpu_radix_join.ops.sorting import (install_sort_observer,
                                        set_default_sort_impl)
from tpu_radix_join.parallel.mesh import make_hierarchical_mesh, make_mesh
from tpu_radix_join.parallel.network_partitioning import (network_partition,
                                                          receive_checksums)
from tpu_radix_join.parallel.window import (ExchangeResult, Window,
                                            parse_exchange_mode)
from tpu_radix_join.performance.measurements import (BACKOFFMS, HEDGED,
                                                     HEDGEWIN, MEPOCH,
                                                     PACKRATIO, RANKLOST,
                                                     RETRYN, SPECWASTE, VCHK,
                                                     VCHKN, VFAIL, VREPAIR,
                                                     XSTAGES)
from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness import verify as _verify
from tpu_radix_join.robustness.membership import (LeaseBoard, RankJoined,
                                                  RankLost, StaleEpoch)
from tpu_radix_join.robustness.straggler import (StragglerDetected,
                                                 StragglerDetector,
                                                 board_progress, score_hedge)
from tpu_radix_join.utils.hostsync import host_readback
from tpu_radix_join.robustness.retry import (CAPACITY_OVERFLOW,
                                             RETRIES_EXHAUSTED,
                                             RETRYABLE_SIZING, RetryPolicy,
                                             classify_diagnostics,
                                             is_retryable_class)

#: the engine's regrow loop only reruns what bigger shapes can fix — a
#: transient tunnel outage must fall through to the caller (the service's
#: circuit breaker), not spin the capacity doubler
_SIZING_POLICY = RetryPolicy(retryable_classes=RETRYABLE_SIZING)


class JoinResult(NamedTuple):
    matches: int             # exact global match count (host uint64 sum)
    ok: bool                 # conservation invariants held (no overflow, counts conserved)
    partition_counts: np.ndarray  # per-device per-partition (or per-bucket) uint32
    diagnostics: Optional[dict] = None   # failure breakdown (see _flags_to_diag)


class MaterializedJoinResult(NamedTuple):
    """Materialized join output (the probe_match_rate capability,
    kernels.cu:314-411, end to end): matching rid pairs, globally gathered."""
    r_rid: np.ndarray        # uint32 [matches]
    s_rid: np.ndarray        # uint32 [matches]
    matches: int
    ok: bool                 # conservation + no per-tuple cap overflow
    diagnostics: Optional[dict] = None


def split_donation(program: str, skew: bool = False,
                   wide: bool = False) -> tuple:
    """``donate_argnums`` for the phase-split back-half programs.

    The split pipeline's intermediate buffers (shuffled receive windows,
    locally-partitioned bucket blocks, sorted bucket rows) are dead after
    the next program consumes them: a capacity retry reruns the whole
    attempt from the pristine ``r``/``s`` inputs (``_run_split``), never
    from a stale intermediate.  Donating them lets XLA reuse that HBM for
    the consumer's own temporaries instead of holding both generations
    live across the program boundary — the fix graftcheck's ``donation``
    rule demands (tools_jaxpr_audit.py).  The front-half programs
    (histogram, shuffle, fused pipeline) deliberately do NOT donate:
    their inputs are the retry loop's regeneration source and the
    pipelined-repeat path re-feeds them, which the entry registry
    (analysis/jaxpr/trace.py) records as reasoned waivers.

    One definition shared by the ``jax.jit`` sites below and the
    graftcheck entry registry, so the auditor checks the donation map
    the engine actually compiles with.  The tiny replicated inputs
    (``s_gh``: the [P] outer histogram) stay undonated — scalar-scale,
    and replicated buffers cannot alias a sharded output anyway.
    """
    return {
        # (rp_batch, rp_valid, sp_batch, sp_valid, sp_pid, [hot], s_gh)
        "probe": tuple(range(6 if skew else 5)),
        # (rp_batch, rp_valid, sp_batch, sp_valid, [hot])
        "lp": tuple(range(5 if skew else 4)),
        # (lr_blocks, ls_blocks)
        "bp": (0, 1),
        "bp_build": (0, 1),
        # sorted bucket-row lanes (key rows [+ hi rows], weight rows)
        "bp_probe": tuple(range(3 if wide else 2)),
        # (rp_batch, sp_batch, [hot])
        "materialize_probe": tuple(range(3 if skew else 2)),
    }[program]


def _as_compressed(batch: TupleBatch) -> CompressedBatch:
    """Identity-compression view: the sort probe compares full keys (safe
    across mixed partitions in the receive buffer; see network_partitioning
    docstring), so fanout-0 compression is used here."""
    return CompressedBatch(key_rem=batch.key, rid=batch.rid, key_rem_hi=batch.key_hi)


class HashJoin:
    """Host-side driver: owns the mesh, compiles the pipeline, runs joins.

    Equivalent of constructing ``hpcjoin::operators::HashJoin`` and calling
    ``join()`` (main.cpp:110-121), except construction compiles an SPMD
    program instead of wiring a task queue.
    """

    def __init__(self, config: JoinConfig, mesh: Optional[Mesh] = None,
                 measurements=None, plan_cache=None):
        # injectable device-unavailable site: lets tier-1 exercise the
        # TPU-init-failure -> CPU-fallback path (robustness/degrade.py)
        # without a real dead accelerator
        _faults.check(_faults.DEVICE_INIT, measurements)
        self.config = config
        # planner.PlanCache (or None): warm starts read the previous run's
        # converged window capacities instead of dispatching the sizing
        # pre-pass, and successful joins write theirs back
        self.plan_cache = plan_cache
        if mesh is not None:
            self.mesh = mesh
        elif config.num_hosts > 1:
            self.mesh = make_hierarchical_mesh(config.num_hosts,
                                               config.num_nodes)
        else:
            self.mesh = make_mesh(config.num_nodes, config.mesh_axis)
        if self.mesh.devices.size != config.num_nodes:
            raise ValueError(
                f"mesh has {self.mesh.devices.size} devices, config expects "
                f"{config.num_nodes}")
        self._compiled = {}
        self.measurements = measurements   # performance.Measurements or None
        # trace-time partition telemetry (PARTPASS spans, PARTFALLBACK):
        # ops/radix has no registry handle of its own, so the operator
        # donates this one for the lifetime of the process
        if measurements is not None:
            install_partition_observer(measurements)
            install_sort_observer(measurements)
        # the sort primitives are reached from deep inside ops/ with no
        # config in scope (that is the point of the ops/sorting switch),
        # so the configured impl binds process-wide; join entry points
        # re-assert it before tracing in case another engine rebound it
        set_default_sort_impl(config.sort_impl)
        # cooperative cancellation hook (service/deadline.py): an optional
        # ``callable(phase: str)`` consulted between pipeline phases; it
        # raises (e.g. DeadlineExceeded) to cancel the query between
        # programs — never mid-dispatch, so device state stays consistent
        self.cancel = None
        # elastic mesh recovery (robustness/membership + recovery), wired
        # attribute-style like ``cancel``: these are runtime services, not
        # compile-time configuration — JoinConfig stays frozen and
        # fingerprint-stable.  ``membership`` (MembershipView or None) is
        # polled at every phase boundary; ``elastic`` makes join_arrays
        # catch RankLost/StaleEpoch and finish on the survivors via
        # partition-level recompute; ``partition_manifest``
        # (checkpoint.PartitionManifest or None) records per-partition
        # completion so recovery resumes instead of restarting
        self.membership = None
        self.elastic = False
        self.partition_manifest = None
        # growth + hedging knobs (same attribute-style wiring):
        # ``elastic_grow`` makes a mid-join admission (RankJoined) finish
        # the join on the GROWN membership instead of raising;
        # ``hedge`` ("off"|"on"|"auto") enables straggler hedging —
        # "auto" additionally backs off while wasted speculation
        # (SPECWASTE) outruns manifest-fence wins (HEDGEWIN);
        # ``straggle_factor`` scales the compute.straggle site's
        # simulated per-rank slowdown (chaos runner / bench set it from
        # their seeds)
        self.elastic_grow = False
        self.hedge = "off"
        self.hedge_threshold = 0.5
        self.straggle_factor = 0.0
        self.straggle_unit_s = float(
            os.environ.get("TPU_RJ_STRAGGLE_UNIT_S", "0.05"))
        self._straggler_detector = None
        # Relation pair of the in-flight join(): recovery regenerates
        # global key lanes host-side from these deterministic specs — it
        # must never read a distributed array once a peer is dead (any
        # collective, including a gather, would hang on the old mesh)
        self._elastic_rel = None
        # resolved per join by _resolve_key_range (config.key_range): True
        # routes the 32-bit count probe to the full-range lexicographic
        # discipline instead of the 31-bit packed fast path
        self._full_range = False
        # static key bound hint for "auto" (set by Relation entry points)
        self._static_key_bound: Optional[int] = None
        # max key observed by this join's sizing pre-pass (the JHIST program
        # carries a pmax alongside the demand histograms) — feeds the packed
        # wire codec's key bound when no static Relation bound exists
        self._measured_key_bound: Optional[int] = None
        # wire-format plan resolved per join by _resolve_exchange_plan:
        # (codec, mode, key_bound, rid_bound_r, rid_bound_s).  Part of every
        # pipeline compile key — the bounds change the lowered program.
        self._xplan = ("off", 1, None, None, None)

    # ------------------------------------------------------------------ build
    def _histogram_fn(self, hot_bits: int = 0):
        """Phase 1+2 front half: per-(sender, destination) shuffle demand.

        The reference sizes each RMA window exactly from the global histogram
        in its window-allocation phase (Window.cpp:168-177, HashJoin.cpp:73-89)
        — a runtime-sized allocation XLA cannot express inside one program.
        The TPU equivalent is shape specialization: this small program computes
        the true send demands; the host rounds the max up to a power of two and
        compiles the shuffle program at that static capacity.  Guarantees the
        conservation invariant regardless of skew (SURVEY.md §7.4 item 1).

        Also returns the global histograms (for host-side hot-partition
        detection, operators/skew.py) and, when ``hot_bits`` marks a hot set,
        the per-device hot inner-tuple count (the exact capacity for the
        replication buffer) with demands adjusted to the split routing:
        hot R leaves the shuffle, hot S spreads round-robin.
        """
        cfg = self.config
        ax = cfg.mesh_axes
        n = cfg.num_nodes
        fanout = cfg.network_fanout_bits

        def body(r: TupleBatch, s: TupleBatch):
            r_pid, r_hist = compute_local_histogram(r, fanout)
            s_pid, s_hist = compute_local_histogram(s, fanout)
            r_ghist = compute_global_histogram(r_hist, ax)
            s_ghist = compute_global_histogram(s_hist, ax)
            r_hist_eff, s_hist_eff = r_hist, s_hist
            r_gh_eff, s_gh_eff = r_ghist, s_ghist
            spread_demand = jnp.zeros((n,), jnp.uint32)
            hot_r_count = jnp.zeros((1,), jnp.uint32)
            if hot_bits:
                r_hist_eff = skew.mask_hot(r_hist, hot_bits)
                s_hist_eff = skew.mask_hot(s_hist, hot_bits)
                r_gh_eff = skew.mask_hot(r_ghist, hot_bits)
                s_gh_eff = skew.mask_hot(s_ghist, hot_bits)
                is_hot_s = skew.is_hot(s_pid, hot_bits)
                spread_demand = local_histogram(
                    skew.spread_destinations(s.rid, n), n, valid=is_hot_s)
                hot_r_count = jnp.sum(
                    skew.is_hot(r_pid, hot_bits).astype(jnp.uint32)
                ).reshape(1)
            assignment = compute_partition_assignment(
                r_gh_eff, s_gh_eff, n, cfg.assignment_policy)
            dest_onehot = (
                assignment[None, :] == jnp.arange(n, dtype=jnp.uint32)[:, None]
            )  # [N_dest, P]
            r_demand = jnp.sum(jnp.where(dest_onehot, r_hist_eff[None, :], 0),
                               axis=1)
            s_demand = jnp.sum(jnp.where(dest_onehot, s_hist_eff[None, :], 0),
                               axis=1) + spread_demand
            # max key lanes ride the sizing pass for free (the tuples are
            # already streaming through): the packed wire codec derives its
            # key bound from this when no static Relation bound exists.
            # Per-lane maxes are independent upper bounds, so the wide bound
            # (max_hi << 32 | max_lo) is valid even when the lane maxes come
            # from different tuples.
            kmax_lo = jnp.maximum(jnp.max(r.key), jnp.max(s.key))
            kmax_hi = (jnp.uint32(0) if r.key_hi is None
                       else jnp.maximum(jnp.max(r.key_hi), jnp.max(s.key_hi)))
            keymax = jax.lax.pmax(jnp.stack([kmax_lo, kmax_hi]), ax)
            return (r_demand.astype(jnp.uint32), s_demand.astype(jnp.uint32),
                    r_ghist, s_ghist, hot_r_count, keymax)

        spec = P(cfg.mesh_axes)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec, spec),
            out_specs=(spec, spec, P(), P(), spec, P())))

    def _keys_in_contract(self, r: TupleBatch, s: TupleBatch,
                          materialize: bool = False) -> jnp.ndarray:
        """Input contract check (traced): real keys must stay below the
        padding sentinels (tuples.py) — and below the 31-bit merge-count
        packing limit when the narrow sort-merge probe is the branch in use
        (the materializing probe never is: its searchsorted/union-scan
        disciplines accept the full sub-sentinel range).  Violations flip
        ``ok`` rather than silently overcounting against padding slots."""
        cfg = self.config
        uses_merge = ((not materialize) and r.key_hi is None
                      and cfg.sort_probe and not self._full_range)
        key_cap = jnp.uint32(MAX_MERGE_KEY + 1 if uses_merge else R_PAD_KEY)
        return (jnp.max(_sentinel_lane(r)) < key_cap) & (
            jnp.max(_sentinel_lane(s)) < key_cap)

    @staticmethod
    def _concat_hot(batch: TupleBatch, hot_batch) -> TupleBatch:
        """Append the replicated hot build side (operators/skew.py) to a
        local probe input; no-op without a skew plan."""
        if hot_batch is None:
            return batch
        return TupleBatch(
            key=jnp.concatenate([batch.key, hot_batch.key]),
            rid=jnp.concatenate([batch.rid, hot_batch.rid]),
            key_hi=None if batch.key_hi is None else jnp.concatenate(
                [batch.key_hi, hot_batch.key_hi]))

    @classmethod
    def _concat_hot_valid(cls, batch: TupleBatch, valid, hot_batch):
        """(batch + hot, valid + hot-valid) for paths that carry an explicit
        valid lane (the bucket discipline's local radix pass): the hot
        block's padding slots are R sentinels, so validity IS the sentinel
        test — one definition shared by the fused and phase-split pipelines
        so they cannot diverge."""
        if hot_batch is None:
            return batch, valid
        hot_valid = _sentinel_lane(hot_batch) < jnp.uint32(R_PAD_KEY)
        return (cls._concat_hot(batch, hot_batch),
                jnp.concatenate([valid, hot_valid]))

    # phase keys nested inside another recorded phase (SNETCOMPL in JMPI;
    # BPBUILD/BPPROBE in JPROC): rolled back from their own columns on a
    # superseded attempt but not double-added to MWINWAIT
    _NESTED_PHASES = frozenset({"SNETCOMPL", "BPBUILD", "BPPROBE"})

    @classmethod
    def _rollback_attempt(cls, m, dts) -> None:
        """Reclassify a superseded attempt's phase times into MWINWAIT (the
        reference's stall column, Measurements.cpp:272-349) so the phase
        columns report only the attempt that produced the result."""
        m.incr("RETRIES")
        m.add_time_us("MWINWAIT",
                      sum(v for k, v in dts.items()
                          if k not in cls._NESTED_PHASES))
        for k, v in dts.items():
            if v:
                m.times_us[k] -= v

    # ------------------------------------------------------- plan cache
    def _membership_epoch(self) -> int:
        """Current membership epoch (0 = boot mesh, no view attached).
        Part of every compiled-program key and capacity fingerprint: work
        stamped with an older epoch must never run after the mesh shrank
        — its collectives would address a dead peer."""
        return self.membership.epoch if self.membership is not None else 0

    def _cache_config_fp(self) -> dict:
        """The JoinConfig fields that window capacities depend on — two
        configs agreeing here size identical shuffle windows for the same
        inputs, so a cached capacity transfers between them."""
        cfg = self.config
        return {"num_nodes": cfg.num_nodes, "num_hosts": cfg.num_hosts,
                "network_fanout_bits": cfg.network_fanout_bits,
                "local_fanout_bits": cfg.local_fanout_bits,
                "key_bits": cfg.key_bits, "two_level": cfg.two_level,
                "probe_algorithm": cfg.probe_algorithm,
                "assignment_policy": cfg.assignment_policy,
                "window_sizing": cfg.window_sizing,
                "exchange_codec": cfg.exchange_codec,
                "exchange_stages": cfg.exchange_stages,
                # membership fence: capacities converged on the boot mesh
                # must not warm-start a shrunken survivor mesh (and vice
                # versa) — the epoch is part of the capacity identity
                "membership_epoch": self._membership_epoch()}

    def _cache_eligible(self) -> bool:
        """Warm-start capacities only apply where the sizing pre-pass would
        run and its result is a pure function of (inputs, config): the n==1
        specialization never sizes, "static" sizing is already free, and a
        skew plan carries measured hot sets the cache does not model."""
        return (self.plan_cache is not None
                and not self._single_node_sort_probe()
                and self.config.window_sizing == "measured"
                and self.config.skew_threshold is None)

    def _cache_store_capacities(self, r, s, cap_r: int, cap_s: int,
                                local_slack: int, ok: bool) -> None:
        """After a successful join, persist the *converged* capacities
        (post any overflow-retry doublings) so the next run with this
        (profile, shapes, config) skips the sizing pre-pass entirely."""
        if not ok or not self._cache_eligible():
            return
        self.plan_cache.store(
            r.size, s.size, self._cache_config_fp(),
            capacities={"cap_r": cap_r, "cap_s": cap_s,
                        "local_slack": local_slack})

    def _single_node_sort_probe(self) -> bool:
        """True when the pipeline takes the n==1 specialization (no shuffle,
        no windows): the sizing pre-pass would compute capacities nothing
        reads, so the driver skips it and uses a fixed dummy capacity."""
        cfg = self.config
        return cfg.num_nodes == 1 and cfg.sort_probe

    def _measure_capacities(self, r: TupleBatch, s: TupleBatch,
                            shuffles: bool = True):
        """Window allocation (HashJoin.cpp phase 2): (cap_r, cap_s, skew_plan)
        — static block capacity = next power of two >= worst (sender, dest)
        demand, or the allocation-factor estimate in "static" mode (no sizing
        pre-pass).

        ``skew_plan`` is None, or ``(hot_bits, hot_cap)`` when
        config.skew_threshold detects hot partitions in the measured global
        histograms: the pipeline is then compiled with the split routing
        (operators/skew.py) and a replication buffer of ``hot_cap`` slots
        (exact worst per-device hot inner count, measured by a second sizing
        dispatch).

        ``shuffles=False`` marks a pipeline variant that takes the n==1
        no-shuffle specialization: capacities are never read, so skip the
        sizing program and return fixed dummies."""
        cfg = self.config
        n = cfg.num_nodes
        if not shuffles:
            return 8, 8, None
        if cfg.window_sizing == "static":
            return (cfg.shuffle_block_capacity(r.size // n),
                    cfg.shuffle_block_capacity(s.size // n), None)
        r_demand, s_demand, r_gh, s_gh, _, keymax = self._run_hist(r, s, 0)
        km = self._to_host(keymax)
        self._measured_key_bound = ((int(km[1]) << 32) | int(km[0])) + 1

        def cap(demand):
            worst = max(1, int(self._to_host(demand).max()))
            return max(8, 1 << (worst - 1).bit_length())

        skew_plan = None
        if cfg.skew_threshold is not None and n > 1:
            hot = skew.detect_hot_partitions(
                host_readback(r_gh), host_readback(s_gh), cfg.skew_threshold,
                num_nodes=n)
            if hot.any():
                hot_bits = skew.hot_mask_bits(hot)
                r_demand, s_demand, _, _, hot_counts, _ = self._run_hist(
                    r, s, hot_bits)
                skew_plan = (hot_bits, cap(hot_counts))

        return cap(r_demand), cap(s_demand), skew_plan

    def _compile_timed(self, key, build):
        """Compile-and-cache with JCOMPILE attribution — the single place
        compile time enters the registry (the reference has no runtime
        compilation; this tag keeps it out of every phase column).  Running
        outer timers (JTOTAL, SWINALLOC) are shifted past the compile so the
        reported phases stay reference-comparable: the reference's JTOTAL has
        no compile in it, and a compile-dominated JTOTAL understated the
        engine's CLI throughput ~50x at 20M (VERDICT r3 weak #5).

        Keys are prefixed with the membership epoch: a program lowered
        against the pre-shrink mesh is fenced out after a rank loss
        instead of deadlocking its collectives against a dead peer."""
        key = (self._membership_epoch(), key)
        if key not in self._compiled:
            m = self.measurements
            if m:
                m.start("JCOMPILE")
            self._compiled[key] = build()
            if m:
                dt = m.stop("JCOMPILE")
                m.exclude_from_running(dt)
        return self._compiled[key]

    def _run_hist(self, r: TupleBatch, s: TupleBatch, hot_bits: int):
        """AOT-compile (JCOMPILE) and execute (JHIST) the sizing program.

        JHIST is the reference's histogram-phase column
        (Measurements.cpp:139,183-244): here the local+global histogram work
        runs inside the sizing program, so its execution time — separated
        from compilation — is the honest analog."""
        m = self.measurements
        n = self.config.num_nodes
        key = ("hist", hot_bits, r.size // n, s.size // n,
               r.key_hi is None, s.key_hi is None,
               getattr(r.key, "sharding", None),
               getattr(s.key, "sharding", None))
        fn = self._compile_timed(
            key, lambda: self._histogram_fn(hot_bits).lower(r, s).compile())
        if m:
            m.start("JHIST")
        out = fn(r, s)
        if m:
            m.stop("JHIST", fence=out)
        return out

    def _pipeline_fn(self, local_size_r: int, local_size_s: int,
                     cap_r: int, cap_s: int, local_slack: int = 1,
                     skew_plan=None, verify: bool = False):
        cfg = self.config
        ax = cfg.mesh_axes
        n = cfg.num_nodes
        fanout = cfg.network_fanout_bits
        num_p = cfg.network_partition_count
        win_r, win_s = self._make_windows(cap_r, cap_s)

        def body(r: TupleBatch, s: TupleBatch):
            keys_ok = self._keys_in_contract(r, s)

            if n == 1 and cfg.sort_probe:
                # Single-node specialization: the all_to_all is an identity
                # and the sort-merge probe needs no pre-partitioned input
                # (the reference runs NetworkPartitioning even at 1 node,
                # HashJoin.cpp:98-105, because its pointer-chasing BuildProbe
                # requires partitioned buffers — the merge probe does not),
                # so phases 2-5 vanish and JPROC is the probe alone.
                if r.key_hi is not None:
                    counts, maxw = merge_count_wide_per_partition(
                        r.key, r.key_hi, s.key, s.key_hi, fanout,
                        return_max_weight=True)
                elif self._full_range:
                    counts, maxw = merge_count_per_partition_full(
                        r.key, s.key, fanout, return_max_weight=True)
                else:
                    counts, maxw = merge_count_per_partition(
                        r.key, s.key, fanout, return_max_weight=True)
                # overflow-risk bound: the scalar pre-test
                # maxw * |S| < 2**32 clears every realistic workload with
                # zero extra passes; only suspect workloads pay the
                # per-partition histogram refinement under the cond (no
                # shuffle histograms exist on this no-shuffle path)
                scalar_limit = (2**32 - 1) // max(1, s.key.shape[0])

                def _refine(mw):
                    s_pid = s.key & jnp.uint32(num_p - 1)
                    return self._count_risk(mw,
                                            local_histogram(s_pid, num_p))

                count_risk = jax.lax.cond(
                    maxw > jnp.uint32(scalar_limit),
                    _refine,
                    # same varying annotation as the refine branch
                    lambda mw: mw > jnp.uint32(0xFFFFFFFF),
                    maxw)
                zero = jnp.uint32(0)
                flags = jnp.stack([
                    jax.lax.psum((~keys_ok).astype(jnp.uint32), ax),
                    zero, zero, zero, zero, zero,
                    jax.lax.psum(count_risk.astype(jnp.uint32), ax),
                ])
                return counts, flags

            # ---- Phases 1-4: histograms, window allocation (implicit in
            # static shapes), all_to_all shuffle, conservation barrier
            # (HashJoin.cpp:58-121) — shared with the materialize variant ----
            (rp, sp, hot_batch, lost_r, lost_s, hot_overflow, conserve_bad,
             s_gh) = self._shuffle(r, s, win_r, win_s, skew_plan)

            # ---- Phase 5/6: local processing (HashJoin.cpp:131-204) ----
            counts, local_overflow, count_risk, sort_checks = \
                self._local_process(
                    rp.batch, rp.valid, sp.batch, sp.valid, sp.pid, hot_batch,
                    cap_r, cap_s, local_slack, s_hist_bound=s_gh,
                    checksum_axis=ax if verify else None)

            # Failure breakdown, globally reduced (SURVEY.md section 5.3: the
            # reference aborts on any failure; here every mode is counted so
            # the driver can distinguish retryable capacity shortfalls from
            # contract violations — and grow only the shape that fell short
            # (the reference sizes each relation's window separately,
            # Window.cpp:168-177).
            flags = jnp.stack([
                jax.lax.psum((~keys_ok).astype(jnp.uint32), ax),
                lost_r.astype(jnp.uint32),
                lost_s.astype(jnp.uint32),
                conserve_bad.astype(jnp.uint32),
                jax.lax.psum(local_overflow.astype(jnp.uint32), ax),
                hot_overflow.astype(jnp.uint32),
                jax.lax.psum(count_risk.astype(jnp.uint32), ax),
            ])
            if verify:
                # integrity fingerprints recomputed downstream of the
                # exchange (robustness/verify.py): what each stage received,
                # alternating R/S per set.  The host compares them against
                # the pre-exchange fingerprints of what was sent.
                vsets = [receive_checksums(rp, num_p, ax),
                         receive_checksums(sp, num_p, ax)]
                if sort_checks is not None:
                    vsets.extend(sort_checks)
                return counts, flags, jnp.stack(vsets)
            return counts, flags

        spec = P(ax)
        out_specs = (spec, P(), P()) if verify else (spec, P())
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(spec, spec),
            out_specs=out_specs,
        ))

    def _shuffle_fn(self, cap_r: int, cap_s: int, skew_plan=None,
                    materialize: bool = False):
        """Front half of the phase-split pipeline (config.measure_phases):
        phases 1-4 as their own program so the host timer sees JMPI — the
        reference's network-partitioning column (Measurements.cpp:140,
        HashJoin.cpp:91-121) — separately from local processing.
        ``materialize`` selects the materializing probe's key contract (pad
        sentinels only — no 31-bit merge packing limit), matching the fused
        _materialize_fn."""
        cfg = self.config
        ax = cfg.mesh_axes
        win_r, win_s = self._make_windows(cap_r, cap_s)

        def body(r: TupleBatch, s: TupleBatch):
            keys_ok = self._keys_in_contract(r, s, materialize=materialize)
            (rp, sp, hot_batch, lost_r, lost_s, hot_overflow, conserve_bad,
             s_gh) = self._shuffle(r, s, win_r, win_s, skew_plan)
            sflags = jnp.stack([
                jax.lax.psum((~keys_ok).astype(jnp.uint32), ax),
                lost_r.astype(jnp.uint32),
                lost_s.astype(jnp.uint32),
                conserve_bad.astype(jnp.uint32),
                hot_overflow.astype(jnp.uint32),
            ])
            if materialize:
                # the materializing probe consumes only the two batches (it
                # re-derives nothing from valid/pid) — don't ship buffers
                # across the program boundary that the consumer drops
                out = (rp.batch, sp.batch, sflags)
            else:
                out = (rp.batch, rp.valid, sp.batch, sp.valid, sp.pid, sflags)
            if skew_plan:
                out = out + (hot_batch,)
            if not materialize:
                # the probe program's overflow-risk bound reads the global
                # outer histogram — ship the tiny [P] array instead of
                # re-histogramming the receive buffers there
                out = out + (s_gh,)
            return out

        spec = P(ax)
        # hot_batch is value-replicated (all_gather) but shard_map's static
        # replication check cannot prove it, so it travels "sharded": each
        # device keeps its identical copy as its shard and the probe program
        # slices the same copy back out — same bytes per device either way.
        if materialize:
            out_specs = (spec, spec, P())
        else:
            out_specs = (spec, spec, spec, spec, spec, P())
        if skew_plan:
            out_specs = out_specs + (spec,)
        if not materialize:
            out_specs = out_specs + (P(),)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec, spec),
            out_specs=out_specs))

    def _probe_fn(self, cap_r: int, cap_s: int, local_slack: int,
                  skew_plan=None):
        """Back half of the phase-split pipeline: local processing on the
        shuffled buffers, timed by the host as JPROC."""
        cfg = self.config
        ax = cfg.mesh_axes

        def run(rp_batch, rp_valid, sp_batch, sp_valid, sp_pid, hot_batch,
                s_gh):
            counts, local_overflow, count_risk, _ = self._local_process(
                rp_batch, rp_valid, sp_batch, sp_valid, sp_pid, hot_batch,
                cap_r, cap_s, local_slack, s_hist_bound=s_gh)
            return (counts,
                    jax.lax.psum(local_overflow.astype(jnp.uint32), ax),
                    jax.lax.psum(count_risk.astype(jnp.uint32), ax))

        spec = P(ax)
        if skew_plan:
            def body(rpb, rpv, spb, spv, spp, hot, s_gh):
                return run(rpb, rpv, spb, spv, spp, hot, s_gh)
            in_specs = (spec, spec, spec, spec, spec, spec, P())
        else:
            def body(rpb, rpv, spb, spv, spp, s_gh):
                return run(rpb, rpv, spb, spv, spp, None, s_gh)
            in_specs = (spec, spec, spec, spec, spec, P())
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=in_specs,
            out_specs=(spec, P(), P())),
            donate_argnums=split_donation("probe", bool(skew_plan)))

    def _split_key(self, r: TupleBatch, s: TupleBatch, cap_r: int, cap_s: int,
                   skew_plan):
        n = self.config.num_nodes
        return (r.size // n, s.size // n, cap_r, cap_s, skew_plan,
                r.key_hi is None, s.key_hi is None, self._full_range,
                self._xplan,
                getattr(r.key, "sharding", None),
                getattr(s.key, "sharding", None))

    def _run_shuffle_program(self, r: TupleBatch, s: TupleBatch, cap_r: int,
                             cap_s: int, skew_plan, base,
                             materialize: bool = False):
        """Compile + execute the standalone shuffle program, timing JMPI and
        its nested completion wait.  Returns (shuffled outputs, shuffle-flag
        ndarray, phase-dt dict)."""
        m = self.measurements
        fn_mpi = self._compile_timed(
            ("mpim" if materialize else "mpi",) + base,
            lambda: self._shuffle_fn(cap_r, cap_s, skew_plan,
                                     materialize).lower(r, s).compile())
        dts = {}
        if m:
            m.start("JMPI")
        shuffled = fn_mpi(r, s)
        if m:
            # the dispatch has returned but the exchange may still be in
            # flight; the fence wait is the network-completion barrier —
            # SNETCOMPL (Measurements.cpp:176-178, Window completion wait).
            # JMPI spans dispatch + completion, as the reference's network
            # phase spans Puts + the flush barrier.
            m.start("SNETCOMPL")
            dts["SNETCOMPL"] = m.stop("SNETCOMPL", fence=shuffled)
            dts["JMPI"] = m.stop("JMPI", fence=shuffled)
        sflags = host_readback(shuffled[2 if materialize else 5])
        return shuffled, sflags, dts

    def _run_split(self, r: TupleBatch, s: TupleBatch, cap_r: int, cap_s: int,
                   local_slack: int, skew_plan):
        """Execute one attempt as separate phase programs, recording JMPI and
        JPROC — plus SLOCPREP on the bucket path, where local partitioning
        runs as its own program (the reference's LP/BP task columns,
        Measurements.cpp:372-542) — from the host clock (the fused path can
        only time their sum).  Returns (counts, flags ndarray, phase-dt dict
        keyed by registry tag; SNETCOMPL is nested inside JMPI)."""
        m = self.measurements
        cfg = self.config
        base = self._split_key(r, s, cap_r, cap_s, skew_plan)
        shuffled, sflags, dts = self._run_shuffle_program(
            r, s, cap_r, cap_s, skew_plan, base)
        if cfg.bucket_path:
            # three-program chain: the second radix pass is its own program
            # timed as SLOCPREP; with a skew plan the shuffle's trailing
            # replicated-hot output joins the LP program's inputs
            lp_args = tuple(shuffled[:4])
            if skew_plan:
                lp_args = lp_args + (shuffled[6],)
            fn_lp = self._compile_timed(
                ("lprep", local_slack) + base,
                lambda: self._lp_fn(cap_r, cap_s, local_slack, skew_plan
                                    ).lower(*lp_args).compile())
            if m:
                m.start("SLOCPREP")
            lr_blocks, ls_blocks, local_flag = fn_lp(*lp_args)
            if m:
                dts["SLOCPREP"] = m.stop("SLOCPREP",
                                         fence=(lr_blocks, ls_blocks))
            lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                               skew_plan)
            wide = r.key_hi is not None
            if m:
                # capacity-padded slots the build/probe stages process (the
                # reference's per-task tuple sums, BPBUILDTUPLES/
                # BPPROBETUPLES, Measurements.cpp:471-542); retried attempts
                # count too — those slots were processed
                nb = cfg.local_partition_count
                n = cfg.num_nodes
                m.incr("BPBUILDTUPLES", n * nb * lcap_r)
                m.incr("BPPROBETUPLES", n * nb * lcap_s)
            if max(lcap_r, lcap_s) <= DENSE_BUCKET_LIMIT:
                # dense equality-reduction discipline: no build structure
                # exists (the GPU shared-memory probe analog), so the whole
                # program is the probe stage
                fn_bp = self._compile_timed(
                    ("bprobe", local_slack) + base,
                    lambda: self._bp_fn(cap_r, cap_s, local_slack, skew_plan
                                        ).lower(lr_blocks,
                                                ls_blocks).compile())
                if m:
                    m.start("JPROC")
                counts, count_risk = fn_bp(lr_blocks, ls_blocks)
                if m:
                    dts["JPROC"] = m.stop("JPROC", fence=counts)
                    m.add_time_us("BPPROBE", dts["JPROC"])
                    dts["BPPROBE"] = dts["JPROC"]
            else:
                # merge discipline: the batched row sort is the build stage
                # (BPBUILD) and the weight scan the probe stage (BPPROBE),
                # each its own program so the host clock times them — the
                # reference's build/probe sub-columns (Measurements.cpp:
                # 471-542); JPROC spans both, as its BuildProbe task does
                fn_bb = self._compile_timed(
                    ("bpbuild", local_slack) + base,
                    lambda: self._bp_build_fn(
                        cap_r, cap_s, local_slack, skew_plan, wide
                    ).lower(lr_blocks, ls_blocks).compile())
                if m:
                    m.start("JPROC")
                    m.start("BPBUILD")
                sorted_lanes = fn_bb(lr_blocks, ls_blocks)
                if m:
                    dts["BPBUILD"] = m.stop("BPBUILD", fence=sorted_lanes)
                fn_bp2 = self._compile_timed(
                    ("bpprobe", local_slack) + base,
                    lambda: self._bp_probe_fn(
                        cap_r, cap_s, local_slack, skew_plan, wide
                    ).lower(*sorted_lanes).compile())
                if m:
                    m.start("BPPROBE")
                counts, count_risk = fn_bp2(*sorted_lanes)
                if m:
                    dts["BPPROBE"] = m.stop("BPPROBE", fence=counts)
                    dts["JPROC"] = m.stop("JPROC", fence=counts)
        else:
            probe_args = tuple(shuffled[:5]) + tuple(shuffled[6:])
            fn_proc = self._compile_timed(
                ("proc", local_slack) + base,
                lambda: self._probe_fn(cap_r, cap_s, local_slack, skew_plan
                                       ).lower(*probe_args).compile())
            if m:
                m.start("JPROC")
            counts, local_flag, count_risk = fn_proc(*probe_args)
            if m:
                dts["JPROC"] = m.stop("JPROC", fence=counts)
        flags = np.array([sflags[0], sflags[1], sflags[2], sflags[3],
                          int(host_readback(local_flag)), sflags[4],
                          int(host_readback(count_risk))],
                         dtype=np.uint32)
        return counts, flags, dts

    def _materialize_probe_fn(self, rate_cap: int, skew_plan=None):
        """Back half of the materializing phase split: the rid-pair-emitting
        probe on the shuffled buffers (JPROC)."""
        cfg = self.config
        ax = cfg.mesh_axes

        def run(rp_batch, sp_batch, hot_batch):
            rb = self._concat_hot(rp_batch, hot_batch)
            if cfg.chunk_size:
                mm = probe_materialize_chunked(
                    _as_compressed(rb), _as_compressed(sp_batch),
                    rate_cap, cfg.chunk_size)
            else:
                mm = probe_materialize(_as_compressed(rb),
                                       _as_compressed(sp_batch), rate_cap)
            return (mm.r_rid, mm.s_rid, mm.valid,
                    jax.lax.psum(mm.overflow.astype(jnp.uint32), ax))

        spec = P(ax)
        if skew_plan:
            def body(rpb, spb, hot):
                return run(rpb, spb, hot)
            in_specs = (spec, spec, spec)
        else:
            def body(rpb, spb):
                return run(rpb, spb, None)
            in_specs = (spec, spec)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=in_specs,
            out_specs=(spec, spec, spec, P())),
            donate_argnums=split_donation("materialize_probe",
                                          bool(skew_plan)))

    def _run_split_materialize(self, r: TupleBatch, s: TupleBatch,
                               cap_r: int, cap_s: int, rate_cap: int,
                               skew_plan):
        """Materializing attempt as two programs (shuffle -> probe), the
        measure_phases discipline for join_materialize.  Returns
        (r_rid, s_rid, valid, flags ndarray, phase-dt dict)."""
        m = self.measurements
        base = self._split_key(r, s, cap_r, cap_s, skew_plan)
        shuffled, sflags, dts = self._run_shuffle_program(
            r, s, cap_r, cap_s, skew_plan, base, materialize=True)
        probe_args = tuple(shuffled[:2]) + tuple(shuffled[3:])
        fn_mp = self._compile_timed(
            ("mprobe", rate_cap) + base,
            lambda: self._materialize_probe_fn(rate_cap, skew_plan
                                               ).lower(*probe_args).compile())
        if m:
            m.start("JPROC")
        r_rid, s_rid, valid, ovf = fn_mp(*probe_args)
        if m:
            dts["JPROC"] = m.stop("JPROC", fence=valid)
        flags = np.array([sflags[0], sflags[1], sflags[2], sflags[3],
                          int(host_readback(ovf)), sflags[4]], dtype=np.uint32)
        return r_rid, s_rid, valid, flags, dts

    def _bucket_caps(self, cap_r: int, cap_s: int, local_slack: int,
                     skew_plan=None):
        """Per-bucket capacities of the second radix pass.  With a skew
        plan the replicated hot build side (n * hot_cap gathered tuples)
        rides through local partitioning too, so the inner total includes
        it — concentrated in the hot partitions' buckets, hence the same
        allocation_factor slack plus retry doubling as everywhere else."""
        cfg = self.config
        n = cfg.num_nodes
        nb = cfg.local_partition_count
        hot_total = n * skew_plan[1] if skew_plan else 0
        return (cfg.bucket_capacity(n * cap_r + hot_total, nb) * local_slack,
                cfg.bucket_capacity(n * cap_s, nb) * local_slack)

    @staticmethod
    def _guarded_bucket_counts(count_fn, lcap_r: int, lcap_s: int):
        """(counts, count-overflow risk) for a bucketized counting callable
        ``count_fn(return_max_weight=...)``: a bucket's count is statically
        <= lcap_r * lcap_s, so the runtime max-weight bound
        (:meth:`_count_risk` rationale) only runs when that product can
        reach 2**32 — ONE definition shared by the fused probe and the
        phase-split BPPROBE program so the two cannot diverge."""
        if lcap_r * lcap_s < (1 << 32):
            counts = count_fn(return_max_weight=False)
            # statically-safe False that still carries the counts' device-
            # varying annotation (a bare constant would trip shard_map's
            # psum varying check at the flag-assembly site)
            return counts, jnp.sum(counts) < jnp.uint32(0)
        counts, maxw = count_fn(return_max_weight=True)
        return counts, maxw > jnp.uint32(0xFFFFFFFF // lcap_s)

    def _bucket_probe(self, lr_blocks: TupleBatch, ls_blocks: TupleBatch,
                      lcap_r: int, lcap_s: int):
        """Per-bucket counting over capacity-padded bucket blocks; wide keys'
        hi lanes ride the same blocks and the probe's three-key batched row
        sort compares full (hi, lo) pairs.  Returns (counts, count-overflow
        risk)."""
        args = self._bucket_row_args(lr_blocks, ls_blocks, lcap_r, lcap_s)
        return self._guarded_bucket_counts(
            functools.partial(probe_count_bucketized, *args),
            lcap_r, lcap_s)

    def _lp_fn(self, cap_r: int, cap_s: int, local_slack: int,
               skew_plan=None):
        """Local-partitioning program of the bucket-path phase split:
        SLOCPREP, the reference's local-preparation column
        (Measurements.cpp:176-178; LocalPartitioning task time).  With a
        skew plan the replicated hot build side arrives as a sixth input
        and is appended to the inner pass (valid = non-sentinel slots)."""
        cfg = self.config
        ax = cfg.mesh_axes
        fanout = cfg.network_fanout_bits
        lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                           skew_plan)

        def run(rp_batch, rp_valid, sp_batch, sp_valid, hot_batch):
            rp_batch, rp_valid = self._concat_hot_valid(rp_batch, rp_valid,
                                                        hot_batch)
            lr = local_partition(rp_batch, rp_valid, fanout,
                                 cfg.local_fanout_bits, lcap_r, "inner",
                                 impl=cfg.partition_impl)
            ls = local_partition(sp_batch, sp_valid, fanout,
                                 cfg.local_fanout_bits, lcap_s, "outer",
                                 impl=cfg.partition_impl)
            ovf = jax.lax.psum(
                (lr.overflow + ls.overflow).astype(jnp.uint32), ax)
            return lr.blocks, ls.blocks, ovf

        spec = P(ax)
        if skew_plan:
            def body(rpb, rpv, spb, spv, hot):
                return run(rpb, rpv, spb, spv, hot)
            in_specs = (spec,) * 5
        else:
            def body(rpb, rpv, spb, spv):
                return run(rpb, rpv, spb, spv, None)
            in_specs = (spec,) * 4
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=in_specs,
            out_specs=(spec, spec, P())),
            donate_argnums=split_donation("lp", bool(skew_plan)))

    def _bp_fn(self, cap_r: int, cap_s: int, local_slack: int,
               skew_plan=None):
        """Build-probe program of the bucket-path phase split (JPROC: the
        BuildProbe task time, Measurements.cpp:471-542)."""
        cfg = self.config
        ax = cfg.mesh_axes
        lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                           skew_plan)

        def body(lr_blocks, ls_blocks):
            counts, risk = self._bucket_probe(lr_blocks, ls_blocks,
                                              lcap_r, lcap_s)
            return counts, jax.lax.psum(risk.astype(jnp.uint32), ax)

        spec = P(ax)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec, spec),
            out_specs=(spec, P())),
            donate_argnums=split_donation("bp"))

    def _bucket_row_args(self, lr_blocks: TupleBatch, ls_blocks: TupleBatch,
                         lcap_r: int, lcap_s: int):
        nb = self.config.local_partition_count
        return (lr_blocks.key.reshape(nb, lcap_r),
                ls_blocks.key.reshape(nb, lcap_s),
                None if lr_blocks.key_hi is None
                else lr_blocks.key_hi.reshape(nb, lcap_r),
                None if ls_blocks.key_hi is None
                else ls_blocks.key_hi.reshape(nb, lcap_s))

    def _bp_build_fn(self, cap_r: int, cap_s: int, local_slack: int,
                     skew_plan, wide: bool):
        """BPBUILD program: the batched per-bucket row sort as its own
        program so the host clock times the build stage separately — the
        reference's hash-table-build column (BPBUILD + tuple sums,
        Measurements.cpp:471-505).  The sorted-row layout is this
        framework's hash table (see ops/build_probe.bucket_rows_sort)."""
        cfg = self.config
        ax = cfg.mesh_axes
        lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                           skew_plan)

        def body(lr_blocks, ls_blocks):
            return bucket_rows_sort(*self._bucket_row_args(
                lr_blocks, ls_blocks, lcap_r, lcap_s))

        spec = P(ax)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec, spec),
            out_specs=(spec,) * (3 if wide else 2)),
            donate_argnums=split_donation("bp_build"))

    def _bp_probe_fn(self, cap_r: int, cap_s: int, local_slack: int,
                     skew_plan, wide: bool):
        """BPPROBE program: the weight scan over pre-sorted bucket rows —
        the reference's probe-loop column (BPPROBE, Measurements.cpp:
        506-542) — with the same uint32-overflow guard as the fused path."""
        cfg = self.config
        ax = cfg.mesh_axes
        lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                           skew_plan)

        def body(*lanes):
            counts, risk = self._guarded_bucket_counts(
                functools.partial(bucket_rows_count, *lanes),
                lcap_r, lcap_s)
            return counts, jax.lax.psum(risk.astype(jnp.uint32), ax)

        spec = P(ax)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec,) * (3 if wide else 2),
            out_specs=(spec, P())),
            donate_argnums=split_donation("bp_probe", wide=wide))

    @staticmethod
    def _count_risk(max_weight, s_hist) -> jnp.ndarray:
        """True when some partition's uint32 match count could have wrapped.

        count_p <= max_weight * outer_p (each matched outer tuple contributes
        at most the max inner multiplicity), so the exact integer test
        ``outer_p > (2**32 - 1) // max_weight`` flags every workload whose
        count might reach 2**32 — conservatively (a flagged count may still
        be below the bound), never the other way.  The reference cannot wrap
        by construction (uint64 RESULT_COUNTER, HashJoin.h:26); uint32
        device counts + this guard are the no-device-int64 equivalent
        (VERDICT r3 weak #4)."""
        limit = jnp.uint32(0xFFFFFFFF) // jnp.maximum(max_weight,
                                                      jnp.uint32(1))
        return jnp.any(s_hist > limit)

    def _local_process(self, rp_batch: TupleBatch, rp_valid, sp_batch: TupleBatch,
                       sp_valid, sp_pid, hot_batch, cap_r: int, cap_s: int,
                       local_slack: int, s_hist_bound=None,
                       checksum_axis=None):
        """Phase 5/6 — local partitioning + build-probe on the received
        buffers (HashJoin.cpp:131-204).  Traced either inside the fused
        pipeline body or as its own shard_map program when the driver times
        JMPI/JPROC separately (``config.measure_phases``).  Returns
        (per-partition counts, local overflow, count-overflow risk,
        post-local-sort checksum sets or None).

        ``s_hist_bound``: global per-partition outer tuple counts for the
        overflow-risk bound — always the shuffle's s_ghist (free: the fused
        pipeline has it in scope; the split probe program receives the tiny
        [P] array as an input).  Required on the non-bucket paths; the
        bucket path bounds per-bucket counts from static capacities
        instead.

        ``checksum_axis``: when set (config.verify), the bucket path also
        fingerprints its re-partitioned blocks (robustness/verify.py) so a
        tuple damaged by the local radix pass — not just the exchange — is
        caught; skipped under a skew plan, where the replicated hot build
        side makes the block contents incomparable with the pre-exchange
        fingerprint.  The sort/chunked probes reorder nothing the caller
        can observe, so only the bucket path has a third stage to check."""
        cfg = self.config
        ax = cfg.mesh_axes
        fanout = cfg.network_fanout_bits
        num_p = cfg.network_partition_count
        wide = rp_batch.key_hi is not None
        if cfg.bucket_path:
            skew_plan = ((0, hot_batch.size // cfg.num_nodes)
                         if hot_batch is not None else None)
            lcap_r, lcap_s = self._bucket_caps(cap_r, cap_s, local_slack,
                                               skew_plan)
            # the replicated hot build side joins the local radix pass (the
            # reference's skew locus IS its partitioned probe,
            # kernels_optimized.cu:301-943)
            rp_batch, rp_valid = self._concat_hot_valid(rp_batch, rp_valid,
                                                        hot_batch)
            lr = local_partition(rp_batch, rp_valid, fanout,
                                 cfg.local_fanout_bits, lcap_r, "inner",
                                 impl=cfg.partition_impl)
            ls = local_partition(sp_batch, sp_valid, fanout,
                                 cfg.local_fanout_bits, lcap_s, "outer",
                                 impl=cfg.partition_impl)
            counts, count_risk = self._bucket_probe(
                lr.blocks, ls.blocks, lcap_r, lcap_s)
            sort_checks = None
            if checksum_axis is not None and hot_batch is None:
                sort_checks = [
                    _verify.global_partition_checksums(
                        blocks.key, partition_ids(blocks, fanout), num_p,
                        checksum_axis, valid=valid_mask(blocks, side),
                        key_hi=blocks.key_hi)
                    for blocks, side in ((lr.blocks, "inner"),
                                         (ls.blocks, "outer"))]
            return counts, lr.overflow + ls.overflow, count_risk, sort_checks
        if s_hist_bound is None:
            raise ValueError(
                "non-bucket local processing requires s_hist_bound (the "
                "shuffle's global outer histogram) for the overflow guard")
        if cfg.chunk_size:
            # out-of-core discipline (LD kernels): outer slabs under scan
            counts, maxw = probe_count_chunked(
                _as_compressed(rp_batch), _as_compressed(sp_batch),
                sp_pid, num_p, cfg.chunk_size, return_max_weight=True)
        elif wide:
            # 64-bit keys: three-key lexicographic sort-merge on the
            # hi/lo uint32 lanes — no device int64, no x64 requirement
            # (SURVEY.md §7.4 item 3)
            rk_lo, rk_hi = rp_batch.key, rp_batch.key_hi
            if hot_batch is not None:
                rk_lo = jnp.concatenate([rk_lo, hot_batch.key])
                rk_hi = jnp.concatenate([rk_hi, hot_batch.key_hi])
            counts, maxw = merge_count_wide_per_partition(
                rk_lo, rk_hi, sp_batch.key, sp_batch.key_hi, fanout,
                return_max_weight=True)
        else:
            rk = rp_batch.key
            if hot_batch is not None:
                # replicated hot build side joins the local probe; its
                # padding slots are R sentinels (zero weight)
                rk = jnp.concatenate([rk, hot_batch.key])
            count = (merge_count_per_partition_full if self._full_range
                     else merge_count_per_partition)
            counts, maxw = count(rk, sp_batch.key, fanout,
                                 return_max_weight=True)
        return (counts, jnp.uint32(0),
                self._count_risk(maxw, s_hist_bound), None)

    def _shuffle(self, r: TupleBatch, s: TupleBatch,
                 win_r: Window, win_s: Window, skew_plan=None):
        """Phases 1-4 (histograms -> assignment -> all_to_all shuffle ->
        conservation checks), shared by the counting and materializing
        pipelines.  Traced inside shard_map.

        With a ``skew_plan`` (hot_bits, hot_cap), hot partitions take the
        split route (operators/skew.py): hot inner tuples leave the shuffle
        and come back replicated via all_gather (``hot_batch``), hot outer
        tuples spread round-robin by rid.  Returns
        (rp, sp, hot_batch, lost_r, lost_s, hot_overflow, conserve_bad,
        s_ghist) — the trailing global outer histogram feeds the
        uint32-overflow risk bound (:meth:`_count_risk`).
        """
        cfg = self.config
        ax = cfg.mesh_axes
        n = cfg.num_nodes
        fanout = cfg.network_fanout_bits
        r_pid, r_hist = compute_local_histogram(r, fanout)
        s_pid, s_hist = compute_local_histogram(s, fanout)
        r_ghist = compute_global_histogram(r_hist, ax)
        s_ghist = compute_global_histogram(s_hist, ax)

        hot_batch = None
        hot_overflow = jnp.uint32(0)
        if skew_plan:
            hot_bits, hot_cap = skew_plan
            # hot partitions leave the normal accounting: assignment and the
            # per-device conservation targets see them as empty
            r_gh_eff = skew.mask_hot(r_ghist, hot_bits)
            s_gh_eff = skew.mask_hot(s_ghist, hot_bits)
            assignment = compute_partition_assignment(
                r_gh_eff, s_gh_eff, n, cfg.assignment_policy)
            is_hot_r = skew.is_hot(r_pid, hot_bits)
            is_hot_s = skew.is_hot(s_pid, hot_bits)
            dest_spread = skew.spread_destinations(s.rid, n)
            rp = network_partition(r, fanout, assignment, win_r,
                                   exclude=is_hot_r)
            sp = network_partition(s, fanout, assignment, win_s,
                                   override=(is_hot_s, dest_spread))
            # replicate the hot build side: local extraction block +
            # all_gather (the split's "inner bucket to every execution unit",
            # kernels_optimized.cu:364-457's shared staging, mesh-wide)
            hot_blocks, hot_counts, hot_ovf = scatter_to_blocks(
                r, jnp.zeros_like(r_pid), 1, hot_cap, "inner",
                valid=is_hot_r)
            hot_batch = jax.tree.map(
                lambda x: jax.lax.all_gather(x, ax, tiled=True), hot_blocks)
            hot_overflow = jax.lax.psum(hot_ovf, ax)
            lost_r, bad_r = win_r.diagnostics(
                ExchangeResult(rp.batch, rp.recv_counts, rp.send_overflow),
                r_gh_eff, assignment)
            # spread S keeps a per-device expectation: the assigned non-hot
            # share plus this device's slice of the mesh-wide spread demand
            # (one extra histogram pass, skew runs only)
            me = jax.lax.axis_index(ax).astype(jnp.uint32)
            spread_per_dest = jax.lax.psum(
                local_histogram(dest_spread, n, valid=is_hot_s), ax)
            expected_s = (jnp.sum(jnp.where(assignment == me, s_gh_eff, 0))
                          + spread_per_dest[me])
            lost_s = jax.lax.psum(sp.send_overflow, ax)
            bad_s = (jnp.sum(sp.recv_counts) != expected_s) & (lost_s == 0)
            # hot R conservation: everything extracted+gathered must equal
            # the hot slice of the global histogram (unless it overflowed)
            hot_got = jax.lax.psum(
                jnp.minimum(hot_counts[0], jnp.uint32(hot_cap)), ax)
            hot_want = jnp.sum(r_ghist) - jnp.sum(r_gh_eff)
            bad_r = bad_r | ((hot_got != hot_want) & (hot_overflow == 0))
            r_gh_check, s_gh_check = r_gh_eff, s_gh_eff
        else:
            assignment = compute_partition_assignment(
                r_ghist, s_ghist, n, cfg.assignment_policy)
            rp = network_partition(r, fanout, assignment, win_r)
            sp = network_partition(s, fanout, assignment, win_s)
            lost_r, bad_r = win_r.diagnostics(
                ExchangeResult(rp.batch, rp.recv_counts, rp.send_overflow),
                r_ghist, assignment)
            lost_s, bad_s = win_s.diagnostics(
                ExchangeResult(sp.batch, sp.recv_counts, sp.send_overflow),
                s_ghist, assignment)
            r_gh_check, s_gh_check = r_ghist, s_ghist

        if cfg.debug_checks:
            # Per-partition conservation (the strong form of the JOIN_ASSERT
            # invariants, SURVEY.md §4.2-4.3): the received tuples of every
            # assigned partition must match its global histogram entry
            # exactly, not just the totals.  Off by default — an extra
            # bincount pass per relation over the receive buffers.  Hot
            # partitions are excluded: hot R is withheld (expected 0, which
            # the masked histogram encodes) and hot S lands by rid spread,
            # so only its non-hot rows have a per-device expectation.
            me = jax.lax.axis_index(ax).astype(jnp.uint32)
            num_p = r_ghist.shape[0]
            hot_rows = (skew.is_hot(jnp.arange(num_p, dtype=jnp.uint32),
                                    skew_plan[0])
                        if skew_plan else jnp.zeros((num_p,), bool))
            pp_bad = jnp.bool_(False)
            for part, ghist, lost in ((rp, r_gh_check, lost_r),
                                      (sp, s_gh_check, lost_s)):
                got_pp = jnp.bincount(
                    jnp.where(part.valid, part.pid, num_p).astype(jnp.int32),
                    length=num_p + 1)[:num_p].astype(jnp.uint32)
                want_pp = jnp.where(assignment == me, ghist, 0)
                row_bad = (got_pp != want_pp) & ~hot_rows
                pp_bad = pp_bad | (jnp.any(row_bad) & (lost == 0))
            # OffsetMap invariant (histograms/offset_map.py, the analog of
            # OffsetMap.cpp:59-93): every rank's exclusive-prefix offset plus
            # its local count must fit inside the partition's global total —
            # the disjoint-write-ranges guarantee that lets the reference's
            # ranks MPI_Put with zero coordination.  A violation means the
            # histogram collectives disagree (psum vs all_gather), the race
            # class SURVEY.md §5.2 tracks.
            for lhist, ghist in ((r_hist, r_ghist), (s_hist, s_ghist)):
                offs = compute_offsets(lhist, ghist, assignment, ax)
                pp_bad = pp_bad | jnp.any(offs.relative + lhist > ghist)
            bad_r = bad_r | pp_bad   # same failure class: misrouting
        conserve_bad = jax.lax.psum(
            bad_r.astype(jnp.uint32) + bad_s.astype(jnp.uint32), ax)
        return (rp, sp, hot_batch, lost_r, lost_s, hot_overflow, conserve_bad,
                s_ghist)

    def _materialize_fn(self, cap_r: int, cap_s: int, rate_cap: int,
                        skew_plan=None):
        """Pipeline variant that emits rid pairs instead of counts — the
        distributed realisation of the dormant GPU ``probe_match_rate``
        capability (kernels.cu:314-411): static [outer_slots * cap] output
        buffers per device, overflow reported, never silently truncated.
        With a ``skew_plan`` the hot build side arrives replicated
        (operators/skew.py) and joins the local probe input — hot R and
        non-hot receive-buffer keys live in disjoint partitions, so each
        (r_rid, s_rid) pair is still emitted exactly once (the
        probe_match_rate arm of the SD::OPT skew machinery,
        kernels_optimized.cu:689-787)."""
        cfg = self.config
        ax = cfg.mesh_axes
        win_r, win_s = self._make_windows(cap_r, cap_s)

        def body(r: TupleBatch, s: TupleBatch):
            keys_ok = (jnp.max(_sentinel_lane(r)) < R_PAD_KEY) & (
                jnp.max(_sentinel_lane(s)) < R_PAD_KEY)
            (rp, sp, hot_batch, lost_r, lost_s, hot_overflow, conserve_bad,
             _s_gh) = self._shuffle(r, s, win_r, win_s, skew_plan)
            rb = self._concat_hot(rp.batch, hot_batch)
            if cfg.chunk_size:
                # out-of-core discipline for the materializing probe too
                # (LD output kernels, kernels.cu:778-856)
                m = probe_materialize_chunked(
                    _as_compressed(rb), _as_compressed(sp.batch),
                    rate_cap, cfg.chunk_size)
            else:
                m = probe_materialize(_as_compressed(rb),
                                      _as_compressed(sp.batch), rate_cap)
            flags = jnp.stack([
                jax.lax.psum((~keys_ok).astype(jnp.uint32), ax),
                lost_r.astype(jnp.uint32),
                lost_s.astype(jnp.uint32),
                conserve_bad.astype(jnp.uint32),
                jax.lax.psum(m.overflow.astype(jnp.uint32), ax),
                hot_overflow.astype(jnp.uint32),
            ])
            return m.r_rid, m.s_rid, m.valid, flags

        spec = P(cfg.mesh_axes)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(spec, spec),
            out_specs=(spec, spec, spec, P()),
        ))

    def _get_compiled(self, r: TupleBatch, s: TupleBatch,
                      cap_r: int, cap_s: int, local_slack: int = 1,
                      skew_plan=None, verify: bool = False):
        """AOT-compiled pipeline executable for these shapes/capacities.

        Ahead-of-time ``lower().compile()`` keeps XLA compilation out of the
        JPROC execution timer (the reference's phase timers never include
        compilation — there is none at runtime)."""
        n = self.config.num_nodes
        key = (r.size // n, s.size // n, cap_r, cap_s, local_slack, skew_plan,
               r.key_hi is None, s.key_hi is None, self._full_range, verify,
               self._xplan,
               getattr(r.key, "sharding", None), getattr(s.key, "sharding", None))
        return self._compile_timed(
            key,
            lambda: self._pipeline_fn(r.size // n, s.size // n, cap_r, cap_s,
                                      local_slack, skew_plan,
                                      verify=verify).lower(r, s).compile())

    # --------------------------------------------------- integrity verify
    def _verify_pre_fn(self, hot_bits: int):
        """Pre-exchange fingerprint program: ``[2, rows, P]`` (R then S)
        global checksums of the pristine inputs (robustness/verify.py).
        Runs as its own tiny program *before* the pipeline dispatch so the
        fingerprint captures what was sent, not what arrived.  Under a skew
        plan hot R partitions are excluded — they leave the shuffle for the
        replication route and have no post-exchange counterpart; hot S
        spreads but still lands in the receive buffers with its true pid,
        so S fingerprints all tuples."""
        cfg = self.config
        ax = cfg.mesh_axes
        fanout = cfg.network_fanout_bits
        num_p = cfg.network_partition_count

        def body(r: TupleBatch, s: TupleBatch):
            r_pid = partition_ids(r, fanout)
            s_pid = partition_ids(s, fanout)
            r_valid = ~skew.is_hot(r_pid, hot_bits) if hot_bits else None
            return jnp.stack([
                _verify.global_partition_checksums(
                    r.key, r_pid, num_p, ax, valid=r_valid, key_hi=r.key_hi),
                _verify.global_partition_checksums(
                    s.key, s_pid, num_p, ax, key_hi=s.key_hi),
            ])

        spec = P(ax)
        return jax.jit(jax.shard_map(
            body, mesh=self.mesh, in_specs=(spec, spec), out_specs=P()))

    def _run_verify_pre(self, r: TupleBatch, s: TupleBatch, skew_plan):
        """Compile + execute the pre-exchange fingerprint program, timed
        under VCHK (the tag tools_check_regress.py gates the verification
        overhead on)."""
        m = self.measurements
        n = self.config.num_nodes
        hot_bits = skew_plan[0] if skew_plan else 0
        key = ("vpre", hot_bits, r.size // n, s.size // n,
               r.key_hi is None, s.key_hi is None,
               getattr(r.key, "sharding", None),
               getattr(s.key, "sharding", None))
        fn = self._compile_timed(
            key, lambda: self._verify_pre_fn(hot_bits).lower(r, s).compile())
        if m:
            m.start(VCHK)
        pre = fn(r, s)
        if m:
            m.stop(VCHK, fence=pre)
        return pre

    def _inject_exchange_corrupt(self, s: TupleBatch):
        """Fault site ``exchange.corrupt_lane``: flip bit 30 of one outer
        key between the pre-exchange fingerprint and the pipeline dispatch
        — the in-flight bit-flip the integrity checksums exist to catch.
        Bit 30 keeps the damaged key inside the key contract (below the
        31-bit merge packing and both pad sentinels) and above the radix
        bits, so the tuple still routes to its original partition: counts
        conserve, flags stay clean, and only the checksum comparison can
        see the damage.  Returns ``(batch for the pipeline, pristine batch
        or None)`` — the pristine copy is the repair source."""
        if not _faults.fires(_faults.EXCHANGE_CORRUPT, self.measurements):
            return s, None
        if not getattr(s.key, "is_fully_addressable", True):
            return s, None   # multi-process shards: cannot mutate host-side
        sk = host_readback(s.key).copy()
        sk[0] ^= np.uint32(0x40000000)
        # keep an explicit mesh layout; a host-built array stays uncommitted
        # (shard_map lays it out), since device_put with its single-device
        # sharding would pin it and break the mesh dispatch
        sharding = getattr(s.key, "sharding", None)
        key = (jax.device_put(sk, sharding)
               if isinstance(sharding, NamedSharding) else jnp.asarray(sk))
        return TupleBatch(key=key, rid=s.rid, key_hi=s.key_hi), s

    @staticmethod
    def _stamp_fault_sites(diag: Optional[dict]) -> Optional[dict]:
        """Record the active injector's per-site hit/fire accounting in the
        result diagnostics (the FaultSites aggregate print_results reports
        next to FailureClasses).  No-op in production (no injector)."""
        inj = _faults.active()
        if inj is not None and diag is not None:
            diag["fault_sites"] = inj.site_stats()
        return diag

    @staticmethod
    def _to_host(x) -> np.ndarray:
        """Device -> host readback that also works for arrays sharded across
        *processes* (multi-host worlds): non-addressable shards are
        allgathered first — the result-gather the reference does over MPI
        (main.cpp:120-135).  Single-process arrays convert directly."""
        if getattr(x, "is_fully_addressable", True):
            return host_readback(x)
        from jax.experimental import multihost_utils
        return host_readback(multihost_utils.process_allgather(x, tiled=True))

    @staticmethod
    def _flags_to_diag(flags: np.ndarray) -> dict:
        """Failure breakdown from the pipeline's reduced flag vector.  The
        two shuffle overflows are per relation so a retry grows only the
        window that fell short (the reference sizes them separately,
        Window.cpp:168-177).  The trailing count-overflow entry exists only
        on the counting pipelines (the materializing probe counts matches
        from host bools — no uint32 accumulator to wrap)."""
        diag = {
            "key_contract_violations": int(flags[0]),   # nodes with out-of-range keys
            "shuffle_overflow_r_tuples": int(flags[1]),  # inner block capacity shortfall
            "shuffle_overflow_s_tuples": int(flags[2]),  # outer block capacity shortfall
            "conservation_violations": int(flags[3]),   # nodes with misrouted counts
            "local_overflow": int(flags[4]),            # bucket / match-cap shortfall
            "hot_overflow": int(flags[5]),              # skew replication buffer shortfall
            # nodes whose uint32 partition counts could have wrapped
            # (max_weight x outer_p bound, _count_risk)
            "count_overflow_risk": int(flags[6]) if len(flags) > 6 else 0,
        }
        # machine-readable failure taxonomy (robustness/retry.py): callers
        # branch on this instead of re-deriving severity from raw flags
        diag["failure_class"] = classify_diagnostics(diag)
        return diag

    def _inject_shuffle_fault(self, flags: np.ndarray) -> np.ndarray:
        """Fault site ``engine.shuffle_overflow``: when armed, report an
        outer-window capacity shortfall even though the real run fit — the
        retry loop then exercises its grow-and-respecialize path under test
        control.  Returns ``flags`` untouched when the site is quiet."""
        if _faults.fires(_faults.SHUFFLE_OVERFLOW, self.measurements):
            flags = flags.copy()
            flags[2] += 1   # outer (S) shuffle window shortfall: retryable
        return flags

    @staticmethod
    def _retryable(diag: dict) -> bool:
        """Capacity shortfalls are fixable with bigger static shapes; key or
        conservation violations are not (the reference aborts on everything,
        Debug.h:27-37 — the retry is this framework's shape-specialization
        answer to runtime-sized windows, SURVEY.md section 7.4 item 1).
        Routed through the shared policy-driven predicate under a
        sizing-only policy: classify_diagnostics already ranks fatal flags
        above capacity, so a key-contract violation in the same attempt
        never looks retryable."""
        return is_retryable_class(classify_diagnostics(diag), _SIZING_POLICY)

    def _check_key_width(self, r: TupleBatch, s: TupleBatch) -> None:
        """``config.key_bits`` must match the lanes the batches actually
        carry: a 64-bit config joining lo-lane-only batches would silently
        run a 32-bit join on truncated keys and report ok=True — the exact
        hole test_materialize_64bit exposed in round 2."""
        for name, b in (("inner", r), ("outer", s)):
            wide = b.key_hi is not None
            if wide != (self.config.key_bits == 64):
                raise ValueError(
                    f"config.key_bits={self.config.key_bits} but the {name} "
                    f"batch {'carries' if wide else 'lacks'} a key_hi lane; "
                    f"refusing to run a silently-truncated join")

    def _resolve_key_range(self, r: TupleBatch, s: TupleBatch) -> bool:
        """Resolve ``config.key_range`` to this join's concrete discipline:
        True = the full-range lexicographic count (no 31-bit packing cap).

        Only the 32-bit count paths that use the packed merge (the sort
        probe — fused or split) have a choice to make; everything else
        (wide keys, bucket/two-level, chunked, materializing) is full-range
        already.  "auto" prefers a static decision from the Relation key
        bounds the entry points record (:meth:`join` via
        ``Relation.key_bound``); for raw arrays it probes the device max
        key once (~2 HBM scans + one scalar readback) — callers who know
        their key range set "narrow"/"full" and skip the probe."""
        cfg = self.config
        if (cfg.key_bits == 64 or not cfg.sort_probe
                or r.key_hi is not None):
            return False
        if cfg.key_range == "narrow":
            return False
        if cfg.key_range == "full":
            return True
        if self._static_key_bound is not None:
            return self._static_key_bound - 1 > MAX_MERGE_KEY
        if not hasattr(self, "_maxkey_jit"):
            self._maxkey_jit = jax.jit(
                lambda a, b: jnp.maximum(jnp.max(a), jnp.max(b)))
        # _to_host: the replicated scalar still reports non-addressable
        # shards in multi-process worlds, where bare np.asarray raises
        return int(self._to_host(
            self._maxkey_jit(r.key, s.key))) > MAX_MERGE_KEY

    # ------------------------------------------------- exchange wire plan
    def _resolve_exchange_plan(self, r: TupleBatch, s: TupleBatch):
        """Resolve ``config.exchange_codec`` / ``exchange_stages`` into this
        join's concrete wire plan ``(codec, mode, key_bound, rid_bound_r,
        rid_bound_s)`` — appended to every pipeline compile key, because the
        bounds change the lowered program (data/tuples.make_wire_spec).

        ``key_bound`` priority: the static Relation bound recorded by
        :meth:`join`, then the max key the sizing pre-pass measured (the
        JHIST program carries a pmax alongside the demand histograms), then
        a one-off device max probe (~2 HBM scans).  All three are exact
        upper bounds, so packing can never mask a real key bit.  The rid
        bounds are exact and free: rids are global dense tuple indices
        (data/relation.py), so each side's relation size bounds its lane.

        ``codec="auto"`` stays "auto" here — whether packing actually beats
        the raw lanes depends on each window's capacity (header
        amortization), resolved per side by :meth:`_wire_side`.
        """
        cfg = self.config
        mode = "auto" if cfg.exchange_stages == 0 else int(cfg.exchange_stages)
        if cfg.exchange_codec == "off" or cfg.num_nodes == 1:
            return ("off", mode, None, None, None)
        key_bound = self._static_key_bound
        if key_bound is None:
            key_bound = self._measured_key_bound
        if key_bound is None:
            key_bound = self._probe_key_bound(r, s)
        return (cfg.exchange_codec, mode, int(key_bound), r.size, s.size)

    def _probe_key_bound(self, r: TupleBatch, s: TupleBatch) -> int:
        """Exact measured key bound (device max + 1) for raw-array joins
        that skipped the sizing pre-pass (warm starts, static sizing)."""
        if not hasattr(self, "_maxkey_jit"):
            self._maxkey_jit = jax.jit(
                lambda a, b: jnp.maximum(jnp.max(a), jnp.max(b)))
        lo = int(self._to_host(self._maxkey_jit(r.key, s.key)))
        if r.key_hi is None:
            return lo + 1
        hi = int(self._to_host(self._maxkey_jit(r.key_hi, s.key_hi)))
        return ((hi << 32) | lo) + 1

    def _wire_side(self, cap: int, rid_bound):
        """Resolve one window's codec under the current plan: ``('pack',
        WireSpec)`` or ``('off', None)``.  codec="auto" packs only when the
        packed block actually beats the raw lanes at this capacity — the
        per-partition header is amortized over the block, so tiny blocks
        can lose."""
        cfg = self.config
        codec = self._xplan[0]
        if codec == "off":
            return "off", None
        wide = cfg.key_bits == 64
        spec = make_wire_spec(cap, cfg.network_fanout_bits, wide=wide,
                              key_bound=self._xplan[2], rid_bound=rid_bound)
        if codec == "auto" and spec.bytes_per_block >= cap * (12 if wide
                                                              else 8):
            return "off", None
        return "pack", spec

    def _make_windows(self, cap_r: int, cap_s: int):
        """The per-relation shuffle Windows under the resolved wire plan
        (one construction site shared by the fused, phase-split, and
        materializing pipelines so they cannot diverge)."""
        cfg = self.config
        ax, n = cfg.mesh_axes, cfg.num_nodes
        _, mode, key_bound, rid_r, rid_s = self._xplan

        def one(cap, side, rid_bound):
            codec, _ = self._wire_side(cap, rid_bound)
            return Window(n, cap, ax, side, codec=codec, mode=mode,
                          fanout_bits=cfg.network_fanout_bits,
                          key_bound=key_bound, rid_bound=rid_bound,
                          partition_impl=cfg.partition_impl,
                          epoch=self._membership_epoch())

        return one(cap_r, "inner", rid_r), one(cap_s, "outer", rid_s)

    def _exchange_stats(self, cap_r: int, cap_s: int) -> dict:
        """Static wire geometry of ONE exchange under the resolved plan —
        everything here is shape-derived, computed on the host with no
        device readback, and stamped into ``meta["exchange_plan"]`` so
        bench/regress read measured-format truth instead of re-deriving it.

        ``wire_bytes``: bytes each node actually ships per exchange, both
        relations.  ``bytes_per_tuple``: wire bytes per *slot* of the block
        format (the baseline format is exactly 8 B/slot narrow, 12 B wide —
        per-slot keeps the A/B comparison independent of pow2 capacity
        slack, which inflates both arms identically).
        ``peak_exchange_bytes``: the largest single collective's live
        buffer (simultaneously-dispatched lanes summed) — the quantity the
        staged mode bounds to ~1/k."""
        cfg = self.config
        n = cfg.num_nodes
        wide = cfg.key_bits == 64
        raw_pt, lanes = (12, 3) if wide else (8, 2)
        mode = self._xplan[1]
        stats = {"codec": cfg.exchange_codec, "key_bound": self._xplan[2]}
        wire_total = raw_total = 0
        peak = 0
        stages_used = 1
        for side, cap, rid_bound in (("r", cap_r, self._xplan[3]),
                                     ("s", cap_s, self._xplan[4])):
            codec, spec = self._wire_side(cap, rid_bound)
            raw = n * cap * raw_pt
            if codec == "pack":
                wire = n * spec.bytes_per_block
                k = parse_exchange_mode(mode, spec.block_words)
                side_peak = n * 4 * -(-spec.block_words // k)
                bpt = spec.bytes_per_tuple
            else:
                wire = raw
                k = parse_exchange_mode(mode, cap)
                # the raw lane collectives have no sequencing barrier
                # between them — count them as one in-flight buffer
                side_peak = n * 4 * lanes * -(-cap // k)
                bpt = float(raw_pt)
            stats[f"codec_{side}"] = codec
            stats[f"stages_{side}"] = k
            stats[f"bytes_per_tuple_{side}"] = round(bpt, 4)
            wire_total += wire
            raw_total += raw
            peak = max(peak, side_peak)
            stages_used = max(stages_used, k)
        stats["wire_bytes"] = wire_total
        stats["raw_bytes"] = raw_total
        stats["bytes_per_tuple"] = round(
            wire_total / max(1, n * (cap_r + cap_s)), 4)
        stats["pack_ratio_pct"] = round(100.0 * wire_total / max(1, raw_total),
                                        2)
        stats["peak_exchange_bytes"] = peak
        stats["stages"] = stages_used
        return stats

    def _strategy_label(self) -> str:
        """The executed discipline in the planner's strategy vocabulary
        (planner/cost_model.enumerate_strategies) — stamped onto timeline
        spans so traces and predicted-cost tables speak one language."""
        cfg = self.config
        mode = "split" if cfg.measure_phases else "fused"
        if cfg.sort_probe:
            kr = "full" if self._full_range else "narrow"
            return f"incore_{mode}_sort_{kr}"
        return (f"incore_{mode}_twolevel" if cfg.two_level
                else f"incore_{mode}_bucket")

    # ------------------------------------------------------------------- run
    def join_arrays_pipelined(self, r: TupleBatch, s: TupleBatch,
                              repeats: int) -> JoinResult:
        """Alias for ``join_arrays(..., repeats=...)`` (kept for API
        discoverability of the amortized-dispatch mode)."""
        return self.join_arrays(r, s, repeats=repeats)

    def join_arrays(self, r: TupleBatch, s: TupleBatch,
                    repeats: int = 1) -> JoinResult:
        """Join globally-sharded TupleBatch arrays (leading dim divisible by
        the mesh size).

        ``repeats > 1`` pipelines that many joins of the same batches as
        asynchronous dispatches closed by ONE fence — the
        amortized-throughput methodology (bench.py) through the full driver
        flow.  Through a host-attached chip each synchronous join pays a
        non-pipelining ~100 ms dispatch round-trip (PERF_NOTES), so the
        driver-visible rate reads ~2x below the chip's amortized truth;
        pipelined mode sizes and compiles once and divides.  No retry loop
        there (a capacity shortfall surfaces identically in every attempt's
        flags), and no phase-split (the split timers need a fence per
        program — the combination raises).  Cumulative counters keep the
        synchronous convention: tuple/exchange counters accumulate once per
        dispatched join, so JRATE = cumulative tuples / cumulative time.
        The reference driver runs exactly one join (main.cpp), so repeats
        carry no parity constraint.

        With ``self.elastic`` set, a mid-join rank loss (the
        ``membership.rank_death`` site, a lapsed lease surfacing at a
        phase boundary, a fenced stale epoch, or a transport error a
        lapsed lease explains) is absorbed: the join finishes on the
        survivors via partition-level recompute (:meth:`_recover_join`)
        instead of raising.  Successful joins record their realized
        partitions into ``self.partition_manifest`` when one is attached.
        """
        set_default_sort_impl(self.config.sort_impl)
        if not self.elastic and self.partition_manifest is None:
            return self._join_arrays_inner(r, s, repeats)
        if (self.membership is not None and self.partition_manifest is not None
                and self.membership.board.progress_of is None):
            # export this process's manifest progress on every lease beat
            # — the per-rank progress clock the straggler detector reads
            self.membership.board.progress_of = self._my_partitions_done
        try:
            result = self._join_arrays_inner(r, s, repeats)
        except BaseException as e:     # noqa: BLE001 — triaged below
            if not self.elastic:
                raise
            if isinstance(e, StragglerDetected):
                return self._hedge_join(r, s, e, repeats)
            if isinstance(e, RankJoined):
                return self._regrow_join(r, s, e, repeats)
            exc = self._as_rank_lost(e)
            if exc is None:
                raise
            return self._recover_join(r, s, exc, repeats)
        self._manifest_record(result)
        return result

    def _join_arrays_inner(self, r: TupleBatch, s: TupleBatch,
                           repeats: int = 1) -> JoinResult:
        """:meth:`join_arrays` body (the wrapper above owns rank-loss
        recovery and manifest recording)."""
        if repeats < 1:
            raise ValueError("repeats must be >= 1")
        if repeats > 1 and self.config.measure_phases:
            raise ValueError(
                "pipelined repeats dispatch without intermediate fences; "
                "the measure_phases split timers need a fence per program "
                "— loop synchronous joins instead")
        n = self.config.num_nodes
        if r.size % n or s.size % n:
            raise ValueError("relation sizes must divide the mesh size")
        self._check_key_width(r, s)
        self._check_cancel("start")
        m = self.measurements
        # Timer placement mirrors HashJoin.cpp:50-212: JTOTAL spans the whole
        # join; SWINALLOC wraps the sizing pass (whose execution is JHIST and
        # whose compilation is JCOMPILE, see _run_hist).  By default the
        # shuffle+local program is fused, so JPROC covers both phases (the
        # JMPI/JPROC split is visible in profiler traces); with
        # config.measure_phases the attempt runs as two programs and JMPI is
        # recorded from the host clock (Measurements.cpp:139-141 parity).
        if m:
            m.start("JTOTAL")
        # the auto key-range probe is join work (2 HBM scans + readback):
        # it must land inside JTOTAL, like every other pre-pass
        self._full_range = self._resolve_key_range(r, s)
        if m:
            if self.config.key_bits == 32 and self.config.sort_probe:
                # perf artifacts self-describe which count discipline ran
                m.meta["key_range"] = ("full" if self._full_range
                                       else "narrow")
            # timeline spans carry the executed discipline (planner
            # vocabulary) so a merged trace reads per rank: which strategy,
            # which phase, when (observability/spans.py)
            m.set_trace_tags(strategy=self._strategy_label())
            m.start("SWINALLOC")
        local_slack = 1
        warm = None
        self._measured_key_bound = None   # only this join's sizing pass counts
        if self._cache_eligible():
            _, warm = self.plan_cache.lookup(r.size, s.size,
                                             self._cache_config_fp())
        if warm is not None:
            # warm start: the previous run's converged capacities replace
            # the sizing dispatch — no JHIST this join, one CKPTLOAD
            cap_r, cap_s, skew_plan = (int(warm["cap_r"]),
                                       int(warm["cap_s"]), None)
            local_slack = int(warm.get("local_slack", 1))
        else:
            cap_r, cap_s, skew_plan = self._measure_capacities(
                r, s, shuffles=not self._single_node_sort_probe())
        if m:
            m.stop("SWINALLOC")
        # wire-format plan: resolved after sizing so the measured key bound
        # is available; the fallback device max probe is join work and lands
        # inside JTOTAL like every other pre-pass.  The exchange_pack span
        # marks the host-side resolution — the packing itself is traced
        # inside the jitted pipeline, invisible to host timers.
        with (m.span("exchange_pack", codec=self.config.exchange_codec,
                     stages=self.config.exchange_stages)
              if m else contextlib.nullcontext()):
            self._xplan = self._resolve_exchange_plan(r, s)
        self._check_cancel("sized")
        if m and not self._single_node_sort_probe():
            # stamp the resolved wire geometry NOW, not only in
            # _finish_join: a live heartbeat tick mid-join (or the last
            # tick before a death) must show the exchange plan even
            # though the cumulative WIREBYTES counter only lands after
            # the pipeline completes.  _finish_join overwrites with the
            # final (possibly regrown) capacities.
            xs = self._exchange_stats(cap_r, cap_s)
            m.meta["exchange_plan"] = xs
            m.counters[PACKRATIO] = int(round(xs["pack_ratio_pct"]))
            m.counters[XSTAGES] = int(xs["stages"])
        if _faults.fires(_faults.BACKEND_STALL, m):
            # simulated hung collective (the downed-tunnel failure mode):
            # spin without recording progress — exactly what a blocked
            # dispatch looks like to the flight recorder — while still
            # consulting the cancel hook, the watchdog's kill path.  The
            # env-tunable cap keeps an unwatched test from hanging
            # tier-1 forever; hitting it classifies as the transient
            # infrastructure failure a real stuck tunnel would be.
            cap_s_stall = float(os.environ.get("TPU_RADIX_STALL_CAP_S",
                                               "120"))
            t0_stall = time.monotonic()
            while True:
                self._check_cancel("stalled")
                if time.monotonic() - t0_stall >= cap_s_stall:
                    if m is not None and "JTOTAL" in m._starts:
                        m.stop("JTOTAL")
                    raise _faults.TransientFault(_faults.BACKEND_STALL, 1)
                time.sleep(0.01)
        if _faults.fires(_faults.COMPUTE_STRAGGLE, m):
            # simulated alive-but-slow rank: unlike BACKEND_STALL this is
            # NOT an infrastructure failure — the straggler keeps
            # heartbeating, so the lease machinery must never declare it
            # dead; with hedging enabled the detector turns the stretch
            # into a bounded speculative recompute instead
            self._compute_straggle()
        # integrity verification (robustness/verify.py): fingerprint the
        # pristine inputs before anything can damage them.  The n==1 sort
        # specialization performs no exchange (nothing to verify against)
        # and is skipped entirely.
        verify_on = (self.config.verify != "off"
                     and not self._single_node_sort_probe())
        pre = self._run_verify_pre(r, s, skew_plan) if verify_on else None
        # host-side corruption site, consulted between the pre-exchange
        # fingerprint and the pipeline dispatch — and regardless of the
        # verify mode: real corruption does not ask whether anyone is
        # checking (verify="off" + this site armed IS the silent-wrong-
        # answer scenario the chaos soak hunts)
        s, pristine_s = self._inject_exchange_corrupt(s)
        if repeats > 1:
            # amortized-dispatch mode: one compiled program, ``repeats``
            # async dispatches, one fence; flags read once (identical
            # static shapes make every attempt fail or succeed alike)
            fn = self._get_compiled(r, s, cap_r, cap_s, local_slack,
                                    skew_plan, verify=verify_on)
            if m:
                m.start("JPROC")
            counts = flags = vchk = None
            for _ in range(repeats):
                if verify_on:
                    counts, flags, vchk = fn(r, s)
                else:
                    counts, flags = fn(r, s)
            if m:
                m.stop("JPROC", fence=(counts, flags))
            flags = host_readback(flags)
            diag = self._flags_to_diag(flags)
            if verify_on and not flags.any():
                result = self._verified_finish(
                    r, s, pristine_s, counts, flags, diag, pre, vchk,
                    cap_r, cap_s, skew_plan, repeats)
            else:
                result = self._finish_join(r, s, counts, flags, diag,
                                           cap_r, cap_s, repeats)
            self._cache_store_capacities(r, s, cap_r, cap_s, local_slack,
                                         result.ok)
            return result
        # the split is honored with or without a registry (a profiler-trace
        # user still gets two separate programs); only the host timers need m
        use_split = (self.config.measure_phases
                     and not self._single_node_sort_probe())
        vchk = None
        for attempt in range(self.config.max_retries + 1):
            self._check_cancel("probe")
            if use_split:
                # config.__post_init__ rejects verify + measure_phases, so
                # verify_on is always False on this branch
                counts, flags, dts = self._run_split(
                    r, s, cap_r, cap_s, local_slack, skew_plan)
            else:
                fn = self._get_compiled(r, s, cap_r, cap_s, local_slack,
                                        skew_plan, verify=verify_on)
                if m:
                    m.start("JPROC")
                if verify_on:
                    counts, flags, vchk = fn(r, s)
                else:
                    counts, flags = fn(r, s)
                dts = ({"JPROC": m.stop("JPROC", fence=(counts, flags))}
                       if m else {})
            flags = self._inject_shuffle_fault(host_readback(flags))
            diag = self._flags_to_diag(flags)
            if not flags.any() or not self._retryable(diag):
                break
            # capacity shortfall: double only the shapes that fell short and
            # respecialize (detect-and-retry, SURVEY.md section 7.4 item 1)
            if diag["shuffle_overflow_r_tuples"]:
                cap_r *= 2
            if diag["shuffle_overflow_s_tuples"]:
                cap_s *= 2
            if diag["local_overflow"]:
                local_slack *= 2
            if diag["hot_overflow"]:
                skew_plan = (skew_plan[0], 2 * skew_plan[1])
            if m and attempt < self.config.max_retries:
                # when retries are exhausted the last attempt IS the result
                # — keep its time (see _rollback_attempt)
                self._rollback_attempt(m, dts)
            self._retry_backoff(attempt)
        if (flags.any() and self._retryable(diag)
                and self.config.fallback == "chunked"):
            # retries exhausted on a retryable (capacity) failure: degrade
            # to the out-of-core grid path instead of returning ok=False
            return self._fallback_chunked(r, s, diag, cap_r, cap_s)
        if verify_on and not flags.any():
            # checksum comparison only judges the accepted attempt, and only
            # when its flags are clean: a capacity shortfall legitimately
            # drops tuples (its own failure class), and fatal flags already
            # fail the join without verification's help
            result = self._verified_finish(r, s, pristine_s, counts, flags,
                                           diag, pre, vchk, cap_r, cap_s,
                                           skew_plan, 1)
        else:
            result = self._finish_join(r, s, counts, flags, diag, cap_r,
                                       cap_s, 1)
        self._cache_store_capacities(r, s, cap_r, cap_s, local_slack,
                                     result.ok)
        return result

    def _check_cancel(self, phase: str) -> None:
        """Phase-boundary service point: consult the injectable
        ``membership.rank_death`` / ``membership.rank_join`` sites, the
        membership view (lease scan: admissions then lapses), the
        straggler detector (when hedging), and the cooperative
        cancellation hook, in that order.  On any raise the open JTOTAL
        timer is closed first so the aborted query still reports how
        long it ran before it died."""
        m = self.measurements
        try:
            if _faults.fires(_faults.RANK_DEATH, m):
                self._rank_death(phase)
            if _faults.fires(_faults.RANK_JOIN, m):
                self._rank_join(phase)
            if self.membership is not None:
                mv = self.membership
                # self-heartbeat rides the same boundary as the peer scan:
                # a long compile/dispatch gap must not lapse OUR lease just
                # because no sampler thread is ticking it
                mv.board.heartbeat(mv.epoch, status=mv.my_status())
                prev_joined = set(mv.joined)
                newly = mv.check()
                if newly:
                    raise RankLost(newly[0], mv.epoch,
                                   f"lease lapsed at phase {phase!r}")
                admitted = sorted(mv.joined - prev_joined)
                if admitted and self.elastic_grow:
                    # publish the fenced epoch on our lease BEFORE the
                    # re-expansion: the newcomer's admission signal is an
                    # incumbent member lease at the bumped epoch, and the
                    # run may end before another boundary heartbeats it
                    mv.board.heartbeat(mv.epoch, status=mv.my_status())
                    # in-flight work is stamped with the pre-admission
                    # epoch; finish on the grown membership instead of
                    # dispatching stale-epoch collectives
                    raise RankJoined(admitted, mv.epoch)
                if self._should_hedge():
                    self._poll_straggler(phase)
            if self.cancel is not None:
                self.cancel(phase)
        except BaseException:
            if m is not None and "JTOTAL" in m._starts:
                m.stop("JTOTAL")
            raise

    # ------------------------------------------------------ elastic recovery
    def _rank_death(self, phase: str) -> None:
        """The ``membership.rank_death`` chaos site fired at this phase
        boundary.  Two modes:

          * **real** (``TPU_RJ_RANK_DEATH_SUICIDE`` set — the victim
            process of the multi-rank recovery test): die the way a real
            rank dies — instantly, silently, no cleanup, no goodbye;
          * **simulated** (single process): the highest node rank is the
            victim — declare it lost (bumping the epoch) and raise the
            :class:`RankLost` the elastic path owns.
        """
        if os.environ.get("TPU_RJ_RANK_DEATH_SUICIDE"):
            os.kill(os.getpid(), signal.SIGKILL)
        m = self.measurements
        victim = self.config.num_nodes - 1
        if self.membership is not None:
            epoch = self.membership.declare_lost(victim, cause="injected")
        else:
            epoch = 1
            if m is not None:
                m.incr(MEPOCH)
                m.incr(RANKLOST)
                m.event("rank_lost", ranks=[victim], epoch=epoch,
                        cause="injected",
                        survivors=self.config.num_nodes - 1)
        raise RankLost(victim, epoch, f"injected at phase {phase!r}")

    def _rank_join(self, phase: str) -> None:
        """The ``membership.rank_join`` chaos site fired at this phase
        boundary: simulate a newcomer by writing a fresh ``joining``
        lease for the next unused rank — the stand-in for a real new
        process's first heartbeat.  The ordinary admission scan in
        :meth:`_check_cancel`'s ``membership.check()`` does the rest
        (fenced epoch bump, RANKJOIN, and — under ``elastic_grow`` —
        the :class:`RankJoined` re-expansion)."""
        mv = self.membership
        if mv is None:
            return
        board = mv.board
        new_rank = LeaseBoard.next_rank(board.run_dir,
                                        floor=board.num_ranks)
        joiner = LeaseBoard(board.run_dir, new_rank, board.num_ranks,
                            lease_s=board.lease_s, clock=board.clock,
                            missed_beats=board.missed_beats)
        joiner.heartbeat(mv.epoch, status="joining")
        m = self.measurements
        if m is not None:
            m.event("rank_join_injected", rank=new_rank, phase=phase)

    # ------------------------------------------------------------- hedging
    def _should_hedge(self) -> bool:
        """Hedging needs the manifest fence (no fence, no safe
        speculation) and a membership view; ``auto`` additionally backs
        off while wasted speculation outruns wins — the SPECWASTE /
        HEDGEWIN closed loop."""
        if self.hedge == "off" or self.membership is None \
                or self.partition_manifest is None:
            return False
        if self.hedge == "auto":
            m = self.measurements
            if m is not None and (m.counters.get(SPECWASTE, 0)
                                  > m.counters.get(HEDGEWIN, 0)):
                return False
        return True

    def _detector(self) -> StragglerDetector:
        if self._straggler_detector is None:
            self._straggler_detector = StragglerDetector(
                threshold=self.hedge_threshold)
        return self._straggler_detector

    def _my_partitions_done(self) -> int:
        """This process's manifest progress (partitions realized by node
        ranks it owns) — exported on every lease beat as the per-rank
        progress clock."""
        mf = self.partition_manifest
        if mf is None:
            return -1
        done = mf.completed()
        scope = self._recovery_scope()
        if scope is None:
            return len(done)
        sc = set(scope)
        return sum(1 for rec in done.values() if rec["owner"] in sc)

    def _poll_straggler(self, phase: str) -> None:
        """Real-path straggler detection: compare live peers' lease
        progress clocks; a confirmed (post-dwell) verdict on a PEER
        raises :class:`StragglerDetected` for the hedge path.  A verdict
        on ourselves is ignored — a straggler cannot hedge itself."""
        mv = self.membership
        board = mv.board
        live = [r for r in mv.survivors if r in set(board.discover())
                or r < board.num_ranks]
        progress = board_progress(board, live)
        if len(progress) < 2:
            return
        num_p = self.config.network_partition_count
        share = max(1, num_p // max(1, len(progress)))
        outstanding = {r: max(0, share - done)
                       for r, done in progress.items()}
        verdict = self._detector().observe(progress, outstanding)
        if verdict is not None and verdict.rank != board.rank:
            raise verdict.to_exc(mv.epoch)

    def _compute_straggle(self) -> None:
        """The ``compute.straggle`` site fired: the highest node rank
        slows by ``straggle_factor`` x ``straggle_unit_s``.  Unhedged,
        the join simply eats the stretch (tail latency — the failure
        mode).  With hedging on, the spin feeds the detector a simulated
        progress picture (healthy ranks at their share, the straggler at
        its manifest progress) and aborts into the hedge as soon as the
        post-dwell verdict lands — tail becomes detect + recompute."""
        m = self.measurements
        n = self.config.num_nodes
        victim = n - 1
        factor = max(0.0, float(self.straggle_factor))
        duration = factor * self.straggle_unit_s
        if m is not None:
            m.event("straggle", rank=victim, factor=factor,
                    duration_s=round(duration, 3))
        if duration <= 0:
            return
        hedging = self._should_hedge()
        num_p = self.config.network_partition_count
        share = max(1, num_p // n)
        detector = self._detector() if hedging else None
        t0 = time.monotonic()
        while time.monotonic() - t0 < duration:
            if hedging:
                done = self.partition_manifest.completed()
                victim_done = sum(1 for p, rec in done.items()
                                  if p % n == victim)
                progress = {r: share for r in range(n) if r != victim}
                progress[victim] = victim_done
                outstanding = {victim: max(0, share - victim_done)}
                verdict = detector.observe(progress, outstanding)
                if verdict is not None:
                    epoch = self._membership_epoch()
                    if m is not None and "JTOTAL" in m._starts:
                        m.stop("JTOTAL")
                    raise verdict.to_exc(epoch)
            time.sleep(min(0.02, duration / 4))

    def _as_rank_lost(self, e: BaseException) -> Optional[RankLost]:
        """Map a mid-join failure to the :class:`RankLost` recovery owns.

        Direct RankLost/StaleEpoch (fault site, lease scan, watchdog
        triage, epoch fence) always qualifies.  Other injected faults
        keep their own failure classes.  A generic transport/runtime
        error (gloo's broken pipe, an aborted collective) qualifies only
        when the membership view confirms a lapsed lease — a dead peer
        explains the error; anything else is not recovery's to absorb."""
        if isinstance(e, RankLost):
            return e
        if isinstance(e, StaleEpoch):
            mv = self.membership
            rank = min(mv.lost) if mv is not None and mv.lost else 0
            return RankLost(rank, e.current, "stale epoch fenced")
        if isinstance(e, _faults.InjectedFault):
            return None
        if (self.membership is not None
                and isinstance(e, (ConnectionError, OSError, RuntimeError,
                                   TimeoutError))):
            # a peer's death can surface as a transport error BEFORE its
            # lease ages out (RST beats the lapse window): give the lease
            # one full window — lease_s x missed_beats, the two-missed-
            # beats rule — to lapse before disowning the error
            mv = self.membership
            deadline = time.monotonic() + mv.board.lapse_window_s + 1.0
            while True:
                lost = mv.check() or sorted(mv.lost)
                if lost or time.monotonic() >= deadline:
                    break
                time.sleep(0.2)
            if lost:
                return RankLost(lost[0], self.membership.epoch,
                                f"peer death surfaced as "
                                f"{type(e).__name__}: {e}"[:200])
        return None

    def _lost_nodes(self, exc: RankLost) -> list:
        """Expand lost PROCESS ranks into the node ranks they own: leases
        are per process, partitions are owned by nodes, and a multi-device
        process takes all its nodes down with it.  Single-process
        simulation (no membership board): identity on the exception's
        rank."""
        n = self.config.num_nodes
        mv = self.membership
        if mv is None or mv.board.num_ranks <= 1:
            r = int(getattr(exc, "rank", n - 1))
            return [r if 0 <= r < n else n - 1]
        nprocs = max(1, mv.board.num_ranks)
        npp = max(1, n // nprocs)
        lost_procs = sorted(mv.lost) or [int(getattr(exc, "rank", 0))]
        out = []
        for pr in lost_procs:
            out.extend(range(pr * npp, min(n, (pr + 1) * npp)))
        return [r for r in out if 0 <= r < n] or [n - 1]

    def _recovery_scope(self):
        """Node ranks THIS process recomputes for, or None for all (the
        single-process simulation recomputes every lost partition; a
        multi-process survivor takes only its reassigned share and merges
        the rest through the shared manifest)."""
        mv = self.membership
        if (mv is None or mv.board.num_ranks <= 1
                or self.partition_manifest is None):
            return None
        n = self.config.num_nodes
        npp = max(1, n // max(1, mv.board.num_ranks))
        me = mv.board.rank
        return range(me * npp, (me + 1) * npp)

    def _joined_nodes(self) -> list:
        """Expand admitted PROCESS ranks into the node ranks they bring —
        the growth mirror of :meth:`_lost_nodes` (same npp convention).
        Joined ids may lie beyond the boot mesh's node range; they are
        assignment/owner labels for the out-of-band recompute path, not
        device indices."""
        mv = self.membership
        if mv is None or not mv.joined:
            return []
        n = self.config.num_nodes
        npp = max(1, n // max(1, mv.board.num_ranks))
        out = []
        for pr in sorted(mv.joined):
            out.extend(range(pr * npp, (pr + 1) * npp))
        return sorted(set(out))

    def _straggler_nodes(self, exc) -> list:
        """Node ranks the straggler owns.  A verdict rank below the
        process count is a PROCESS rank (real-path detection off lease
        progress clocks) and expands npp-wise like :meth:`_lost_nodes`;
        at or beyond it, it is already a node rank (the in-process
        ``compute.straggle`` simulation's victim)."""
        n = self.config.num_nodes
        mv = self.membership
        rk = int(exc.rank)
        if (mv is not None and mv.board.num_ranks > 1
                and rk < mv.board.num_ranks):
            npp = max(1, n // mv.board.num_ranks)
            return [x for x in range(rk * npp, (rk + 1) * npp) if x < n]
        return [rk if 0 <= rk < n else n - 1]

    def _claim_hedge(self, plan, straggler_nodes, epoch: int) -> list:
        """Advisory hedge claims: before recomputing, claim the
        straggler's unfinished partitions in the manifest so a crash
        mid-hedge leaves a forensic trail (the post-mortem hedge-claim
        timeline) and a concurrent hedger can see the race.  The
        done-line fence — not the claim — remains the count arbiter."""
        mf = self.partition_manifest
        n = self.config.num_nodes
        strag = set(straggler_nodes)
        hedged = [p for p in plan.recompute if p % n in strag]
        scope = self._recovery_scope()
        mine = None if scope is None else set(scope)
        for p in hedged:
            owner = plan.reassignment[p]
            if mine is None or owner in mine:
                mf.claim(p, owner, epoch=epoch)
        return hedged

    def _await_peer_partitions(self, plan, counts, rk, sk, rhi, shi):
        """Multi-survivor completeness: partitions the plan reassigned to
        OTHER live processes (an incumbent peer or a newcomer) may not
        have landed yet — poll the shared manifest for one lapse window,
        then recompute any leftovers locally.  Deterministic inputs make
        the local recompute exact and the manifest fence makes the
        double-compute safe, so waiting never blocks correctness."""
        mv, mf = self.membership, self.partition_manifest
        missing = [p for p in plan.recompute if p not in counts]
        if not missing or mf is None or mv is None:
            return counts
        deadline = time.monotonic() + mv.board.lapse_window_s + 1.0
        while missing and time.monotonic() < deadline:
            done = mf.completed()
            for p in list(missing):
                if p in done:
                    counts[p] = done[p]["count"]
                    missing.remove(p)
            if missing:
                time.sleep(0.2)
        if missing:
            from tpu_radix_join.robustness import recovery as _recovery
            owners = {plan.reassignment[p] for p in missing}
            _, extra = _recovery.execute_recovery(
                plan, rk, sk, rhi, shi, only_rank=owners,
                slab=min(1 << 20, max(1, len(sk))),
                pipeline=self.config.grid_pipeline,
                measurements=self.measurements, manifest=mf)
            counts.update(extra)
        return counts

    def _recover_join(self, r: TupleBatch, s: TupleBatch, exc: RankLost,
                      repeats: int, *, lost_nodes=None, joined_nodes=None,
                      epoch=None, span_name: str = "recovery",
                      hedge_exc=None, extra_diag=None) -> JoinResult:
        """Finish an aborted join on the survivor mesh (the elastic
        tentpole, robustness/recovery.py): resume realized partitions
        from the manifest, re-assign the rest across survivors — a set
        that may have GROWN through ``joining``-lease admissions
        (``joined_nodes``) — recompute each as its own masked
        out-of-core join from host-regenerated inputs, and splice —
        ok=True with the exact count, classified ``recovered``
        diagnostics, never a collective on the old mesh.

        Also the shared engine behind :meth:`_regrow_join` (growth: zero
        losses, the admission's fenced epoch) and :meth:`_hedge_join`
        (straggler hedge: ``lost_nodes`` is an assignment EXCLUSION only
        — nothing is declared lost, no epoch bump, the recompute fences
        at the current epoch and the manifest arbitrates against the
        still-running original)."""
        m = self.measurements
        cfg = self.config
        num_p = cfg.network_partition_count
        from tpu_radix_join.robustness import recovery as _recovery
        # Host key lanes WITHOUT touching distributed arrays: prefer the
        # deterministic Relation specs recorded by join(); fall back to
        # fully-addressable batches (chaos runner / single-process).  A
        # multi-process batch with no Relation spec cannot be recovered
        # host-side — re-raise the classified loss for the caller.
        if self._elastic_rel is not None:
            rk, rhi = _recovery.host_keys(self._elastic_rel[0])
            sk, shi = _recovery.host_keys(self._elastic_rel[1])
        elif (getattr(r.key, "is_fully_addressable", True)
                and getattr(s.key, "is_fully_addressable", True)):
            rk = host_readback(r.key)
            sk = host_readback(s.key)
            rhi = None if r.key_hi is None else host_readback(r.key_hi)
            shi = None if s.key_hi is None else host_readback(s.key_hi)
        else:
            raise exc
        if m is not None and "JTOTAL" in m._starts:
            m.stop("JTOTAL")   # the abort point; recovery has its own wall
        if epoch is None:
            epoch = max(1, self._membership_epoch(),
                        int(getattr(exc, "epoch", 1)))
        if lost_nodes is None:
            lost_nodes = self._lost_nodes(exc)
        if joined_nodes is None:
            joined_nodes = self._joined_nodes()
        # advisory re-pricing for the shrunken mesh: best-effort — a
        # missing profile must not block recovery
        profile = workload = None
        try:
            from tpu_radix_join.planner.cost_model import Workload
            from tpu_radix_join.planner.profile import load_profile
            profile = load_profile()
            workload = Workload(r_tuples=int(len(rk)),
                                s_tuples=int(len(sk)),
                                key_bound=self._static_key_bound,
                                key_bits=cfg.key_bits,
                                num_nodes=cfg.num_nodes)
        except Exception:   # noqa: BLE001 — advisory only
            profile = workload = None
        span = (m.span(span_name, epoch=epoch,
                       lost_ranks=list(lost_nodes))
                if m is not None else contextlib.nullcontext())
        with span:
            plan = _recovery.plan_recovery(
                num_nodes=cfg.num_nodes, num_partitions=num_p,
                lost_ranks=lost_nodes, epoch=epoch,
                manifest=self.partition_manifest,
                weights=_recovery.partition_weights(rk, sk, num_p),
                profile=profile, workload=workload,
                joined_ranks=joined_nodes)
            hedged_parts = []
            if hedge_exc is not None and self.partition_manifest is not None:
                hedged_parts = self._claim_hedge(plan, lost_nodes, epoch)
            matches, counts = _recovery.execute_recovery(
                plan, rk, sk, rhi, shi,
                only_rank=self._recovery_scope(),
                slab=min(1 << 20, max(1, len(sk))),
                pipeline=cfg.grid_pipeline, measurements=m,
                manifest=self.partition_manifest)
            counts = self._await_peer_partitions(plan, counts,
                                                 rk, sk, rhi, shi)
            matches = int(sum(counts.values()))
        counts_out = np.zeros(num_p, np.uint32)
        for p, c in counts.items():
            counts_out[p] = c % (1 << 32)
        diag = dict(plan.to_diag(), rank_lost_detail=str(exc)[:200],
                    failure_class="ok")
        if hedge_exc is not None and self.partition_manifest is not None:
            # score the speculation against the fence winners: wins are
            # hedged partitions someone OTHER than the straggler realized
            score = {"hedgewin": 0, "specwaste": 0}
            for node in sorted(set(lost_nodes)):
                sub = [p for p in hedged_parts
                       if p % cfg.num_nodes == node]
                sc = score_hedge(self.partition_manifest, sub, node, m)
                score["hedgewin"] += sc["hedgewin"]
                score["specwaste"] += sc["specwaste"]
            diag.update(score, hedged_partitions=len(hedged_parts))
        if extra_diag:
            diag.update(extra_diag)
        self._stamp_fault_sites(diag)
        if m is not None:
            m.incr("RESULTS", matches * repeats)
            m.incr("RTUPLES", len(rk) * repeats)
            m.incr("STUPLES", len(sk) * repeats)
            m.derive_rates()
        return JoinResult(matches=matches, ok=True,
                          partition_counts=counts_out, diagnostics=diag)

    def _regrow_join(self, r: TupleBatch, s: TupleBatch, exc,
                     repeats: int) -> JoinResult:
        """:class:`RankJoined` landed mid-join (``--elastic-grow``): the
        membership GREW, so finish the aborted join over the enlarged
        set — the same resume/re-assign/recompute engine as rank loss
        with zero losses and the admission's fenced epoch.  The newcomer
        computes the same deterministic host keys every incumbent does,
        takes its reassigned share, and the shared manifest merges the
        totals (:meth:`_await_peer_partitions` waits for them)."""
        m = self.measurements
        if m is not None:
            m.event("regrow", joined_ranks=list(exc.ranks),
                    epoch=int(exc.epoch))
        epoch = max(1, int(exc.epoch), self._membership_epoch())
        return self._recover_join(
            r, s, exc, repeats, lost_nodes=[], epoch=epoch,
            span_name="regrow",
            extra_diag={"regrown": True,
                        "joined_ranks_admitted": list(exc.ranks)})

    def _hedge_join(self, r: TupleBatch, s: TupleBatch, exc,
                    repeats: int) -> JoinResult:
        """:class:`StragglerDetected` (hedging on): speculatively finish
        the straggler's unfinished partitions WITHOUT declaring anyone
        lost.  The straggler's nodes are excluded from the reassignment
        only — membership untouched, no epoch bump — and the recompute
        fences at the current epoch, so if the original lands a
        partition first the hedge's line is fenced out
        (hedge-never-double-counts) and scores as SPECWASTE."""
        m = self.measurements
        strag_nodes = self._straggler_nodes(exc)
        epoch = max(self._membership_epoch(), int(exc.epoch))
        if m is not None:
            # a hedge does NOT bump the epoch, so no membership-layer
            # stamp precedes these records — stamp the fence epoch here
            # so the HEDGED tick (and the later HEDGEWIN/SPECWASTE
            # scoring) carry it instead of forensics inferring it from
            # neighboring ring records
            m.flightrec.set_context(membership_epoch=epoch)
            m.incr(HEDGED)
            m.event("hedge", straggler=int(exc.rank), nodes=strag_nodes,
                    epoch=epoch, progress=int(exc.progress),
                    median=float(exc.median),
                    outstanding=int(exc.outstanding))
        return self._recover_join(
            r, s, exc, repeats, lost_nodes=strag_nodes, epoch=epoch,
            span_name="hedge", hedge_exc=exc,
            extra_diag={"hedged": True, "straggler": int(exc.rank)})

    def _manifest_record(self, result: JoinResult) -> None:
        """Join-epilogue manifest write: record every realized partition
        so a later death resumes at partition granularity.  Lines are
        written strictly post-realization (kill-never-overclaims); shapes
        with no per-partition decomposition (fallback/degraded results)
        and recovered results (already recorded by execute_recovery) are
        skipped."""
        mf = self.partition_manifest
        if mf is None or result is None or not result.ok:
            return
        if result.diagnostics and result.diagnostics.get("recovered"):
            return
        num_p = self.config.network_partition_count
        counts = host_readback(result.partition_counts)
        if counts.size < num_p or counts.size % num_p:
            return
        per_p = counts.astype(np.uint64).reshape(-1, num_p).sum(axis=0)
        n = self.config.num_nodes
        epoch = self._membership_epoch()
        # owner is forensic metadata (the recovery timeline), not an
        # assignment contract — node stripe order stands in for the
        # assignment map's exact ownership
        mf.mark_many({int(p): int(c) for p, c in enumerate(per_p)},
                     owner_of=lambda p: p % n, epoch=epoch)

    def _retry_backoff(self, attempt: int) -> None:
        """Optional pause between capacity-grow retries (``JoinConfig``
        backoff knobs, default off).  On shared hosts the respecialized
        attempt recompiles and reallocates windows; a deterministic
        exponential backoff keeps colocated tenants' retry storms apart."""
        cfg = self.config
        if cfg.retry_backoff_s <= 0 or attempt >= cfg.max_retries:
            return
        delay = RetryPolicy(max_attempts=cfg.max_retries + 1,
                            base_delay_s=cfg.retry_backoff_s,
                            multiplier=cfg.retry_backoff_mult,
                            max_delay_s=cfg.retry_backoff_max_s,
                            jitter=cfg.retry_jitter).delay_s(attempt)
        m = self.measurements
        if m:
            m.incr(RETRYN)
            m.incr(BACKOFFMS, int(delay * 1000))
            m.event("retry", site="engine.capacity", attempt=attempt,
                    delay_s=round(delay, 6))
        time.sleep(delay)

    def _fallback_chunked(self, r: TupleBatch, s: TupleBatch, diag: dict,
                          cap_r: int, cap_s: int) -> JoinResult:
        """Graceful degradation: the shuffle windows cannot be sized for
        this workload within ``max_retries`` doublings, so finish the join
        out-of-core (ops/chunked.py).  The chunked count's only capacity is
        the slab size — chosen here, not measured — so it cannot overflow;
        it is slower (host slabs, no all_to_all overlap) but returns the
        exact count where the engine path would return ok=False."""
        m = self.measurements
        from tpu_radix_join.ops.chunked import chunked_join_count
        diag = dict(diag, failure_class=CAPACITY_OVERFLOW,
                    degraded="chunked")
        self._stamp_fault_sites(diag)
        try:
            slab = min(1 << 20, s.size)
            matches = chunked_join_count(
                TupleBatch(key=jnp.asarray(self._to_host(r.key)), rid=r.rid,
                           key_hi=None if r.key_hi is None
                           else jnp.asarray(self._to_host(r.key_hi))),
                TupleBatch(key=jnp.asarray(self._to_host(s.key)), rid=s.rid,
                           key_hi=None if s.key_hi is None
                           else jnp.asarray(self._to_host(s.key_hi))),
                slab, key_range="auto")
        except Exception as e:   # degraded path must never raise past here
            diag["fallback_error"] = repr(e)
            diag["failure_class"] = RETRIES_EXHAUSTED
            if m:
                m.stop("JTOTAL")
                m.event("fallback", path="chunked", ok=False, error=repr(e))
                m.derive_rates()
            return JoinResult(matches=0, ok=False,
                              partition_counts=np.zeros(1, np.uint32),
                              diagnostics=diag)
        if m:
            m.stop("JTOTAL")
            m.incr("RESULTS", matches)
            m.incr("RTUPLES", r.size)
            m.incr("STUPLES", s.size)
            m.event("fallback", path="chunked", ok=True, slab=slab)
            m.derive_rates()
        return JoinResult(matches=matches, ok=True,
                          partition_counts=np.array([matches % (1 << 32)],
                                                    np.uint32),
                          diagnostics=diag)

    def _verified_finish(self, r: TupleBatch, s: TupleBatch,
                         pristine_s: Optional[TupleBatch], counts, flags,
                         diag: dict, pre, vchk, cap_r: int, cap_s: int,
                         skew_plan, repeats: int) -> JoinResult:
        """Integrity verdict on an accepted flag-clean attempt: compare the
        pre-exchange fingerprints against every set the pipeline recomputed
        (post-exchange always; post-local-sort on the bucket path), then
        cross-check the reported counts against the per-partition
        cross-product bound.  Intact -> the normal epilogue; damaged ->
        ``data_corruption`` (check mode) or partition-granular recompute
        (repair mode)."""
        m = self.measurements
        cfg = self.config
        num_p = cfg.network_partition_count
        if m:
            m.start(VCHK)
        pre_h = self._to_host(pre)
        vchk_h = self._to_host(vchk)
        damaged = set()
        ncomp = 0
        for k in range(vchk_h.shape[0]):
            # sets alternate R/S (post-exchange pair, then the bucket
            # path's post-local-sort pair) — each compares against its
            # relation's pre-exchange fingerprint
            ncomp += 1
            damaged.update(int(p) for p in _verify.damaged_partitions(
                pre_h[k % 2], vchk_h[k]))
        counts_h = self._to_host(counts)
        cross = None
        if not damaged and not cfg.bucket_path and skew_plan is None:
            # bucket-path counts are per local bucket and a skew plan
            # replicates hot R (its pre fingerprint excludes those
            # partitions) — the per-network-partition bound only means
            # something on the plain sort/chunked layouts
            ncomp += 1
            cross = _verify.cross_check_counts(
                counts_h.reshape(cfg.num_nodes, num_p),
                int(counts_h.astype(np.uint64).sum()),
                pre_h[0][0], pre_h[1][0])
        if m:
            m.stop(VCHK)
            m.incr(VCHKN, ncomp)
        if not damaged and cross is None:
            return self._finish_join(r, s, counts_h, flags, diag, cap_r,
                                     cap_s, repeats)
        dmg = sorted(damaged)
        if m:
            m.incr(VFAIL)
            m.event("data_corruption", partitions=dmg[:16],
                    comparisons=ncomp, cross=cross)
        diag = dict(diag, data_corruption_partitions=max(1, len(dmg)))
        if cross is not None:
            diag["data_corruption_cross"] = cross
        diag["failure_class"] = classify_diagnostics(diag)
        if cfg.verify != "repair":
            result = self._finish_join(r, s, counts_h, flags, diag, cap_r,
                                       cap_s, repeats)
            return result._replace(ok=False)
        return self._repair(r, pristine_s if pristine_s is not None else s,
                            counts_h, diag, dmg, repeats)

    def _repair(self, r: TupleBatch, s: TupleBatch, counts_h: np.ndarray,
                diag: dict, dmg, repeats: int) -> JoinResult:
        """``verify="repair"``: recompute only the damaged network
        partitions from the pristine inputs and splice their counts back —
        the degrade-not-fail discipline of _fallback_chunked, at partition
        granularity.  The sort/chunked count layouts expose one column per
        network partition, so intact columns are kept and each damaged
        partition re-joins out-of-core as its own 1x1 grid (grid-pair
        spans + GRIDPAIRS make the narrow scope observable); the bucket
        layout can't be decomposed per network partition, so it recomputes
        the whole join — still without failing it."""
        m = self.measurements
        cfg = self.config
        num_p = cfg.network_partition_count
        from tpu_radix_join.ops.chunked import (chunked_join_count,
                                                chunked_join_grid)
        rk = self._to_host(r.key)
        sk = self._to_host(s.key)
        rhi = None if r.key_hi is None else self._to_host(r.key_hi)
        shi = None if s.key_hi is None else self._to_host(s.key_hi)
        slab = min(1 << 20, max(1, s.size))
        scope = "partition"
        if cfg.bucket_path or not dmg:
            # per-bucket counts (or a cross-check violation, which names no
            # partition): full out-of-core recompute
            scope = "full"
            matches = chunked_join_count(
                TupleBatch(key=jnp.asarray(rk), rid=r.rid,
                           key_hi=None if rhi is None else jnp.asarray(rhi)),
                TupleBatch(key=jnp.asarray(sk), rid=s.rid,
                           key_hi=None if shi is None else jnp.asarray(shi)),
                slab, key_range="auto")
            counts_out = np.array([matches % (1 << 32)], np.uint32)
        else:
            cols = counts_h.reshape(cfg.num_nodes, num_p).astype(np.uint64)
            for p in dmg:
                cols[:, p] = 0
            intact = int(cols.sum())
            mask = np.uint32(num_p - 1)
            total_repaired = 0
            for p in dmg:
                rsel = (rk & mask) == p
                ssel = (sk & mask) == p
                cnt = 0
                if rsel.any() and ssel.any():
                    cnt = chunked_join_grid(
                        [TupleBatch(
                            key=jnp.asarray(rk[rsel]),
                            rid=jnp.zeros(int(rsel.sum()), jnp.uint32),
                            key_hi=None if rhi is None
                            else jnp.asarray(rhi[rsel]))],
                        [TupleBatch(
                            key=jnp.asarray(sk[ssel]),
                            rid=jnp.zeros(int(ssel.sum()), jnp.uint32),
                            key_hi=None if shi is None
                            else jnp.asarray(shi[ssel]))],
                        min(slab, int(ssel.sum())), measurements=m,
                        pipeline=cfg.grid_pipeline)
                # the recomputed count has no per-device decomposition;
                # park it in row 0 of its column (the uint64 total above
                # is exact — partition_counts stays a uint32 view)
                cols[0, p] = cnt % (1 << 32)
                total_repaired += cnt
            matches = intact + total_repaired
            counts_out = cols.astype(np.uint32).reshape(counts_h.shape)
        diag = dict(diag, repaired=scope,
                    repaired_partitions=[int(p) for p in dmg])
        self._stamp_fault_sites(diag)
        if m:
            m.incr(VREPAIR, max(1, len(dmg)))
            m.event("repair", scope=scope,
                    partitions=[int(p) for p in dmg][:16])
            m.stop("JTOTAL")
            m.incr("RESULTS", matches * repeats)
            m.incr("RTUPLES", r.size * repeats)
            m.incr("STUPLES", s.size * repeats)
            m.derive_rates()
        return JoinResult(matches=matches, ok=True,
                          partition_counts=counts_out, diagnostics=diag)

    def _finish_join(self, r: TupleBatch, s: TupleBatch, counts, flags,
                     diag: dict, cap_r: int, cap_s: int,
                     repeats: int) -> JoinResult:
        """Shared join epilogue: host readback, cumulative counters (once
        per dispatched join — the reference counts its exchange in the hot
        loop per Put, Measurements.cpp:272-349), derived rates, result."""
        m = self.measurements
        self._stamp_fault_sites(diag)
        counts = self._to_host(counts)
        matches = int(counts.astype(np.uint64).sum())
        if m:
            m.stop("JTOTAL")
            m.incr("RESULTS", matches * repeats)
            m.incr("RTUPLES", r.size * repeats)
            m.incr("STUPLES", s.size * repeats)
            if not self._single_node_sort_probe():
                # the n==1 specialization performs no exchange at all —
                # recording its dummy capacities would invent network stats
                xs = self._exchange_stats(cap_r, cap_s)
                m.meta["exchange_plan"] = xs
                with m.span("exchange_stage", stages=xs["stages"],
                            peak_exchange_bytes=xs["peak_exchange_bytes"]):
                    pass   # zero-length marker: the staged collectives run
                           # inside the jitted pipeline, untimeable from host
                for _ in range(repeats):
                    m.record_exchange(
                        self.config.num_nodes, cap_r, cap_s,
                        tuple_bytes=8 if r.key_hi is None else 12,
                        wire_bytes=xs["wire_bytes"],
                        pack_ratio_pct=xs["pack_ratio_pct"],
                        stages=xs["stages"])
            m.derive_rates()
        return JoinResult(matches=matches, ok=not flags.any(),
                          partition_counts=counts, diagnostics=diag)

    def join_materialize_arrays(self, r: TupleBatch,
                                s: TupleBatch) -> MaterializedJoinResult:
        """Full join with materialized rid pairs (vs. the count-only default —
        the same distinction as the reference's probe_kernel_eth count-only
        path vs. probe_match_rate, kernels.cu:314-411)."""
        set_default_sort_impl(self.config.sort_impl)
        n = self.config.num_nodes
        if r.size % n or s.size % n:
            raise ValueError("relation sizes must divide the mesh size")
        self._check_key_width(r, s)
        self._check_cancel("start")
        m = self.measurements
        if m:
            m.start("JTOTAL")
            m.start("SWINALLOC")
        self._measured_key_bound = None
        cap_r, cap_s, skew_plan = self._measure_capacities(r, s)
        if m:
            m.stop("SWINALLOC")
        self._xplan = self._resolve_exchange_plan(r, s)
        rate_cap = self.config.match_rate_cap
        use_split = self.config.measure_phases
        for attempt in range(self.config.max_retries + 1):
            if use_split:
                r_rid, s_rid, valid, flags, dts = self._run_split_materialize(
                    r, s, cap_r, cap_s, rate_cap, skew_plan)
            else:
                key = ("mat", r.size // n, s.size // n, cap_r, cap_s,
                       rate_cap, skew_plan, r.key_hi is None,
                       s.key_hi is None, self._xplan,
                       getattr(r.key, "sharding", None),
                       getattr(s.key, "sharding", None))
                fn = self._compile_timed(
                    key,
                    lambda: self._materialize_fn(
                        cap_r, cap_s, rate_cap, skew_plan
                    ).lower(r, s).compile())
                if m:
                    m.start("JPROC")
                r_rid, s_rid, valid, flags = fn(r, s)
                dts = ({"JPROC": m.stop("JPROC", fence=(r_rid, flags))}
                       if m else {})
            flags = self._inject_shuffle_fault(host_readback(flags))
            diag = self._flags_to_diag(flags)
            if not flags.any() or not self._retryable(diag):
                break
            if diag["shuffle_overflow_r_tuples"]:
                cap_r *= 2
            if diag["shuffle_overflow_s_tuples"]:
                cap_s *= 2
            if diag["local_overflow"]:        # match-rate cap shortfall
                rate_cap *= 2
            if diag["hot_overflow"]:
                skew_plan = (skew_plan[0], 2 * skew_plan[1])
            if m and attempt < self.config.max_retries:
                self._rollback_attempt(m, dts)
        if getattr(valid, "is_fully_addressable", True):
            valid = host_readback(valid)
            r_rid = host_readback(r_rid)[valid]
            s_rid = host_readback(s_rid)[valid]
        else:
            # multi-process: ONE collective for all three lanes instead of
            # three sequential full-buffer allgathers of mostly-padding rows
            stacked = self._to_host(jnp.stack(
                [r_rid, s_rid, valid.astype(jnp.uint32)]))
            valid = stacked[2].astype(bool)
            r_rid = stacked[0][valid]
            s_rid = stacked[1][valid]
        if m:
            m.stop("JTOTAL")
            m.incr("RESULTS", int(valid.sum()))
            m.incr("RTUPLES", r.size)
            m.incr("STUPLES", s.size)
            xs = self._exchange_stats(cap_r, cap_s)
            m.meta["exchange_plan"] = xs
            m.record_exchange(n, cap_r, cap_s,
                              tuple_bytes=8 if r.key_hi is None else 12,
                              wire_bytes=xs["wire_bytes"],
                              pack_ratio_pct=xs["pack_ratio_pct"],
                              stages=xs["stages"])
            m.derive_rates()
        self._stamp_fault_sites(diag)
        return MaterializedJoinResult(r_rid=r_rid, s_rid=s_rid,
                                      matches=int(valid.sum()),
                                      ok=not flags.any(), diagnostics=diag)

    def place(self, rel: Relation) -> TupleBatch:
        """Generate a relation's shards and lay them out over the mesh.

        ``config.generation`` picks the path: on-device sharded generation
        (``Relation.generate_sharded`` — no host materialization or
        host->device transfer; the reference generates host-side,
        Relation.cpp:63-97, which SURVEY.md §7.4 item 5 calls out as the
        thing NOT to scale) when the kind supports it, else host ``shard_np``
        + ``device_put``.  Either way the lane count must agree with
        ``config.key_bits`` — a 64-bit config with 32-bit shards (or vice
        versa) raises rather than silently truncating (the failure class
        VERDICT r2 weak #1 flagged)."""
        cfg = self.config
        n = cfg.num_nodes
        if rel.num_nodes != n:
            raise ValueError("relation num_nodes must match config.num_nodes")
        if rel.key_bits != cfg.key_bits:
            raise ValueError(
                f"config.key_bits={cfg.key_bits} but the relation generates "
                f"{rel.key_bits}-bit keys ({'a spurious' if rel.key_bits == 64 else 'no'} "
                f"hi key lane) — widen the config or regenerate with the "
                f"matching key_bits")
        if cfg.generation != "host":
            batch = rel.generate_sharded(self.mesh, cfg.mesh_axes)
            if batch is not None:
                # fence before returning: generation is async, and the
                # reference generates strictly before its join timers start
                # (main.cpp:94-116) — an in-flight generation completing
                # inside the first join's fence would inflate its phase times
                return jax.block_until_ready(batch)
            if cfg.generation == "device":
                # unreachable for today's kinds (unique/modulo/zipf all
                # generate on device since r4); kept for future kinds
                raise ValueError(
                    f"generation='device' but relation kind {rel.kind!r} "
                    f"has no on-device generator")
        sharding = NamedSharding(self.mesh, P(cfg.mesh_axes))
        shards = [rel.shard_np(i) for i in range(n)]
        wide = rel.key_bits == 64   # authoritative; shard_np must agree
        if len(shards[0]) != (3 if wide else 2):
            raise ValueError(
                f"shard_np returned {len(shards[0])} lanes but key_bits="
                f"{rel.key_bits} implies {'(lo, hi, rid)' if wide else '(key, rid)'}")

        def put(arrs):
            full = np.concatenate(arrs)
            if sharding.is_fully_addressable:
                return jax.device_put(full, sharding)
            # multi-process mesh: every process generates the same global
            # relation and contributes only its addressable shards
            return jax.make_array_from_callback(
                full.shape, sharding, lambda idx: full[idx])

        keys = put([sh[0] for sh in shards])
        rids = put([sh[-1] for sh in shards])
        hi = put([sh[1] for sh in shards]) if wide else None
        # same fence as the device path: the transfer must not complete
        # inside a later join's phase timers
        return jax.block_until_ready(TupleBatch(key=keys, rid=rids, key_hi=hi))

    def _place(self, rel: Relation) -> TupleBatch:
        """Alias kept for call-site continuity (tests exercise it too);
        a def — not a class-attribute binding — so subclass overrides of
        :meth:`place` are honored (ADVICE r3)."""
        return self.place(rel)

    def join(self, inner: Relation, outer: Relation) -> JoinResult:
        """Join two relation specs (generates shards, shards onto the mesh).

        Records the relations' static key bounds so ``key_range="auto"``
        resolves without the device max-key probe (:meth:`_resolve_key_range`)."""
        self._static_key_bound = max(inner.key_bound(), outer.key_bound())
        # recovery's host-side input path: the seeded specs regenerate the
        # global relations without touching a (possibly wedged) mesh
        self._elastic_rel = (inner, outer)
        try:
            return self.join_arrays(self.place(inner), self.place(outer))
        finally:
            self._static_key_bound = None
            self._elastic_rel = None

    def join_materialize(self, inner: Relation,
                         outer: Relation) -> MaterializedJoinResult:
        return self.join_materialize_arrays(self.place(inner),
                                            self.place(outer))
