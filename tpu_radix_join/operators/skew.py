"""Hot-partition skew splitting.

The TPU-native counterpart of the reference's probe-level skew machinery
(``operators/gpu/kernels_optimized.cu:301-344`` skew_detect + block remapping,
``:364-672`` probe_skew variants, ``:864-943`` dynamic-parallelism child
kernels): partitions whose weight exceeds a threshold get more execution
resources than the default one-partition-one-owner mapping allows.

Assignment-level balancing (histograms/assignment_map.py) cannot help a
*single* dominant partition — all its tuples land on one device whatever the
map says.  The split here changes the data movement instead (SURVEY.md §5.7
"skew splitting becomes capacity-padded buckets + a second-chance pass",
refined): for each detected hot partition

  * the INNER (build) side is **replicated**: every device extracts its local
    hot-R tuples into a capacity-padded block and an ``all_gather`` hands every
    device the full hot build side;
  * the OUTER (probe) side is **sharded**: hot-S tuples ignore the assignment
    map and spread round-robin by rid across all devices;
  * each device probes its S shard against the replicated R and the
    per-partition counts ``psum``/host-sum to the exact global total (every S
    tuple meets the full hot R exactly once).

Detection is a host-side decision on the (already computed) global histograms
— the shape-specialization philosophy of the pipeline: the hot set is baked
into the compiled program as a constant, like the reference bakes its skew
threshold into ``skew_detect`` (kernels_optimized.cu:301-311).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# The in-program hot test is a vectorized bit probe against one uint32
# constant, so the splittable fanout is capped at 32 partitions (the
# reference's default NETWORK_PARTITIONING_COUNT, Configuration.h:33).
MAX_SKEW_PARTITIONS = 32


def detect_hot_partitions(r_ghist: np.ndarray, s_ghist: np.ndarray,
                          threshold: float) -> np.ndarray:
    """bool [P]: partitions whose combined (R+S) global weight exceeds
    ``threshold`` x the mean partition weight (skew_detect's
    blocks-per-partition criterion, kernels_optimized.cu:301-311, reduced to
    a binary split/don't-split decision)."""
    w = r_ghist.astype(np.float64) + s_ghist.astype(np.float64)
    return w > threshold * w.mean()


def hot_mask_bits(hot: np.ndarray) -> int:
    """Pack a bool [P<=32] mask into the uint32 program constant."""
    if hot.shape[0] > MAX_SKEW_PARTITIONS:
        raise ValueError(
            f"skew splitting supports at most {MAX_SKEW_PARTITIONS} "
            f"network partitions, got {hot.shape[0]}")
    return sum(1 << i for i, h in enumerate(hot) if h)


def is_hot(pid: jnp.ndarray, hot_bits: int) -> jnp.ndarray:
    """Vectorized membership test: bool [n] for uint32 partition ids."""
    return ((jnp.uint32(hot_bits) >> pid) & jnp.uint32(1)) == jnp.uint32(1)


def spread_destinations(rid: jnp.ndarray, num_nodes: int) -> jnp.ndarray:
    """Destination for hot outer tuples: round-robin by rid — dense rids give
    an exactly balanced shard, arbitrary rids a hash-balanced one (the analog
    of generate_block_mapping distributing a hot partition's chunks over
    blocks, kernels_optimized.cu:321-344)."""
    return rid % jnp.uint32(num_nodes)


def mask_hot(hist: jnp.ndarray, hot_bits: int) -> jnp.ndarray:
    """Zero the hot partitions of a [P] histogram: hot partitions leave the
    normal assignment/window accounting entirely."""
    p = hist.shape[0]
    hot = is_hot(jnp.arange(p, dtype=jnp.uint32), hot_bits)
    return jnp.where(hot, jnp.zeros_like(hist), hist)
