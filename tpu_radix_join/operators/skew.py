"""Hot-partition skew splitting.

The TPU-native counterpart of the reference's probe-level skew machinery
(``operators/gpu/kernels_optimized.cu:301-344`` skew_detect + block remapping,
``:364-672`` probe_skew variants, ``:864-943`` dynamic-parallelism child
kernels): partitions whose weight exceeds a threshold get more execution
resources than the default one-partition-one-owner mapping allows.

Assignment-level balancing (histograms/assignment_map.py) cannot help a
*single* dominant partition — all its tuples land on one device whatever the
map says.  The split here changes the data movement instead (SURVEY.md §5.7
"skew splitting becomes capacity-padded buckets + a second-chance pass",
refined): for each detected hot partition

  * the INNER (build) side is **replicated**: every device extracts its local
    hot-R tuples into a capacity-padded block and an ``all_gather`` hands every
    device the full hot build side;
  * the OUTER (probe) side is **sharded**: hot-S tuples ignore the assignment
    map and spread round-robin by rid across all devices;
  * each device probes its S shard against the replicated R and the
    per-partition counts ``psum``/host-sum to the exact global total (every S
    tuple meets the full hot R exactly once).

Detection is a host-side decision on the (already computed) global histograms
— the shape-specialization philosophy of the pipeline: the hot set is baked
into the compiled program as a constant, like the reference bakes its skew
threshold into ``skew_detect`` (kernels_optimized.cu:301-311).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpu_radix_join.utils.hashing import mix32

# The in-program hot test is a vectorized bit probe against one uint32
# constant, so the splittable fanout is capped at 32 partitions (the
# reference's default NETWORK_PARTITIONING_COUNT, Configuration.h:33).
MAX_SKEW_PARTITIONS = 32


def detect_hot_partitions(r_ghist: np.ndarray, s_ghist: np.ndarray,
                          threshold: float,
                          num_nodes: int = 0) -> np.ndarray:
    """bool [P]: partitions worth splitting (skew_detect's
    blocks-per-partition criterion, kernels_optimized.cu:301-311, reduced to
    a binary split/don't-split decision).

    The split replicates the partition's entire R to every device and spreads
    its S, so it pays off exactly when the *probe* side dominates: detection
    requires (a) the S weight alone to exceed ``threshold`` x the mean total
    partition weight, and (b) the replication to be affordable — either the
    R side is not itself hot (within ``threshold`` x the mean R weight), or,
    when ``num_nodes`` is given, the replication cost is dominated by the
    probe work being spread (``num_nodes * R[p] <= S[p]``).  The absolute
    clause matters for small build sides, where a relatively elevated but
    absolutely tiny R must not veto spreading millions of probe tuples; a
    genuinely build-heavy partition still stays single-owner (n-fold
    memory/ICI to replicate precisely where R is largest — ADVICE r2)."""
    r = r_ghist.astype(np.float64)
    s = s_ghist.astype(np.float64)
    w = r + s
    affordable = r <= threshold * max(r.mean(), 1.0)
    if num_nodes > 0:
        affordable |= (num_nodes * r) <= s
    return (s > threshold * w.mean()) & affordable


def hot_mask_bits(hot: np.ndarray) -> int:
    """Pack a bool [P<=32] mask into the uint32 program constant."""
    if hot.shape[0] > MAX_SKEW_PARTITIONS:
        raise ValueError(
            f"skew splitting supports at most {MAX_SKEW_PARTITIONS} "
            f"network partitions, got {hot.shape[0]}")
    return sum(1 << i for i, h in enumerate(hot) if h)


def is_hot(pid: jnp.ndarray, hot_bits: int) -> jnp.ndarray:
    """Vectorized membership test: bool [n] for uint32 partition ids."""
    return ((jnp.uint32(hot_bits) >> pid) & jnp.uint32(1)) == jnp.uint32(1)


def spread_destinations(rid: jnp.ndarray, num_nodes: int) -> jnp.ndarray:
    """Destination for hot outer tuples: a cheap integer mix of the rid,
    modulo the mesh size (the analog of generate_block_mapping distributing a
    hot partition's chunks over blocks, kernels_optimized.cu:321-344).

    The mix (utils/hashing.mix32) matters: raw ``rid % n`` puts every tuple
    of a pre-filtered/strided outer side whose rids are congruent mod n back
    on ONE device — silently recreating the skew the split exists to fix.
    The sizing program and the shuffle both call this, so measured
    capacities stay exact for any rid pattern."""
    return mix32(rid) % jnp.uint32(num_nodes)


def mask_hot(hist: jnp.ndarray, hot_bits: int) -> jnp.ndarray:
    """Zero the hot partitions of a [P] histogram: hot partitions leave the
    normal assignment/window accounting entirely."""
    p = hist.shape[0]
    hot = is_hot(jnp.arange(p, dtype=jnp.uint32), hot_bits)
    return jnp.where(hot, jnp.zeros_like(hist), hist)
