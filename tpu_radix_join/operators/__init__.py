from tpu_radix_join.operators.hash_join import (
    HashJoin,
    JoinResult,
    MaterializedJoinResult,
)
from tpu_radix_join.operators.local_partitioning import local_partition

__all__ = ["HashJoin", "JoinResult", "MaterializedJoinResult",
           "local_partition"]
