from tpu_radix_join.core.config import JoinConfig

__all__ = ["JoinConfig"]
