"""Typed runtime configuration.

TPU-native replacement for the reference's compile-time constant header
(``core/Configuration.h:15-40``) plus its CMake ``-D`` switches
(``CMakeLists.txt:10-15``): one frozen dataclass whose derived quantities
(partition counts, packing layout, padded shuffle capacities) are computed
properties, so the relationships the reference spreads across four files
(``NetworkPartitioning.cpp:128-129``, ``LocalPartitioning.cpp:147-153``,
``BuildProbe.cpp:55-61``, ``GPUWrapper.cu:39-41``) live in one place.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1).bit_length())


@dataclasses.dataclass(frozen=True)
class JoinConfig:
    """All knobs of the join pipeline.

    The reference equivalents:
      * ``network_fanout_bits``  -> ``NETWORK_PARTITIONING_FANOUT`` (Configuration.h:30)
      * ``local_fanout_bits``    -> ``LOCAL_PARTITIONING_FANOUT`` (Configuration.h:31)
      * ``payload_bits``         -> ``PAYLOAD_BITS`` (Configuration.h:38)
      * ``two_level``            -> ``ENABLE_TWO_LEVEL_PARTITIONING`` (Configuration.h:28)
      * ``allocation_factor``    -> ``ALLOCATION_FACTOR`` (Configuration.h:36); here it is
        the slack on the statically-shaped per-destination shuffle blocks rather than on
        a malloc'd pool, because XLA requires static shapes (SURVEY.md 7.2).
      * ``result_aggregation_node`` -> ``RESULT_AGGREGATION_NODE`` (Configuration.h:19)
      * ``assignment_policy``    -> AssignmentMap policy (AssignmentMap.cpp:41-43 is
        round-robin; "load_aware" realises the skew-aware API shape its ctor promises).
      * ``probe_algorithm``      -> selects among the BuildProbe / GPU probe-kernel
        families (BuildProbe.cpp chained table; kernels.cu probe / probe_count).
    """

    # --- partitioning geometry -------------------------------------------------
    network_fanout_bits: int = 5
    local_fanout_bits: int = 5
    two_level: bool = False

    # --- tuple layout ----------------------------------------------------------
    key_bits: int = 32           # 32 -> single uint32 key lane; 64 -> hi/lo lanes
    payload_bits: int = 27       # rid width contract (Configuration.h:38)
    # 32-bit count-path key-range discipline (the sort probe packs key+side
    # into one uint32, capping real keys at 2**31-3 = MAX_MERGE_KEY;
    # ops/merge_count.py):
    #   "narrow" — always the packed fast path; keys above the cap flip
    #              key_contract_violations (loud, never silent).
    #   "full"   — always the full-range 2-key lexicographic discipline
    #              (merge_count_per_partition_full): every sub-sentinel
    #              uint32 key (<= 0xFFFFFFFD) joins exactly, ~1.7x the
    #              packed sort cost.
    #   "auto"   — per join: Relation-driven entry points decide statically
    #              from the relations' key bounds (Relation.key_bound);
    #              join_arrays probes the device max key once (~2 HBM
    #              scans) — set narrow/full explicitly to skip the probe.
    # Irrelevant to key_bits=64 (always the wide 3-lane path), the bucket/
    # two-level, chunked, and materializing disciplines (never packed).
    key_range: str = "auto"

    # --- distribution ----------------------------------------------------------
    num_nodes: int = 1           # total mesh size (all devices, all hosts)
    num_hosts: int = 1           # >1 selects the hierarchical (dcn, ici) mesh
    mesh_axis: str = "nodes"
    result_aggregation_node: int = 0

    # --- shuffle data plane (Window) ------------------------------------------
    # "measured": run the histogram phase as its own program and compile the
    #   shuffle at the exact (pow2-rounded) worst-case block demand — the
    #   analog of the reference's runtime-sized windows (Window.cpp:168-177).
    # "static": skip the sizing pre-pass; capacity = local_size / N *
    #   allocation_factor (cheaper, can overflow under skew; overflow flips ok).
    window_sizing: str = "measured"
    allocation_factor: float = 1.5   # slack multiplier on padded blocks (static
                                     # window sizing + local bucket capacities)
    # Wire codec for the shuffle exchange (data/tuples.make_wire_spec):
    #   "off"  — two/three uint32 lanes per tuple on the wire (8/12 B), plus a
    #            separate per-sender count collective (the pre-codec format).
    #   "pack" — bounds-aware bit-packed blocks: fanout bits dropped from
    #            keys (restored positionally from per-partition header
    #            counts), key remainder and rid packed to the minimum lane
    #            budget implied by the key bound / relation sizes; the count
    #            side channel folds into the header, eliminating one
    #            collective per relation per exchange.
    #   "auto" — the engine (or the planner) packs only when the packed
    #            block is actually smaller than the raw lanes.
    # Note: packing masks key bits above the measured bound, so injected
    # corruption in those high bits (chaos exchange.corrupt_lane) is healed
    # rather than detected — keep "off" when chaos-testing lane corruption.
    exchange_codec: str = "off"
    # Staged exchange (parallel/window.block_all_to_all): split the [N, C]
    # block buffer into k column groups exchanged via k smaller sequenced
    # collectives, bounding live exchange-buffer memory to ~1/k.
    # 1 = fused single collective; 0 = auto (engine/planner picks by block
    # size); k > 1 = exactly k stages.
    exchange_stages: int = 1
    # Partition/reorder implementation (ops/radix scatter_to_blocks &
    # friends):
    #   "auto"   — fused Pallas partition kernel when the backend compiles
    #              Mosaic and the fanout fits MAX_PARTITIONS, else the
    #              XLA sort path (the fallback ticks PARTFALLBACK).
    #   "sort"   — force the XLA sort-based scatter (the pre-kernel path).
    #   "pallas" / "pallas_interpret" — force the fused kernel (interpret
    #              runs it through the Pallas interpreter: CPU tier-1
    #              parity tests and host-mesh benches).
    partition_impl: str = "auto"
    # Sort implementation behind every hot reorder (ops/sorting.py:
    # merge_count presort, bucket build/probe, verify xor-fold, grouped
    # codec — all inherit it with zero call-site edits):
    #   "auto"   — Pallas LSD radix sort (ops/pallas/radix_sort.py) when
    #              the backend compiles Mosaic, the lanes are 1-D uint32,
    #              and the sort is big enough to amortize the digit
    #              passes; else lax.sort (the degrade ticks SORTFALLBACK
    #              once per process and logs once).
    #   "xla"    — force lax.sort (the pre-kernel sort floor).
    #   "pallas" / "pallas_interpret" — force the radix sort for every
    #              eligible sort (interpret = the Pallas interpreter:
    #              CPU tier-1 parity tests and host-mesh benches).
    sort_impl: str = "auto"

    # --- policies --------------------------------------------------------------
    assignment_policy: str = "round_robin"   # or "load_aware"
    probe_algorithm: str = "sort"            # "sort" | "bucket"
    match_rate_cap: int = 8                  # max materialized matches per outer tuple
    chunk_size: Optional[int] = None         # out-of-core probe chunking (LD kernels)
    max_retries: int = 0                     # capacity-shortfall retries with doubled
                                             # static shapes (0 = detect only, the
                                             # reference's abort-on-failure parity)

    # --- resilience (robustness/) ----------------------------------------------
    # Terminal behavior once max_retries capacity doublings are exhausted:
    #   "none"    — return ok=False with diagnostics (detect-and-report).
    #   "chunked" — degrade to the out-of-core chunked count (ops/chunked.py),
    #               whose only capacity is the caller-chosen slab size; the
    #               result carries diagnostics["degraded"] = "chunked".
    fallback: str = "none"
    # Out-of-core grid engine (ops/chunked.chunked_join_grid) used by the
    # chunked fallback and verify="repair":
    #   "off"  — synchronous loop (one probe, one readback, one checkpoint
    #            fsync per pair, in program order).
    #   "on"   — pipelined engine: once-per-row inner sorts probed by
    #            binary search, double-buffered chunk prefetch, deferred
    #            readbacks, write-behind checkpoints.
    #   "auto" — pipelined for any grid larger than a single chunk pair.
    grid_pipeline: str = "auto"
    # Pause between capacity-grow retry attempts (0 = immediate, the
    # pre-robustness behavior).  Exponential with deterministic jitter
    # (robustness/retry.RetryPolicy): attempt k sleeps
    # min(retry_backoff_s * retry_backoff_mult**k, retry_backoff_max_s).
    retry_backoff_s: float = 0.0
    retry_backoff_mult: float = 2.0
    retry_backoff_max_s: float = 30.0
    retry_jitter: float = 0.0

    # --- skew handling ---------------------------------------------------------
    # Probe-level hot-partition splitting (operators/skew.py; the reference's
    # dormant SD::OPT skew machinery, kernels_optimized.cu:301-344,864-943):
    # partitions whose global OUTER weight exceeds skew_threshold x the mean
    # total weight (and whose inner side is cheap enough to replicate) are
    # split — inner side replicated via all_gather, outer side spread by a
    # rid hash — instead of owned by one node.  None disables.  Composes
    # with the sort probe AND the two-level/bucket discipline (the
    # reference's own skew locus is its partitioned probe kernels,
    # kernels_optimized.cu:301-943: replicated hot R simply joins the local
    # radix pass); only the chunked out-of-core probe is excluded (see
    # __post_init__).  Requires network fanout <= 5 (the hot set is a
    # uint32 bit mask) and measured window sizing.
    skew_threshold: Optional[float] = None

    # --- data placement --------------------------------------------------------
    # How Relation-driven entry points materialize shards (SURVEY.md §7.4
    # item 5): "auto" generates on device when the relation kind supports it
    # — since r4 that is every kind (unique/modulo: Feistel walk / residues;
    # zipf: integer-table sampler), all bit-identical to the host twins —
    # with host generation + device_put as the fallback for future kinds;
    # "host" forces the host path (useful for debugging); "device" requires
    # on-device generation.
    generation: str = "auto"

    # --- integrity verification (robustness/verify.py) -------------------------
    # End-to-end per-partition integrity checksums (count + sum + xor-fold of
    # key lanes), computed over the pristine inputs before the exchange and
    # re-derived from the pipeline after exchange / after local sort:
    #   "off"    — no checksums (production default; zero overhead).
    #   "check"  — mismatch => ok=False, failure_class="data_corruption"
    #              (VFAIL counter + a data_corruption event).
    #   "repair" — mismatch => recompute only the damaged network partitions
    #              from the retained pristine inputs via the chunked grid
    #              machinery (VREPAIR counter + grid_pair spans), then return
    #              a corrected ok=True result.
    verify: str = "off"

    # --- instrumentation -------------------------------------------------------
    debug_checks: bool = False   # runtime conservation invariants (JOIN_ASSERT analog)
    # Phase-split timing (Measurements.cpp:139-141 JMPI/JPROC columns): run
    # the shuffle and the local probe as two programs so host timers see each
    # phase, instead of one fused program (which XLA may overlap/fuse across
    # the phase boundary — faster, but host-opaque).  Costs the fusion.
    measure_phases: bool = False

    def __post_init__(self):
        if self.network_fanout_bits < 0 or self.local_fanout_bits < 0:
            raise ValueError("fanout bits must be non-negative")
        if self.key_bits not in (32, 64):
            raise ValueError("key_bits must be 32 or 64")
        if self.num_nodes < 1:
            raise ValueError("num_nodes must be >= 1")
        if self.num_hosts < 1 or self.num_nodes % self.num_hosts:
            raise ValueError("num_nodes must divide evenly over num_hosts")
        if self.assignment_policy not in ("round_robin", "load_aware"):
            raise ValueError(f"unknown assignment policy {self.assignment_policy!r}")
        if self.probe_algorithm not in ("sort", "bucket"):
            raise ValueError(f"unknown probe algorithm {self.probe_algorithm!r}")
        if self.allocation_factor < 1.0:
            raise ValueError("allocation_factor must be >= 1.0")
        if self.window_sizing not in ("measured", "static"):
            raise ValueError(f"unknown window sizing mode {self.window_sizing!r}")
        if self.exchange_codec not in ("off", "pack", "auto"):
            raise ValueError(
                f"unknown exchange codec {self.exchange_codec!r} "
                "(expected 'off', 'pack', or 'auto')")
        if self.exchange_stages < 0:
            raise ValueError(
                "exchange_stages must be >= 0 (0 = auto, 1 = fused, "
                "k > 1 = staged)")
        if self.partition_impl not in (
                "auto", "sort", "pallas", "pallas_interpret"):
            raise ValueError(
                f"unknown partition impl {self.partition_impl!r} (expected "
                "'auto', 'sort', 'pallas', or 'pallas_interpret')")
        if self.sort_impl not in ("auto", "xla", "pallas",
                                  "pallas_interpret"):
            raise ValueError(
                f"unknown sort impl {self.sort_impl!r} (expected "
                "'auto', 'xla', 'pallas', or 'pallas_interpret')")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.fallback not in ("none", "chunked"):
            raise ValueError(f"unknown fallback mode {self.fallback!r}")
        if self.grid_pipeline not in ("off", "on", "auto"):
            raise ValueError(
                f"unknown grid pipeline mode {self.grid_pipeline!r}")
        if self.retry_backoff_s < 0 or self.retry_backoff_max_s < 0:
            raise ValueError("retry backoff delays must be >= 0")
        if self.retry_backoff_mult < 1.0:
            raise ValueError("retry_backoff_mult must be >= 1.0")
        if not 0.0 <= self.retry_jitter <= 1.0:
            raise ValueError("retry_jitter must be in [0, 1]")
        if self.generation not in ("auto", "host", "device"):
            raise ValueError(f"unknown generation mode {self.generation!r}")
        if self.key_range not in ("auto", "narrow", "full"):
            raise ValueError(f"unknown key range mode {self.key_range!r}")
        if self.key_range != "auto" and self.key_bits == 64:
            raise ValueError(
                "key_range selects among 32-bit count disciplines; "
                "key_bits=64 always takes the wide hi/lo path")
        if self.skew_threshold is not None:
            if self.skew_threshold <= 0:
                raise ValueError("skew_threshold must be positive")
            if self.chunk_size:
                raise ValueError(
                    "skew splitting does not compose with the chunked "
                    "out-of-core probe: the split replicates the hot inner "
                    "side onto every device (operators/skew.py), growing "
                    "exactly the resident working set chunking exists to "
                    "bound — for skewed out-of-core joins run the grid join "
                    "(ops/chunked.chunked_join_grid), whose per-pair probes "
                    "need no hot-side replication")
            if self.network_fanout_bits > 5:
                raise ValueError(
                    "skew splitting supports network fanout <= 5 "
                    "(hot set is a uint32 bit mask)")
            if self.window_sizing != "measured":
                raise ValueError(
                    "skew splitting requires window_sizing='measured' "
                    "(hot detection reads the sizing program's histograms)")
        if self.chunk_size is not None and (
                self.chunk_size < 1
                or self.two_level or self.probe_algorithm == "bucket"):
            raise ValueError(
                "chunk_size requires the sort probe (chunking bounds the "
                "probe working set; the bucketized path is already blocked)")
        if self.verify not in ("off", "check", "repair"):
            raise ValueError(f"unknown verify mode {self.verify!r}")
        if self.verify != "off" and self.measure_phases:
            raise ValueError(
                "verify does not compose with measure_phases: the split "
                "driver consumes the shuffle program's outputs positionally "
                "(operators/hash_join._run_split) and cannot carry the "
                "checksum outputs through the phase boundary — use the "
                "fused pipeline (measure_phases=False) for verified runs")

    # --- derived geometry ------------------------------------------------------
    @property
    def sort_probe(self) -> bool:
        """True when the (chunk-free) flat sort-merge probe discipline is
        active — the predicate that selects the 31-bit merge-count packing
        (ops/merge_count.MAX_MERGE_KEY) as the key-range contract."""
        return (not self.two_level and self.probe_algorithm != "bucket"
                and not self.chunk_size)

    @property
    def bucket_path(self) -> bool:
        """True when local processing goes through the second radix pass +
        bucketized probe (two-level discipline)."""
        return self.two_level or self.probe_algorithm == "bucket"

    @property
    def mesh_axes(self):
        """Axis name(s) the pipeline's collectives run over: the flat
        ``mesh_axis`` string, or the ``("dcn", "ici")`` pair when the mesh is
        hierarchical (num_hosts > 1) so the shuffle aggregates cross-host
        traffic (parallel/window.py)."""
        return self.mesh_axis if self.num_hosts == 1 else ("dcn", "ici")

    @property
    def network_partition_count(self) -> int:
        """NETWORK_PARTITIONING_COUNT = 1 << FANOUT (Configuration.h:33)."""
        return 1 << self.network_fanout_bits

    @property
    def local_partition_count(self) -> int:
        """LOCAL_PARTITIONING_COUNT = 1 << FANOUT (Configuration.h:34)."""
        return 1 << self.local_fanout_bits

    @property
    def total_fanout_bits(self) -> int:
        return self.network_fanout_bits + (self.local_fanout_bits if self.two_level else 0)

    @property
    def total_partition_count(self) -> int:
        return 1 << self.total_fanout_bits

    def shuffle_block_capacity(self, local_size: int) -> int:
        """Static per-destination block size for the all_to_all shuffle.

        The reference sizes each rank's RMA window exactly from the global
        histogram (Window.cpp:168-177); XLA needs the shape before the data
        exists, so we take the expected per-destination share with
        ``allocation_factor`` slack, rounded up to a multiple of 8 lanes.
        Overflow is detected at runtime (Window.assert_all_tuples_written).
        """
        n = max(1, self.num_nodes)
        cap = int(math.ceil(local_size / n * self.allocation_factor))
        return max(8, -(-cap // 8) * 8)

    def bucket_capacity(self, total_slots: int, num_buckets: int) -> int:
        """Static per-bucket capacity for the local partitioning pass: expected
        share of ``total_slots`` with ``allocation_factor`` slack (the analog
        of LocalPartitioning's cacheline-padded sub-partition sizing,
        LocalPartitioning.cpp:178-181)."""
        cap = int(math.ceil(total_slots / max(1, num_buckets) * self.allocation_factor))
        return max(8, -(-cap // 8) * 8)

    # --- key/rid packing contract ---------------------------------------------
    @property
    def key_remainder_bits(self) -> int:
        """Key bits that survive compression (partition bits are implied by
        partition membership — NetworkPartitioning.cpp:128-129)."""
        return self.key_bits - self.network_fanout_bits

    @property
    def probe_shift_bits(self) -> int:
        """Bits below the probe-comparison key remainder: the analog of
        ``shiftBits = 5 + 27 (+5)`` in BuildProbe.cpp:55-61 / GPUWrapper.cu:39-41.
        In the SoA layout the rid lives in its own lane, so only fanout bits
        shift out of the key lane."""
        return self.total_fanout_bits

    def bucket_count_for(self, inner_size: int) -> int:
        """N = next power of two >= inner partition size (BuildProbe.cpp:59-61)."""
        return _next_pow2(max(1, inner_size))

    def replace(self, **kw) -> "JoinConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """Knobs of the resident join service (tpu_radix_join/service/).

    Lives beside :class:`JoinConfig` because the pair travels together —
    a session is (how to join) x (how to serve) — but stays a separate
    dataclass: none of these fields changes the compiled program, so they
    must never enter plan-cache or checkpoint fingerprints.
    """

    # --- admission (service/admission.py) --------------------------------
    max_queue_depth: int = 64        # pending queries across all tenants
    tenant_quota: int = 8            # in-flight queries per tenant

    # --- deadlines (service/deadline.py) ---------------------------------
    default_deadline_s: Optional[float] = None   # per-query override wins;
                                                 # None = unlimited

    # --- circuit breaker (service/breaker.py) ----------------------------
    breaker_threshold: int = 3       # consecutive backend failures to trip
    breaker_cooldown_s: float = 30.0  # open -> half-open promotion delay

    # --- outcome retention (service/session.py) --------------------------
    outcomes_keep: int = 512         # recent QueryOutcomes kept in memory;
                                     # the SLO recorder owns the aggregates,
                                     # so a week-long worker must not grow
                                     # this list with every query served

    # --- placed-relation LRU (service/session.py) ------------------------
    place_cache_max: int = 8         # device-resident placed-batch entries;
                                     # the HBM bound on input reuse (was the
                                     # hard-coded _PLACE_CACHE_MAX)

    # --- result cache (service/resultcache.py) ---------------------------
    # Content-fingerprint result cache: a repeated query on unchanged
    # inputs short-circuits before admission.  0 disables (the default —
    # turning whole-result reuse on is an operator decision, not a silent
    # behavior change); entries expire after result_cache_ttl_s (None =
    # no TTL) and invalidate on spec/epoch/config change via the content
    # fingerprint itself.
    result_cache_max: int = 0
    result_cache_ttl_s: Optional[float] = None

    # --- inter-query micro-batching (service/microbatch.py) --------------
    # Bounded window coalescer: small same-shape joins arriving within
    # batch_window_ms fuse into ONE device program (composite-key batched
    # count).  0.0 disables; batch_max_queries bounds one fused batch.
    batch_window_ms: float = 0.0
    batch_max_queries: int = 8

    # --- incremental delta-merge joins (service/resident.py) -------------
    # Explicit HBM budget for device-resident sorted unions kept across
    # queries (O(N+Δ) serving: sort only the per-query delta, merge into
    # the resident state, binary-search probe).  0 disables.
    resident_budget_bytes: int = 0

    def __post_init__(self):
        if self.max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        if self.tenant_quota < 1:
            raise ValueError("tenant_quota must be >= 1")
        if (self.default_deadline_s is not None
                and self.default_deadline_s < 0):
            raise ValueError("default_deadline_s must be >= 0 (or None)")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.breaker_cooldown_s < 0:
            raise ValueError("breaker_cooldown_s must be >= 0")
        if self.outcomes_keep < 1:
            raise ValueError("outcomes_keep must be >= 1")
        if self.place_cache_max < 0:
            raise ValueError("place_cache_max must be >= 0 (0 = no reuse)")
        if self.result_cache_max < 0:
            raise ValueError("result_cache_max must be >= 0 (0 = disabled)")
        if (self.result_cache_ttl_s is not None
                and self.result_cache_ttl_s <= 0):
            raise ValueError("result_cache_ttl_s must be > 0 (or None)")
        if self.batch_window_ms < 0:
            raise ValueError("batch_window_ms must be >= 0 (0 = disabled)")
        if self.batch_max_queries < 2:
            raise ValueError("batch_max_queries must be >= 2 (a batch of "
                             "one is the serial path)")
        if self.resident_budget_bytes < 0:
            raise ValueError(
                "resident_budget_bytes must be >= 0 (0 = disabled)")

    def replace(self, **kw) -> "ServiceConfig":
        return dataclasses.replace(self, **kw)
