"""Explicit device→host readback: the one sanctioned sync point.

The engine's hot paths must not contain *implicit* host syncs —
``np.asarray(device_array)``, ``int(jnp_scalar)``, ``.item()`` — because
each one blocks the dispatch thread mid-pipeline and, worse, hides from
review: an accidental readback reads exactly like a deliberate one.  Two
witnesses now police this:

  * statically, the ``sync-point`` lint rule (analysis/rules_sync.py)
    flags the implicit spellings in the engine/grid hot files;
  * at runtime, ``jax.transfer_guard("disallow")`` (armed by
    ``main.py --transfer-guard`` or the tests' ``transfer_guard``
    fixture) raises on any implicit transfer.

:func:`host_readback` is the escape hatch both accept: it routes through
``jax.device_get`` — an *explicit* transfer, allowed under the guard —
so every surviving sync point is a visible, greppable decision.  The
semantics match ``np.asarray(x)`` for every input the call sites use
(device arrays, numpy arrays, scalars, and lists of either: device_get
maps over pytree leaves and the asarray re-assembles the result).
"""

from __future__ import annotations

import jax
import numpy as np


def host_readback(x) -> np.ndarray:
    """Blocking device→host copy as a numpy array (explicit transfer)."""
    return np.asarray(jax.device_get(x))
