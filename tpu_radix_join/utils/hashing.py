"""Shared 32-bit integer mixing (lowbias32-style xorshift-multiply finalizer).

One definition, two twins (device / numpy, bit-identical), consumed by the
wide-key hi-lane derivation (data/relation.py) and the hot-outer spread
(operators/skew.py) — the constants must never drift apart between callers
or between host and device paths.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_M1 = 0x7FEB352D
_M2 = 0x846CA68B


def mix32(x: jnp.ndarray) -> jnp.ndarray:
    """Bijective uint32 mix (device twin of :func:`mix32_np`)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_M1)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(_M2)
    return x ^ (x >> 16)


def mix32_np(x: np.ndarray) -> np.ndarray:
    """Bijective uint32 mix (numpy twin of :func:`mix32`)."""
    x = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(_M1)
        x = x ^ (x >> np.uint32(15))
        x = x * np.uint32(_M2)
        return x ^ (x >> np.uint32(16))
