"""PID-stamped coordination files for the shared single chip.

bench.py and the out-of-core grid (ops/chunked.chunked_join_grid) must not
time against each other on one device: the bench holds a pause file while
its timed window runs and the grid parks between chunk pairs; the grid
holds a presence file so the bench knows whether a drain wait is needed at
all.  Both files carry the owner's PID, so liveness is exact — a holder
killed hard (no atexit) never wedges the other side, and a legitimately
long-running holder is never declared stale by a clock heuristic.
"""

from __future__ import annotations

import os
from typing import Optional

_ARTIFACTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts")


def bench_pause_file() -> str:
    """The bench's hold file — ONE definition for both sides of the
    handshake (env ``TPU_RJ_PAUSE_FILE`` overrides the canonical path)."""
    return os.environ.get("TPU_RJ_PAUSE_FILE",
                          os.path.join(_ARTIFACTS, "BENCH_RUNNING"))


def grid_presence_file() -> str:
    """The grid's presence file (``+ ".parked"`` while yielded); env
    ``TPU_RJ_GRID_FILE`` overrides the canonical path."""
    return os.environ.get("TPU_RJ_GRID_FILE",
                          os.path.join(_ARTIFACTS, "GRID_RUNNING"))


def write_pid_file(path: str) -> bool:
    """Stamp ``path`` with this process's PID; False if unwritable."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(str(os.getpid()))
        return True
    except OSError:
        return False


def remove_pid_file(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def acquire_pid_file(path: str, timeout_s: float,
                     poll_s: float = 5.0) -> bool:
    """Atomically acquire a PID-stamped hold file.

    ``O_CREAT|O_EXCL`` closes the check-then-write race two concurrent
    acquirers would otherwise hit; a file whose stamped holder is dead is
    broken and re-contested immediately.  True on acquisition; False when a
    LIVE holder still owns the file at the deadline (the caller must then
    proceed without the reservation — never overwrite a live holder's
    stamp, whose atexit would delete the file out from under us)."""
    import time
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
    except OSError:
        return False
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return True
        except FileExistsError:
            if pid_file_alive(path) is False:
                remove_pid_file(path)   # dead holder: break and re-contest
                continue
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        except OSError:
            return False


def pid_file_alive(path: str) -> Optional[bool]:
    """Is the process that stamped ``path`` still alive?

    True/False when the file names a checkable PID; None when the file is
    missing, unreadable, or carries no PID (callers fall back to their own
    policy).  A PID owned by another user counts as alive (EPERM)."""
    try:
        pid = int(open(path).read().strip() or "0")
    except (OSError, ValueError):
        return None
    if pid <= 0:
        return None
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
