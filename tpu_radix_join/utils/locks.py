"""PID-stamped coordination files for the shared single chip.

bench.py and the out-of-core grid (ops/chunked.chunked_join_grid) must not
time against each other on one device: the bench holds a pause file while
its timed window runs and the grid parks between chunk pairs; the grid
holds a presence file so the bench knows whether a drain wait is needed at
all.  Both files carry the owner's PID, so liveness is exact — a holder
killed hard (no atexit) never wedges the other side, and a legitimately
long-running holder is never declared stale by a clock heuristic.
"""

from __future__ import annotations

import os
from typing import Optional

_ARTIFACTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts")


def bench_pause_file() -> str:
    """The bench's hold file — ONE definition for both sides of the
    handshake (env ``TPU_RJ_PAUSE_FILE`` overrides the canonical path)."""
    return os.environ.get("TPU_RJ_PAUSE_FILE",
                          os.path.join(_ARTIFACTS, "BENCH_RUNNING"))


def grid_presence_file() -> str:
    """The grid's presence file (``+ ".parked"`` while yielded); env
    ``TPU_RJ_GRID_FILE`` overrides the canonical path."""
    return os.environ.get("TPU_RJ_GRID_FILE",
                          os.path.join(_ARTIFACTS, "GRID_RUNNING"))


def write_pid_file(path: str) -> bool:
    """Stamp ``path`` with this process's PID; False if unwritable."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(str(os.getpid()))
        return True
    except OSError:
        return False


def remove_pid_file(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def acquire_pid_file(path: str, timeout_s: float,
                     poll_s: float = 5.0) -> str:
    """Atomically acquire a PID-stamped hold file.

    Returns ``"acquired"``, ``"busy"`` (a LIVE holder still owns the file
    at the deadline — never overwritten: its atexit would delete the file
    out from under us), or ``"error"`` (the path is unwritable — distinct
    from busy so callers don't misdiagnose a permissions problem as a
    phantom contender).

    Races closed: ``O_CREAT|O_EXCL`` decides simultaneous creates; a dead
    or PID-less holder's file is broken by an atomic RENAME to a
    contender-private name — exactly one contender gets it — and the
    renamed file is re-verified before discard, so a live file recreated
    in the check window is restored, not destroyed.  A write failure after
    the create unlinks the empty stamp instead of leaving an unbreakable
    PID-less file."""
    import time
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
    except OSError:
        return "error"
    deadline = time.monotonic() + timeout_s
    nones = 0   # consecutive PID-less sightings (transient create window)
    while True:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            try:
                os.write(fd, str(os.getpid()).encode())
            except OSError:
                os.close(fd)
                remove_pid_file(path)
                return "error"
            os.close(fd)
            return "acquired"
        except FileExistsError:
            alive = pid_file_alive(path)
            if alive is True:
                nones = 0
                if time.monotonic() >= deadline:
                    return "busy"
                time.sleep(poll_s)
                continue
            if alive is None:
                # missing (re-contest now) or PID-less: give a holder
                # mid-create two polls before treating the file as broken
                nones += 1
                if not os.path.exists(path):
                    continue
                if nones <= 2:
                    time.sleep(poll_s)
                    continue
            nones = 0
            stale = f"{path}.stale.{os.getpid()}"
            try:
                os.rename(path, stale)
            except OSError:
                continue            # another contender broke it first
            if pid_file_alive(stale) is True:
                # we grabbed a file recreated by a live winner inside the
                # check window: put it back (best effort) and keep waiting
                try:
                    os.rename(stale, path)
                except OSError:
                    remove_pid_file(stale)
                continue
            remove_pid_file(stale)  # confirmed dead/broken; re-contest
        except OSError:
            return "error"


def pid_file_alive(path: str) -> Optional[bool]:
    """Is the process that stamped ``path`` still alive?

    True/False when the file names a checkable PID; None when the file is
    missing, unreadable, or carries no PID (callers fall back to their own
    policy).  A PID owned by another user counts as alive (EPERM)."""
    try:
        pid = int(open(path).read().strip() or "0")
    except (OSError, ValueError):
        return None
    if pid <= 0:
        return None
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
