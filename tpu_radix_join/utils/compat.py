"""jax version-compatibility shims (graceful degradation on older jax).

The codebase targets the current jax API surface (``jax.shard_map``,
``jax.typeof``, ``jax.lax.axis_size``, ``jax.distributed.is_initialized``);
the runtime image may carry an older jax (0.4.x) where those names live
elsewhere or do not exist.  Rather than dying at trace time with
``AttributeError: module 'jax' has no attribute 'shard_map'`` — the failure
mode that took out the whole tier-1 suite on jax 0.4.37 — :func:`install`
fills ONLY the missing attributes with behavior-compatible equivalents:

  * ``jax.shard_map``          -> ``jax.experimental.shard_map.shard_map``
    with ``check_rep=False`` (the old static replication checker has no rule
    for ``while_loop`` and rejects programs the new checker accepts; the
    pipeline's invariants are enforced at runtime anyway — conservation
    flags, not tracer analysis).
  * ``jax.typeof``             -> ``jax.core.get_aval`` (no ``vma``
    attribute, which callers already treat as optional — see
    ops/pallas/merge_scan.out_struct).
  * ``jax.lax.axis_size``      -> axis-env lookup (the static mesh-axis size
    inside shard_map bodies).
  * ``jax.distributed.is_initialized`` -> distributed-client presence probe.

Present attributes are never overwritten, so on a current jax ``install()``
is a no-op.  Called once from ``tpu_radix_join/__init__`` — import order
does not matter because all patched names are resolved at call time.
"""

from __future__ import annotations

import functools

import jax

_installed = False
_legacy = False


def is_legacy() -> bool:
    """True when :func:`install` had to shim ``jax.shard_map`` — the marker
    for an old jax/XLA pair.  Code paths that trip known old-XLA bugs key off
    this (e.g. histograms/assignment_map.py unrolls its LPT scan because the
    bundled XLA's sharding propagation aborts on while-loops feeding sharded
    outputs: ``Check failed: new_num_elements == num_elements() (1 vs. 0)``
    in TileAssignment::Reshape)."""
    return _legacy


def install() -> None:
    """Idempotently fill missing jax API names (never overwrites)."""
    global _installed, _legacy
    if _installed:
        return
    _installed = True

    if not hasattr(jax, "shard_map"):
        _legacy = True
        from jax.experimental.shard_map import shard_map as _shard_map
        jax.shard_map = functools.partial(_shard_map, check_rep=False)

    if not hasattr(jax, "typeof"):
        from jax.core import get_aval as _get_aval
        jax.typeof = _get_aval

    if not hasattr(jax.lax, "axis_size"):
        from jax._src import core as _core

        def _axis_size(axis_name):
            if isinstance(axis_name, (tuple, list)):
                size = 1
                for ax in axis_name:
                    size *= _axis_size(ax)
                return size
            return _core.get_axis_env().axis_size(axis_name)

        jax.lax.axis_size = _axis_size

    if not hasattr(jax.distributed, "is_initialized"):
        def _is_initialized() -> bool:
            try:
                from jax._src import distributed as _dist
                return _dist.global_state.client is not None
            except (ImportError, AttributeError):
                return False

        jax.distributed.is_initialized = _is_initialized
