"""Debug/assert utilities.

Replaces ``utils/Debug.h`` — the JOIN_DEBUG / JOIN_ASSERT printf+exit macros,
compile-time gated by ``JOIN_DEBUG_PRINT`` (Debug.h:16-46).  The runtime gate
here is the ``TPU_RADIX_JOIN_DEBUG`` env var (set to 1 to enable), fixing by
construction the reference's dead flag-name mismatch (``JOIN_MEM_PRINT`` vs
``JOIN_MEMORY_PRINT``, SURVEY.md §5.5).
"""

from __future__ import annotations

import os
import sys

DEBUG = os.environ.get("TPU_RADIX_JOIN_DEBUG", "0") not in ("0", "", "false")


def join_debug(section: str, msg: str) -> None:
    """JOIN_DEBUG analog (Debug.h:16-25)."""
    if DEBUG:
        print(f"[{section}] {msg}", file=sys.stderr)


def join_assert(condition: bool, section: str, msg: str) -> None:
    """JOIN_ASSERT analog (Debug.h:27-44): raises instead of exit(-1) so test
    harnesses can catch it; host-side checks only (device-side invariants are
    returned as bool outputs, see Window.assert_all_tuples_written)."""
    if not condition:
        raise AssertionError(f"[{section}] {msg}")
