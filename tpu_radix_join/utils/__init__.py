from tpu_radix_join.utils.debug import join_assert, join_debug

__all__ = ["join_assert", "join_debug"]
