"""Force the virtual multi-device CPU platform for distributed tests/dry runs.

The reference exercises multi-node behavior with plain oversubscribed
``mpirun`` (SURVEY.md §4 item 5); the JAX analog is N virtual CPU devices via
``--xla_force_host_platform_device_count``.  Two container-specific hazards
make this non-trivial (and are why this lives in one shared helper instead of
per-site env fiddling):

1. sitecustomize imports jax at interpreter start pinned to the live-TPU
   tunnel platform, locking the ``jax_platforms`` config *default* — the env
   var alone is silently ignored, so we must update jax.config directly.
2. ``XLA_FLAGS`` is only read at first backend use; once any backend is
   initialized the flag (and the platform switch) can no longer take effect.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def apply_platform_override() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` env override after import.

    The container's sitecustomize imports jax at interpreter start pinned to
    the live-TPU tunnel, locking the config *default* — the env var alone is
    silently ignored afterwards (module docstring hazard 1).  Entry points
    (CLI, experiments) call this once right after ``import jax`` so
    ``JAX_PLATFORMS=cpu python ...`` behaves the way the env var promises;
    a no-op when unset or when it matches the pinned default."""
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


def force_host_cpu_devices(n: int, respect_existing: bool = False,
                           defer_check: bool = False) -> None:
    """Make ``jax.devices()`` return at least ``n`` virtual CPU devices.

    Must run before any JAX backend use in this process; raises RuntimeError
    with a clear message if a backend already exists and cannot satisfy ``n``.
    Replaces an existing device-count flag so the caller's ``n`` wins, unless
    ``respect_existing`` and the env already requests ``>= n`` devices (so
    e.g. ``XLA_FLAGS=...device_count=16 pytest`` still gets its 16).

    ``defer_check=True`` skips the ``jax.devices()`` validation, which itself
    initializes the backend — required when ``jax.distributed.initialize``
    must still run after this call (multi-process workers), since it refuses
    to run once any backend exists.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    existing = re.search(rf"{_FLAG}=(\d+)", flags)
    if existing and respect_existing and int(existing.group(1)) >= n:
        n = int(existing.group(1))
    if existing:
        flags = re.sub(rf"{_FLAG}=\d+", f"{_FLAG}={n}", flags)
    else:
        flags = f"{flags} {_FLAG}={n}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    try:
        from jax._src import xla_bridge

        already_initialized = bool(xla_bridge._backends)
    except (ImportError, AttributeError):
        already_initialized = False
    jax.config.update("jax_platforms", "cpu")
    if defer_check:
        return
    if len(jax.devices()) < n:
        hint = (
            "a JAX backend was already initialized in this process, so the "
            "platform/device-count override could not take effect; call "
            f"force_host_cpu_devices({n}) before any JAX computation"
            if already_initialized
            else "XLA did not honor the device-count flag"
        )
        raise RuntimeError(
            f"needed {n} virtual CPU devices, got {len(jax.devices())}: {hint}"
        )
