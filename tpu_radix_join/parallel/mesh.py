"""Device mesh helpers.

Replaces the reference's SPMD bootstrap (``MPI_Init`` / ``Comm_size/rank``,
main.cpp:36-48): on TPU the "cluster" is a ``jax.sharding.Mesh`` over the
devices visible to the process (multi-host JAX extends this transparently —
``jax.devices()`` spans hosts, the direct analog of a multi-node MPI world).

For multi-host pods the mesh can be two-dimensional — ``(dcn, ici)`` — so the
shuffle's collectives can be laid out hierarchically: bulk all_to_all hops
ride ICI within each host's slice and only host-aggregated blocks cross DCN
(parallel/window.py hierarchical exchange).  This is the TPU-native analog of
the reference's implicit network hierarchy (MPI ranks over an RDMA fabric,
with foMPI specializing the transport, Window.h:64-68).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh

# Axis argument accepted by the pipeline's collectives: one mesh axis name,
# or the ("dcn", "ici") pair on a hierarchical mesh.  jax.lax collectives
# (psum, all_gather, axis_index) take this union directly — axis_index over a
# tuple is the row-major flat rank, the MPI_Comm_rank analog.
AxisName = Union[str, Tuple[str, ...]]


def device_count() -> int:
    return jax.device_count()


def make_mesh(num_nodes: int | None = None, axis_name: str = "nodes") -> Mesh:
    """A 1-D mesh over the first ``num_nodes`` devices (default: all).

    The join's parallelism is partitioned data parallelism over one axis
    (SURVEY.md §2.3 item 1); higher-dimensional meshes are not needed.
    """
    devs = jax.devices()
    n = num_nodes or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} nodes but only {len(devs)} devices")
    return Mesh(np.asarray(devs[:n]), (axis_name,))


def make_survivor_mesh(lost_nodes: Sequence[int],
                       num_nodes: int | None = None,
                       axis_name: str = "nodes") -> Mesh:
    """A 1-D mesh over the boot mesh's devices MINUS the lost nodes'.

    The elastic-recovery steady state (robustness/recovery.py): after a
    rank loss fences the old mesh, survivors rebuild their collective
    plane from live membership and recompile against it — same axis
    vocabulary, smaller world.  ``lost_nodes`` are node indices into the
    boot mesh's device order (the flat rank every shard_map program
    used).  Raises when nothing survives: an empty mesh is not a mesh.

    Single-process note: on virtual devices this drops the lost node's
    device object from the grid; in a real multi-process job the dead
    process's devices are unreachable and jax itself must be
    re-initialized — there the helper documents the target shape for the
    out-of-band recompute path rather than producing a dispatchable mesh
    (a survivor must never dispatch a collective after a peer death;
    recovery computes host-side).
    """
    devs = jax.devices()
    n = num_nodes or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} nodes but only {len(devs)} devices")
    lost = {int(r) for r in lost_nodes}
    alive = [d for i, d in enumerate(devs[:n]) if i not in lost]
    if not alive:
        raise ValueError(f"all {n} nodes lost — no survivor mesh to build")
    return Mesh(np.asarray(alive), (axis_name,))


def make_elastic_mesh(lost_nodes: Sequence[int],
                      joined_nodes: Sequence[int] = (),
                      num_nodes: int | None = None,
                      axis_name: str = "nodes") -> Mesh:
    """:func:`make_survivor_mesh` extended over a membership that may
    have GROWN: ``joined_nodes`` are node ids the membership view
    admitted beyond (or back into) the boot mesh.  Joined ids inside the
    boot range re-take their original device slot (a readmitted rank);
    ids beyond it map onto the process's spare devices past the boot
    mesh when any exist (the single-process virtual-device simulation),
    and are otherwise dropped from the dispatchable grid — in a real
    multi-process job the newcomer's devices live in its own process, so
    the helper documents the target shape while the out-of-band
    recompute path (robustness/recovery.py) does the actual work
    host-side, same caveat as :func:`make_survivor_mesh`."""
    devs = jax.devices()
    n = num_nodes or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} nodes but only {len(devs)} devices")
    lost = {int(r) for r in lost_nodes}
    joined = sorted({int(r) for r in joined_nodes} - lost)
    grid = [d for i, d in enumerate(devs[:n]) if i not in lost or i in joined]
    spare = list(devs[n:])
    for j in joined:
        if j >= n and spare:
            grid.append(spare.pop(0))
    if not grid:
        raise ValueError(f"all {n} nodes lost — no elastic mesh to build")
    return Mesh(np.asarray(grid), (axis_name,))


def make_hierarchical_mesh(
    num_hosts: int,
    num_nodes: int | None = None,
    axis: Sequence[str] = ("dcn", "ici"),
) -> Mesh:
    """A 2-D ``[num_hosts, per_host]`` mesh whose leading axis crosses DCN.

    In a real multi-process job the device grid comes from
    ``mesh_utils.create_hybrid_device_mesh`` so the leading axis truly follows
    process (= host) boundaries; single-process (tests, virtual CPU devices)
    falls back to reshaping the flat device list, which preserves the
    collective semantics being tested.
    """
    devs = jax.devices()
    n = num_nodes or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} nodes but only {len(devs)} devices")
    if n % num_hosts:
        raise ValueError(f"{n} devices do not divide over {num_hosts} hosts")
    per_host = n // num_hosts
    if jax.process_count() > 1:
        try:
            from jax.experimental import mesh_utils
            grid = mesh_utils.create_hybrid_device_mesh(
                (1, per_host), (num_hosts, 1), devices=devs[:n])
        except ValueError:
            # no slice topology (e.g. multi-process virtual CPU devices):
            # group rows by owning process — valid only when the resulting
            # rows are process-homogeneous and each row is a distinct
            # process, else the "dcn" axis would not cross process
            # boundaries and the misconfiguration must surface
            if num_hosts != jax.process_count():
                raise
            ordered = sorted(devs[:n], key=lambda d: (d.process_index, d.id))
            grid = np.asarray(ordered).reshape(num_hosts, per_host)
            row_procs = [{d.process_index for d in row} for row in grid]
            if (any(len(p) != 1 for p in row_procs)
                    or len(set().union(*row_procs)) != num_hosts):
                raise ValueError(
                    f"cannot build a process-aligned hierarchical mesh from "
                    f"the first {n} of {len(devs)} devices: rows would not "
                    f"each map to one distinct process — use num_nodes "
                    f"spanning all processes' devices")
    else:
        grid = np.asarray(devs[:n]).reshape(num_hosts, per_host)
    return Mesh(grid, tuple(axis))
