"""Device mesh helpers.

Replaces the reference's SPMD bootstrap (``MPI_Init`` / ``Comm_size/rank``,
main.cpp:36-48): on TPU the "cluster" is a ``jax.sharding.Mesh`` over the
devices visible to the process (multi-host JAX extends this transparently —
``jax.devices()`` spans hosts, the direct analog of a multi-node MPI world).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return jax.device_count()


def make_mesh(num_nodes: int | None = None, axis_name: str = "nodes") -> Mesh:
    """A 1-D mesh over the first ``num_nodes`` devices (default: all).

    The join's parallelism is partitioned data parallelism over one axis
    (SURVEY.md §2.3 item 1); higher-dimensional meshes are not needed.
    """
    devs = jax.devices()
    n = num_nodes or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} nodes but only {len(devs)} devices")
    return Mesh(np.asarray(devs[:n]), (axis_name,))
