"""Network partitioning: route every tuple to its partition's owner node.

Replaces ``tasks/NetworkPartitioning.{h,cpp}`` — the all-to-all shuffle
producer.  The reference's per-tuple hot loop (hash, compress, SWWC cacheline
append, AVX stream, 64KB ``MPI_Put`` with double buffering,
NetworkPartitioning.cpp:116-173) becomes three vectorized steps:

  1. partition id per tuple (radix bits, LocalHistogram.cpp:20);
  2. destination node per tuple via the AssignmentMap
     (``window->write``'s target resolution, Window.cpp:110);
  3. one dense block scatter + ``all_to_all`` (parallel/window.py).

Wire format parity: the reference ships 8B CompressedTuples; with 32-bit keys
our two uint32 lanes (full key + rid) are the same 8B/tuple, and keeping the
full key lets the receiver recompute partition ids instead of shipping them
(compression to key remainders happens at the probe boundary instead —
tuples.compress).  Communication/computation overlap (SURVEY.md §2.3 item 6)
is XLA's job: the scatter and the collective are in one program and XLA/ICI
pipeline them.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from tpu_radix_join.data.tuples import TupleBatch, partition_ids, valid_mask
from tpu_radix_join.parallel.window import Window, ExchangeResult


class NetworkPartitionResult(NamedTuple):
    batch: TupleBatch        # received tuples, [N * C] lanes, sentinel-padded
    valid: jnp.ndarray       # bool [N * C]
    pid: jnp.ndarray         # uint32 [N * C] — recomputed partition ids
    recv_counts: jnp.ndarray # uint32 [N]
    send_overflow: jnp.ndarray


def network_partition(
    batch: TupleBatch,
    fanout_bits: int,
    assignment: jnp.ndarray,
    window: Window,
    valid: jnp.ndarray | None = None,
    exclude: jnp.ndarray | None = None,
    override: Tuple[jnp.ndarray, jnp.ndarray] | None = None,
) -> NetworkPartitionResult:
    """Runs inside shard_map over the mesh axis.

    ``exclude``: bool [n] — tuples withheld from the shuffle (the skew split
    pulls hot inner tuples out for replication instead, operators/skew.py).
    ``override``: (mask, dest) — tuples whose destination ignores the
    assignment map (hot outer tuples spread round-robin).
    """
    pid = partition_ids(batch, fanout_bits)
    dest = assignment[pid]
    if override is not None:
        dest = jnp.where(override[0], override[1], dest)
    if exclude is not None:
        valid = ~exclude if valid is None else (valid & ~exclude)
    # pid rides along for the packed wire codec: the bit-packed format drops
    # the fanout bits and the receiver restores them from the header's
    # per-partition counts (a no-op for codec="off" windows)
    res: ExchangeResult = window.exchange(batch, dest, valid=valid, pid=pid)
    recv_valid = valid_mask(res.batch, window.side)
    recv_pid = partition_ids(res.batch, fanout_bits)
    return NetworkPartitionResult(
        batch=res.batch, valid=recv_valid, pid=recv_pid,
        recv_counts=res.recv_counts, send_overflow=res.send_overflow,
    )


def receive_checksums(res: NetworkPartitionResult, num_partitions: int,
                      axis) -> jnp.ndarray:
    """Mesh-global ``[rows, P]`` integrity fingerprint of what the exchange
    delivered (robustness/verify.py), traced inside the same shard_map as
    the exchange itself.  Compared on the host against the pre-exchange
    fingerprint of what was sent: equal rows == the shuffle conserved every
    tuple and every key bit."""
    from tpu_radix_join.robustness import verify as _verify
    return _verify.global_partition_checksums(
        res.batch.key, res.pid, num_partitions, axis,
        valid=res.valid, key_hi=res.batch.key_hi)
