"""Multi-process (multi-host) bootstrap: the ``MPI_Init`` analog.

The reference becomes a distributed job by being launched under ``mpirun``
(main.cpp:36-48: ``MPI_Init`` + ``Comm_size/rank`` discovery).  A multi-host
JAX job is launched as one process per host with a shared coordinator; after
``initialize()`` every process sees the whole pod through ``jax.devices()``
and the same shard_map programs run unchanged — the mesh is the cluster.

The join pipeline needs nothing else: collectives are compiled against mesh
axes, and ``make_hierarchical_mesh`` (parallel/mesh.py) lays the ``dcn`` axis
along process boundaries so the shuffle's bulk hops ride ICI.

This module is environment-driven and single-host-safe: with no cluster
variables set it is a no-op, so every entry point can call it unconditionally
(the way every reference binary calls ``MPI_Init``).

Resilience (the hardening ``MPI_Init`` never had): the coordinator connect
runs under a ``robustness.retry.RetryPolicy`` — a worker that races ahead of
a slow coordinator backs off and retries instead of dying, and a worker that
can never connect fails with the ``coordinator_timeout`` failure class after
a bounded schedule rather than hanging the job.  Knobs come from the
environment (``TPU_RJ_COORD_ATTEMPTS``, ``TPU_RJ_COORD_BACKOFF_S``,
``TPU_RJ_COORD_TIMEOUT_S``) or an explicit ``retry_policy``.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

import jax

from tpu_radix_join.robustness import faults as _faults
from tpu_radix_join.robustness.retry import (COORDINATOR_TIMEOUT,
                                             RetriesExhausted, RetryPolicy,
                                             execute)

_initialized = False


class CoordinatorTimeout(ConnectionError):
    """Could not reach the distributed coordinator within policy.

    Carries the retry history the terminal re-raise used to lose:
    ``attempts`` (connect attempts made) and ``backoff_s`` (cumulative
    seconds slept between them) — rendered into the message and picked
    up by the forensics bundle (``bundle_extra``), so a post-mortem
    distinguishes "died on the first dial" from "backed off for a minute
    against a coordinator that never answered"."""

    failure_class = COORDINATOR_TIMEOUT

    def __init__(self, msg: str, attempts: int = 1, backoff_s: float = 0.0):
        super().__init__(msg)
        self.attempts = attempts
        self.backoff_s = backoff_s
        #: merged into the post-mortem bundle's ``extra`` by the failure
        #: path (main._emit_failure_bundle)
        self.bundle_extra = {"coordinator_attempts": attempts,
                             "coordinator_backoff_s": round(backoff_s, 3)}


def _default_policy() -> RetryPolicy:
    return RetryPolicy(
        max_attempts=int(os.environ.get("TPU_RJ_COORD_ATTEMPTS", "3")),
        base_delay_s=float(os.environ.get("TPU_RJ_COORD_BACKOFF_S", "1.0")),
        multiplier=2.0,
        max_delay_s=30.0,
        jitter=0.1,
        # per-process seed: ranks de-synchronize their retry storms
        seed=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               retry_policy: Optional[RetryPolicy] = None,
               connect_timeout_s: Optional[float] = None,
               measurements=None,
               _sleep: Optional[Callable[[float], None]] = None) -> bool:
    """Join the multi-process world if one is configured; returns True when
    running distributed.

    Joining is strictly opt-in: it happens only with an explicit
    ``coordinator_address`` argument or ``JAX_COORDINATOR_ADDRESS`` in the
    environment (plus ``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` — the moral
    equivalent of mpirun's rank environment).  Cloud TPU pod launchers that
    rely on jax's own pod auto-detection should call
    ``jax.distributed.initialize()`` directly before importing this package;
    auto-detection is deliberately not replicated here because single-chip
    tunnel environments carry pod-like variables.

    ``connect_timeout_s`` bounds each connect attempt (forwarded to
    ``jax.distributed.initialize(initialization_timeout=...)`` where the
    installed jax supports it, default
    ``TPU_RJ_COORD_TIMEOUT_S``); retryable connect failures (timeout /
    connection errors / the injectable ``multihost.coordinator_connect``
    fault) back off per ``retry_policy`` and terminally raise
    :class:`CoordinatorTimeout`.  ``_sleep`` is test-injectable.
    """
    global _initialized
    if _initialized or jax.distributed.is_initialized():
        return jax.process_count() > 1
    env = os.environ
    coordinator_address = coordinator_address or env.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in env:
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in env:
        process_id = int(env["JAX_PROCESS_ID"])
    if coordinator_address is None:
        return False   # single-process run; nothing to join
    if connect_timeout_s is None and "TPU_RJ_COORD_TIMEOUT_S" in env:
        connect_timeout_s = float(env["TPU_RJ_COORD_TIMEOUT_S"])

    from tpu_radix_join.utils import compat
    # platform read from config/env, NOT jax.default_backend(): probing the
    # backend here would initialize it, and distributed.initialize refuses
    # to run once any backend exists
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or env.get("JAX_PLATFORMS") or "")
    if compat.is_legacy() and "cpu" in platforms:
        # legacy jaxlib's default CPU client rejects multi-process
        # computations ("Multiprocess computations aren't implemented on
        # the CPU backend"); its gloo collectives implementation handles
        # them — current jax selects this automatically
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass

    kwargs = dict(coordinator_address=coordinator_address,
                  num_processes=num_processes, process_id=process_id)
    if connect_timeout_s is not None:
        kwargs["initialization_timeout"] = int(connect_timeout_s)

    def connect():
        _faults.check(_faults.COORD_CONNECT, measurements)
        try:
            jax.distributed.initialize(**kwargs)
        except TypeError:
            # older jax.distributed.initialize without initialization_timeout
            kwargs.pop("initialization_timeout", None)
            jax.distributed.initialize(**kwargs)

    policy = retry_policy or _default_policy()
    try:
        execute(connect, policy,
                retryable=(ConnectionError, TimeoutError,
                           _faults.InjectedFault, RuntimeError),
                sleep=_sleep or time.sleep,
                measurements=measurements,
                label="coordinator_connect")
    except RetriesExhausted as e:
        # the slept schedule is one delay per attempt pair actually made
        backoff_s = sum(policy.schedule()[:max(0, e.attempts - 1)])
        raise CoordinatorTimeout(
            f"could not reach coordinator {coordinator_address} after "
            f"{e.attempts} attempt(s) ({backoff_s:.1f}s cumulative "
            f"backoff): {e.last_error!r}",
            attempts=e.attempts, backoff_s=backoff_s) from e
    _initialized = True
    return jax.process_count() > 1


def process_info() -> tuple[int, int]:
    """(process_id, process_count) — the ``Comm_rank``/``Comm_size`` pair."""
    return jax.process_index(), jax.process_count()
