"""Multi-process (multi-host) bootstrap: the ``MPI_Init`` analog.

The reference becomes a distributed job by being launched under ``mpirun``
(main.cpp:36-48: ``MPI_Init`` + ``Comm_size/rank`` discovery).  A multi-host
JAX job is launched as one process per host with a shared coordinator; after
``initialize()`` every process sees the whole pod through ``jax.devices()``
and the same shard_map programs run unchanged — the mesh is the cluster.

The join pipeline needs nothing else: collectives are compiled against mesh
axes, and ``make_hierarchical_mesh`` (parallel/mesh.py) lays the ``dcn`` axis
along process boundaries so the shuffle's bulk hops ride ICI.

This module is environment-driven and single-host-safe: with no cluster
variables set it is a no-op, so every entry point can call it unconditionally
(the way every reference binary calls ``MPI_Init``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join the multi-process world if one is configured; returns True when
    running distributed.

    Joining is strictly opt-in: it happens only with an explicit
    ``coordinator_address`` argument or ``JAX_COORDINATOR_ADDRESS`` in the
    environment (plus ``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` — the moral
    equivalent of mpirun's rank environment).  Cloud TPU pod launchers that
    rely on jax's own pod auto-detection should call
    ``jax.distributed.initialize()`` directly before importing this package;
    auto-detection is deliberately not replicated here because single-chip
    tunnel environments carry pod-like variables.
    """
    global _initialized
    if _initialized or jax.distributed.is_initialized():
        return jax.process_count() > 1
    env = os.environ
    coordinator_address = coordinator_address or env.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in env:
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in env:
        process_id = int(env["JAX_PROCESS_ID"])
    if coordinator_address is None:
        return False   # single-process run; nothing to join
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return jax.process_count() > 1


def process_info() -> tuple[int, int]:
    """(process_id, process_count) — the ``Comm_rank``/``Comm_size`` pair."""
    return jax.process_index(), jax.process_count()
