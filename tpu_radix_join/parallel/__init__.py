from tpu_radix_join.parallel.mesh import (
    device_count,
    make_hierarchical_mesh,
    make_mesh,
)
from tpu_radix_join.parallel.window import Window
from tpu_radix_join.parallel.network_partitioning import network_partition
from tpu_radix_join.parallel.distribute import distribute
from tpu_radix_join.parallel.multihost import initialize as initialize_multihost

__all__ = ["make_mesh", "make_hierarchical_mesh", "device_count",
           "Window", "network_partition", "distribute",
           "initialize_multihost"]
