from tpu_radix_join.parallel.mesh import make_mesh, device_count
from tpu_radix_join.parallel.window import Window
from tpu_radix_join.parallel.network_partitioning import network_partition
from tpu_radix_join.parallel.distribute import distribute

__all__ = ["make_mesh", "device_count", "Window", "network_partition",
           "distribute"]
