"""The communication data plane: fixed-capacity blocks + ICI all_to_all.

Replaces ``data/Window.{h,cpp}`` — the MPI one-sided RMA window that backs the
reference's shuffle (``MPI_Alloc_mem``/``Win_create`` Window.cpp:35-46, epoch
``Win_lock_all/unlock_all`` :65-84, ``MPI_Put`` at OffsetMap-computed offsets
:86-144, conservation check ``assertAllTuplesWritten`` :180-191).

TPU-native design (SURVEY.md §7.2): instead of exactly-sized windows and
one-sided Puts, every node owns a statically-shaped [N, C] block buffer per
relation; senders scatter their tuples into per-destination blocks
(ops/radix.scatter_to_blocks) and one dense ``jax.lax.all_to_all`` over the
ICI mesh axis delivers block j of every sender to node j.  Padding slots carry
side sentinels; per-sender valid counts ride along in a second (tiny)
all_to_all — the moral equivalent of OffsetMap's exactly-written guarantee.
Epochs/barriers are implicit in XLA program order.

Two orthogonal levers reshape the wire (ISSUE 7):

* ``mode="staged:<k>"`` slices the [N, C] block buffer into k column groups
  exchanged by a *sequence* of smaller collectives chained with
  ``optimization_barrier`` — live exchange memory drops to ~1/k of the fused
  peak (the portable-redistribution decomposition of arXiv 2112.01075) while
  the received ordering stays bit-identical to the fused route.
* ``codec="pack"`` bit-packs tuples to their measured key/rid bounds before
  the collective (data/tuples.pack_blocks) and unpacks exactly on receipt;
  the packed block's header region carries the per-partition valid counts,
  so the separate count collective disappears.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import (WireSpec, make_wire_spec, pack_blocks,
                                        unpack_blocks)
from tpu_radix_join.ops.radix import (scatter_to_blocks,
                                      scatter_to_blocks_grouped)
from tpu_radix_join.parallel.mesh import AxisName


def parse_exchange_mode(mode, block: int) -> int:
    """Resolve an exchange mode to a stage count k >= 1.

    ``"fused"``/1 = one collective; ``"staged:<k>"``/k = k column-group
    collectives; ``"auto"`` stages 4-ways once the block is large enough
    that the ~1/k live-memory bound matters (>= 4096 slots per block —
    below that the whole buffer is smaller than the staging bookkeeping
    is worth)."""
    if isinstance(mode, int):
        k = mode
    elif mode == "fused":
        k = 1
    elif mode == "auto":
        k = 4 if block >= 4096 else 1
    elif isinstance(mode, str) and mode.startswith("staged:"):
        try:
            k = int(mode.split(":", 1)[1])
        except ValueError:
            raise ValueError(
                f"exchange mode {mode!r}: the stage count after 'staged:' "
                f"must be an integer") from None
    else:
        raise ValueError(
            f"exchange mode must be 'fused', 'staged:<k>', 'auto', or an "
            f"int stage count, got {mode!r}")
    if k < 1:
        raise ValueError(f"exchange stage count must be >= 1, got {k}")
    return min(k, block) if block else k


def block_all_to_all(x: jnp.ndarray, num_nodes: int, block: int,
                     axis_name: AxisName, mode="fused") -> jnp.ndarray:
    """Dense block exchange: slice ``x``'s leading [num_nodes * block] axis
    into per-destination blocks and deliver block j to node j.  The single
    collective that replaces the reference's windowed ``MPI_Put`` schedule
    (Window.cpp:86-144) and pairwise ``MPI_Send/Recv`` exchange
    (Relation.cpp:104-136).  Runs inside shard_map over ``axis_name``; a
    ``(dcn, ici)`` axis pair selects the hierarchical route.

    ``mode`` ("fused" | "staged:<k>" | "auto" | int) splits the block
    dimension into k column groups exchanged sequentially (chained with
    ``optimization_barrier`` so XLA cannot re-fuse them): peak live exchange
    memory drops to ~1/k while the received ordering stays identical to the
    fused route — group i of sender s lands in the same rows either way,
    and concatenating the groups along the block axis restores the exact
    fused layout."""
    if x.shape[0] != num_nodes * block:
        raise ValueError(
            f"block_all_to_all: leading axis of {x.shape[0]} must equal "
            f"num_nodes * block = {num_nodes} * {block} = "
            f"{num_nodes * block} (one fixed-capacity block per "
            f"destination)")
    stages = parse_exchange_mode(mode, block)
    if stages == 1:
        return _one_exchange(x, num_nodes, block, axis_name)
    rest = x.shape[1:]
    v = x.reshape((num_nodes, block) + rest)
    base, extra = divmod(block, stages)
    sizes = [base + (1 if i < extra else 0) for i in range(stages)]
    outs = []
    prev = None
    off = 0
    for g in sizes:
        part = v[:, off:off + g]
        if prev is not None:
            # tie group i+1's send to group i's arrival: the collectives
            # run as a sequence, so only ~1/k of the buffer is in flight
            part, _ = jax.lax.optimization_barrier((part, prev))
        out = _one_exchange(
            part.reshape((num_nodes * g,) + rest), num_nodes, g, axis_name
        ).reshape((num_nodes, g) + rest)
        outs.append(out)
        prev = out
        off += g
    return jnp.concatenate(outs, axis=1).reshape(
        (num_nodes * block,) + rest)


def _one_exchange(x: jnp.ndarray, num_nodes: int, block: int,
                  axis_name: AxisName) -> jnp.ndarray:
    """One fused block exchange (flat or hierarchical by axis type)."""
    if not isinstance(axis_name, str):
        dcn_axis, ici_axis = axis_name
        return hierarchical_block_all_to_all(x, num_nodes, block,
                                             dcn_axis, ici_axis)
    return jax.lax.all_to_all(
        x.reshape((num_nodes, block) + x.shape[1:]), axis_name,
        split_axis=0, concat_axis=0, tiled=False,
    ).reshape((num_nodes * block,) + x.shape[1:])


def hierarchical_block_all_to_all(x: jnp.ndarray, num_nodes: int, block: int,
                                  dcn_axis: str, ici_axis: str) -> jnp.ndarray:
    """Two-stage exchange over a ``[num_hosts, per_host]`` mesh.

    Destination flat id ``d = host(d) * per_host + local(d)``.  Stage 1 rides
    ICI: within each host, blocks are exchanged so the device at local index
    ``l`` aggregates everything (from all its host's devices) destined for
    *any* host's local-``l`` device.  Stage 2 crosses DCN once, between
    same-local-index peers, shipping per-host-aggregated slabs — N² small
    messages become H² aggregated ones, which is the point of routing the
    bulk hops over ICI (SURVEY.md §2.4 TPU mapping; the reference leans on
    foMPI/DMAPP for the same reason on Cray fabrics, Window.h:64-68).

    Result ordering matches the flat exchange: received blocks are stacked by
    source flat id (source-host major), so callers cannot tell the routes
    apart (tested against ``block_all_to_all`` on a flat mesh).
    """
    num_hosts = jax.lax.axis_size(dcn_axis)
    per_host = jax.lax.axis_size(ici_axis)
    if num_hosts * per_host != num_nodes:
        raise ValueError(
            f"hierarchical exchange: mesh axes ({dcn_axis!r}={num_hosts}) x "
            f"({ici_axis!r}={per_host}) = {num_hosts * per_host} devices, "
            f"but num_nodes={num_nodes} — the (dcn, ici) mesh must factor "
            f"the node count exactly")
    v = x.reshape((num_hosts, per_host, block) + x.shape[1:])
    # Stage 1 (ICI): deliver column l of every destination host to local peer l.
    v = jax.lax.all_to_all(v, ici_axis, split_axis=1, concat_axis=1,
                           tiled=False)          # [H_dest, L_src, block]
    # Stage 2 (DCN): deliver row h (aggregated over the host) to host peer h.
    v = jax.lax.all_to_all(v, dcn_axis, split_axis=0, concat_axis=0,
                           tiled=False)          # [H_src, L_src, block]
    return v.reshape((num_nodes * block,) + x.shape[1:])


class ExchangeResult(NamedTuple):
    batch: object            # received batch, arrays shaped [N * C]
    recv_counts: jnp.ndarray  # uint32 [N] — valid tuples from each sender
    send_overflow: jnp.ndarray  # uint32 — local tuples dropped for lack of capacity


class Window:
    """Per-relation shuffle plane bound to a mesh axis.

    ``capacity`` is the static per-(sender, destination) block size — the
    analog of ``computeWindowSize`` (Window.cpp:168-177) except sized ahead of
    the data with ``allocation_factor`` slack (overflow is reported, never
    silently dropped from the accounting).

    ``codec="pack"`` + a :class:`~tpu_radix_join.data.tuples.WireSpec`
    switches the wire to the bounds-aware bit-packed format: tuples travel at
    ``spec.tuple_bits`` bits each and the packed header replaces the count
    side channel (one collective per exchange instead of lanes + counts).
    ``mode`` is the staged-exchange knob forwarded to every collective this
    window dispatches.
    """

    def __init__(self, num_nodes: int, capacity: int, axis_name: AxisName,
                 side: str, codec: str = "off", mode="fused",
                 fanout_bits: int = 0,
                 key_bound: Optional[int] = None,
                 rid_bound: Optional[int] = None,
                 partition_impl: Optional[str] = None,
                 epoch: int = 0):
        if codec not in ("off", "pack"):
            raise ValueError(
                f"window codec must be 'off' or 'pack', got {codec!r} "
                f"('auto' must be resolved by the caller)")
        self.num_nodes = num_nodes
        self.capacity = capacity
        self.axis_name = axis_name
        self.side = side
        self.codec = codec
        self.mode = mode
        self.fanout_bits = fanout_bits
        self.key_bound = key_bound
        self.rid_bound = rid_bound
        self.partition_impl = partition_impl
        #: membership-epoch stamp (robustness/membership.py): the mesh
        #: shape this window's collectives were laid out against.  A
        #: window is mesh-shape-specific — after a rank loss bumps the
        #: epoch, dispatching it would address a dead peer, so callers
        #: guard dispatch with :meth:`fence`.
        self.epoch = epoch

    def fence(self, view) -> None:
        """Host-side dispatch guard: raise ``StaleEpoch`` (via
        ``view.fence``, robustness/membership.MembershipView) when the
        membership epoch moved past the one this window was built at —
        a stale exchange dies loudly instead of deadlocking against a
        peer that no longer exists.  No-op when ``view`` is None."""
        if view is not None:
            view.fence(self.epoch)

    def wire_spec(self, wide: bool) -> WireSpec:
        """The packed-wire geometry for this window's bounds (static)."""
        return make_wire_spec(self.capacity, self.fanout_bits, wide=wide,
                              key_bound=self.key_bound,
                              rid_bound=self.rid_bound)

    def exchange(self, batch, dest: jnp.ndarray,
                 valid: jnp.ndarray | None = None,
                 pid: jnp.ndarray | None = None) -> ExchangeResult:
        """Scatter into destination blocks and all_to_all them.

        ``batch``: TupleBatch/CompressedBatch with [n] lanes; ``dest``: uint32
        [n] destination node per tuple (= assignment[pid], Window.cpp:110).
        ``pid``: the tuple partition ids — required by the packed codec
        (the dropped key bits are reconstructed from partition membership).
        Runs inside shard_map over ``axis_name``.
        """
        n, c = self.num_nodes, self.capacity
        if self.codec == "pack":
            if pid is None:
                raise ValueError(
                    "codec='pack' needs the per-tuple partition ids: the "
                    "wire drops the fanout bits and restores them from "
                    "partition membership — pass pid= to exchange()")
            spec = self.wire_spec(wide=batch[2] is not None)
            blocks, counts, group_counts, overflow = scatter_to_blocks_grouped(
                batch, dest, pid, n, spec.num_sub, c, self.side, valid=valid,
                impl=self.partition_impl)
            words = pack_blocks(spec, blocks, group_counts)
            recv_words = block_all_to_all(words, n, spec.block_words,
                                          self.axis_name, mode=self.mode)
            recv_batch, recv_counts = unpack_blocks(spec, recv_words,
                                                    self.side)
            return ExchangeResult(recv_batch, recv_counts, overflow)
        blocks, counts, overflow = scatter_to_blocks(
            batch, dest, n, c, self.side, valid=valid,
            impl=self.partition_impl)

        received = jax.tree.map(
            lambda x: block_all_to_all(x, n, c, self.axis_name,
                                       mode=self.mode), blocks)
        sent_counts = jnp.minimum(counts, jnp.uint32(c))
        recv_counts = block_all_to_all(sent_counts, n, 1, self.axis_name)
        return ExchangeResult(received, recv_counts, overflow)

    def diagnostics(
        self, result: ExchangeResult, global_hist: jnp.ndarray,
        assignment: jnp.ndarray,
    ):
        """(overflow_tuples, conservation_bad) — the two failure modes of the
        shuffle, separated so callers can tell "blocks too small" (retryable
        with bigger capacity) from "tuples misrouted" (a real bug).

        ``overflow_tuples``: psum of tuples senders dropped for lack of block
        capacity.  ``conservation_bad``: True iff the receive total differs
        from the global histogram over this node's assigned partitions
        (Window.cpp:180-191) *beyond what the overflow explains* — when
        tuples overflowed, the exact equality is unevaluable, so it is only
        asserted when overflow is zero."""
        me = jax.lax.axis_index(self.axis_name).astype(jnp.uint32)
        expected = jnp.sum(jnp.where(assignment == me, global_hist, 0))
        lost = jax.lax.psum(result.send_overflow, self.axis_name)
        conserve_bad = (jnp.sum(result.recv_counts) != expected) & (lost == 0)
        return lost, conserve_bad

    def assert_all_tuples_written(
        self, result: ExchangeResult, global_hist: jnp.ndarray,
        assignment: jnp.ndarray,
    ) -> jnp.ndarray:
        """Combined invariant (conservation AND zero overflow) — the exact
        contract of the reference's assert (SURVEY.md §4.3)."""
        lost, bad = self.diagnostics(result, global_hist, assignment)
        return (lost == 0) & ~bad
