"""The communication data plane: fixed-capacity blocks + ICI all_to_all.

Replaces ``data/Window.{h,cpp}`` — the MPI one-sided RMA window that backs the
reference's shuffle (``MPI_Alloc_mem``/``Win_create`` Window.cpp:35-46, epoch
``Win_lock_all/unlock_all`` :65-84, ``MPI_Put`` at OffsetMap-computed offsets
:86-144, conservation check ``assertAllTuplesWritten`` :180-191).

TPU-native design (SURVEY.md §7.2): instead of exactly-sized windows and
one-sided Puts, every node owns a statically-shaped [N, C] block buffer per
relation; senders scatter their tuples into per-destination blocks
(ops/radix.scatter_to_blocks) and one dense ``jax.lax.all_to_all`` over the
ICI mesh axis delivers block j of every sender to node j.  Padding slots carry
side sentinels; per-sender valid counts ride along in a second (tiny)
all_to_all — the moral equivalent of OffsetMap's exactly-written guarantee.
Epochs/barriers are implicit in XLA program order.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from tpu_radix_join.ops.radix import scatter_to_blocks


def block_all_to_all(x: jnp.ndarray, num_nodes: int, block: int,
                     axis_name: str) -> jnp.ndarray:
    """Dense block exchange: slice ``x``'s leading [num_nodes * block] axis
    into per-destination blocks and deliver block j to node j.  The single
    collective that replaces the reference's windowed ``MPI_Put`` schedule
    (Window.cpp:86-144) and pairwise ``MPI_Send/Recv`` exchange
    (Relation.cpp:104-136).  Runs inside shard_map over ``axis_name``."""
    return jax.lax.all_to_all(
        x.reshape((num_nodes, block) + x.shape[1:]), axis_name,
        split_axis=0, concat_axis=0, tiled=False,
    ).reshape((num_nodes * block,) + x.shape[1:])


class ExchangeResult(NamedTuple):
    batch: object            # received batch, arrays shaped [N * C]
    recv_counts: jnp.ndarray  # uint32 [N] — valid tuples from each sender
    send_overflow: jnp.ndarray  # uint32 — local tuples dropped for lack of capacity


class Window:
    """Per-relation shuffle plane bound to a mesh axis.

    ``capacity`` is the static per-(sender, destination) block size — the
    analog of ``computeWindowSize`` (Window.cpp:168-177) except sized ahead of
    the data with ``allocation_factor`` slack (overflow is reported, never
    silently dropped from the accounting).
    """

    def __init__(self, num_nodes: int, capacity: int, axis_name: str, side: str):
        self.num_nodes = num_nodes
        self.capacity = capacity
        self.axis_name = axis_name
        self.side = side

    def exchange(self, batch, dest: jnp.ndarray,
                 valid: jnp.ndarray | None = None) -> ExchangeResult:
        """Scatter into destination blocks and all_to_all them.

        ``batch``: TupleBatch/CompressedBatch with [n] lanes; ``dest``: uint32
        [n] destination node per tuple (= assignment[pid], Window.cpp:110).
        Runs inside shard_map over ``axis_name``.
        """
        n, c = self.num_nodes, self.capacity
        blocks, counts, overflow = scatter_to_blocks(
            batch, dest, n, c, self.side, valid=valid)

        received = jax.tree.map(
            lambda x: block_all_to_all(x, n, c, self.axis_name), blocks)
        sent_counts = jnp.minimum(counts, jnp.uint32(c))
        recv_counts = jax.lax.all_to_all(
            sent_counts.reshape(n, 1), self.axis_name, 0, 0).reshape(n)
        return ExchangeResult(received, recv_counts, overflow)

    def assert_all_tuples_written(
        self, result: ExchangeResult, global_hist: jnp.ndarray,
        assignment: jnp.ndarray,
    ) -> jnp.ndarray:
        """Conservation invariant (Window.cpp:180-191 / SURVEY.md §4.3): the
        tuples received must equal the global histogram summed over this
        node's assigned partitions, and nothing may have overflowed.
        Returns a bool scalar (all good)."""
        me = jax.lax.axis_index(self.axis_name).astype(jnp.uint32)
        expected = jnp.sum(jnp.where(assignment == me, global_hist, 0))
        got = jnp.sum(result.recv_counts)
        no_overflow = jax.lax.psum(result.send_overflow, self.axis_name) == 0
        return (got == expected) & no_overflow
