"""Pre-join redistribution: the ``Relation::distribute`` analog.

The reference's pre-shuffle (``data/Relation.cpp:99-141``) pairwise-exchanges
equal-size contiguous sections over ``MPI_Send/Recv`` — rank ``n`` swaps the
section selected by ``(n + i) % N`` with every peer ``i`` — so each rank ends
up holding a random slice of the global key space instead of its own dense
generation range, then reshuffles locally (``Relation.cpp:139``).

TPU-native design: the N² pairwise Send/Recv schedule collapses into ONE dense
``jax.lax.all_to_all`` over the mesh axis (block ``j`` of every sender lands on
node ``j``), and the local reshuffle is a key-value sort on a per-tuple
splitmix hash — no network round trips, no rank-ordered deadlock discipline
(``Relation.cpp:104-136``), and the exchange rides ICI.

The seeded-generator relations in ``data/relation.py`` are *already* globally
shuffled, so the join pipeline never needs this op; it exists for workloads
whose shards arrive with locality (e.g. range-partitioned inputs) and as the
capability-parity counterpart of the reference's mandatory pre-step
(``main.cpp:101-104``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.sorting import sort_kv_unstable
from tpu_radix_join.parallel.window import block_all_to_all


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """Vectorized 32-bit finalizer (murmur3-style) for shuffle keys."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def distribute(batch: TupleBatch, num_nodes: int, axis_name: str,
               seed: int = 0, mode="fused") -> TupleBatch:
    """Redistribute so every node holds a uniform slice of the global data.

    Runs inside ``shard_map`` over ``axis_name``.  The local shard is cut into
    ``num_nodes`` equal blocks; block ``j`` travels to node ``j``
    (``all_to_all``), then the received tuples are locally shuffled by a
    seeded hash — together the exact effect of the reference's section
    exchange + ``shuffle`` (``Relation.cpp:99-141``).

    ``mode`` is the staged-exchange knob ("fused" | "staged:<k>" | "auto",
    parallel/window.block_all_to_all): redistribution moves the entire
    relation at once, so it benefits first from bounding live exchange
    memory to ~1/k.  (The bit-pack codec does not apply here — there is no
    partition structure yet to imply key bits from.)

    The local size must divide by ``num_nodes`` (the reference has the same
    constraint implicitly: equal section sizes, ``Relation.cpp:106``).
    """
    n = batch.size
    if n % num_nodes != 0:
        raise ValueError(f"local size {n} must divide by {num_nodes} nodes")
    block = n // num_nodes

    received = TupleBatch(*(
        None if lane is None else block_all_to_all(lane, num_nodes, block,
                                                   axis_name, mode=mode)
        for lane in batch))

    me = jax.lax.axis_index(axis_name).astype(jnp.uint32)
    salt = _mix32(me + jnp.uint32(seed) * jnp.uint32(0x9E3779B9))
    h = _mix32(jnp.arange(n, dtype=jnp.uint32) ^ salt)
    if received.key_hi is None:
        _, key, rid = sort_kv_unstable(h, received.key, received.rid)
        return TupleBatch(key=key, rid=rid)
    _, key, rid, key_hi = sort_kv_unstable(h, received.key, received.rid,
                                           received.key_hi)
    return TupleBatch(key=key, rid=rid, key_hi=key_hi)
